# Repo-level build/test/bench surface (reference: top-level Makefile +
# hack/make-rules — `make`, `make test`, `make test-integration`,
# `make bench`).  Native components build under native/; everything else
# is Python and needs no build step.

PYTHON ?= python

all: native

native:
	$(MAKE) -C native

# Unit + integration + chaos tiers (tests/ runs on a virtual 8-device
# CPU mesh; see tests/conftest.py).
test: native
	$(PYTHON) -m pytest tests/ -x -q

# Fast smoke: the kernel/parity core only.
test-unit: native
	$(PYTHON) -m pytest tests/test_kernel_smoke.py tests/test_parity.py -x -q

# Static analysis: ktpu-lint (kubernetes_tpu/analysis), the go vet
# analog — AST rules enforcing jit-purity, determinism, twin-coverage,
# f32-reduction discipline, lock discipline, and metrics hygiene.
# Exits non-zero on any finding that is neither suppressed
# (`# ktpu: allow[rule]`) nor in analysis/baseline.json.
lint:
	$(PYTHON) -m kubernetes_tpu.analysis

# The standing verification surface: static analysis first (cheap,
# catches invariant drift before any test runs), then the full tier.
verify: lint test

# Chaos tier: component-crash suite + the fault-injection suite
# (`faults`/`chaos` markers: scrubber, device-path breaker, fault
# points, leader failover) + the `partition` zone-disruption suite
# (eviction storm control under mass node failure) + the `hostpath`
# numpy-twin suite (breaker-open degraded waves, device==host parity)
# + the `racecheck` lock-order suite (go test -race analog, incl. the
# runtime-edges ⊆ static-lock-graph bridge against ktpu-lint)
# + the `storm` overload-control suite (priority-aware load shedding,
# device-dispatch watchdog, clock-driven burst SLO gates)
# + the `shadow` weight hot-swap suite (live WeightProfile swap /
# rollback under a degraded path, candidate==production zero-divergence
# parity)
# + the `meshfault` mesh fault-tolerance suite (device-loss detection,
# quarantine/probe bisection, the 8->4->2->1->heal reform ladder with
# twin-salvage placement parity)
# + the `poison` poison-work isolation suite (input-fault attribution
# vs device faults, wave bisection, pod quarantine/re-probe, the
# kernel's numeric-integrity sentinels)
# + the `autopilot` promotion-pipeline suite (trainer fault points,
# gate rejections, force-promote -> regression-watch auto-rollback,
# candidate-deleted-mid-gating races)
# + the `campaign` chaos-campaign suite (kubernetes_tpu/chaos/:
# cluster-invariant checker mutation tests, fault-point registry drift
# guard, KTPU_FAULTPOINTS parse hardening, a fixed-seed ~8-schedule
# campaign smoke, and the broken-build catch-and-shrink acceptance)
# + the `topology` topology & heterogeneity suite (PodTopologySpread
# kernels incl. breaker-open degraded enforcement, dense
# rack/superpod/accel-gen columns, gang compactness scoring)
# + the `soak` resource-exhaustion suite (HBM budget governor, vocab &
# row compaction, capacity-fault OOM recovery that never convicts a
# device or pod, churn-plateau regression gates).
# Unregistered-marker warnings are ERRORS here so fault-point/marker
# drift is caught at test time.
chaos: native
	$(PYTHON) -m pytest tests/test_chaos.py -q \
		-W error::pytest.PytestUnknownMarkWarning
	$(PYTHON) -m pytest tests/ -q \
		-m "faults or chaos or partition or hostpath or telemetry or racecheck or storm or shadow or meshfault or poison or autopilot or campaign or outage or topology or soak" \
		--continue-on-collection-errors \
		-W error::pytest.PytestUnknownMarkWarning

# Resource-exhaustion soak tier: the `soak`-marked pytest suite
# (compaction + capacity-fault recovery) followed by the bench soak
# harness — multi-day churn compressed onto the virtual clock, gating
# vocab/HBM/RSS/recompile plateaus, placement bit-parity across a
# forced compaction, and a device.oom storm surviving with zero
# breaker trips / mesh reforms / pod convictions.
soak: native
	$(PYTHON) -m pytest tests/ -q -m soak \
		--continue-on-collection-errors \
		-W error::pytest.PytestUnknownMarkWarning
	JAX_PLATFORMS=cpu $(PYTHON) bench.py --workload soak

# Observability tier: the flight-recorder / metrics-exposition suite,
# the numpy-twin parity suite, the decision-observatory /
# cluster-telemetry suite (score decomposition, /debug/score, telemetry
# plane parity), the shadow-scoring observatory suite (live
# WeightProfile hot swap, counterfactual divergence, /debug/shadow),
# and the topology suite (its score planes extend the round ledger's
# keyed-by-plane-name breakdown records — see _record_decisions).
obs: native
	$(PYTHON) -m pytest tests/ -q \
		-m "observability or hostpath or telemetry or shadow or topology" \
		--continue-on-collection-errors \
		-W error::pytest.PytestUnknownMarkWarning

# Multi-device tier: the mesh-sharded-parity suite (`mesh` marker) on 8
# virtual CPU devices, so multi-chip coverage runs in tier-1
# environments without TPUs. tests/conftest.py forces the same layout
# for the whole suite; the explicit env here keeps the target honest if
# that ever changes. Unregistered-marker warnings are errors so the
# marker can't silently drift.
multichip:
	XLA_FLAGS="--xla_force_host_platform_device_count=8" JAX_PLATFORMS=cpu \
		$(PYTHON) -m pytest tests/ -q -m mesh \
		--continue-on-collection-errors \
		-W error::pytest.PytestUnknownMarkWarning

# Full budgeted chaos campaign (test/e2e/chaosmonkey analog): 200
# seeded composed fault schedules replayed against the HollowCluster
# scenario with every cluster invariant checked after each round,
# capped at 10 minutes of wall clock. Violations exit non-zero and
# print a shrunk KTPU_FAULTPOINTS reproducer; re-trigger one with
#   KTPU_FAULTPOINTS='<spec>' $(PYTHON) -m kubernetes_tpu.chaos --repro --seed <seed>
# The fast fixed-seed smoke lives in `make chaos` (campaign marker).
chaos-campaign:
	JAX_PLATFORMS=cpu $(PYTHON) -m kubernetes_tpu.chaos \
		--seed 7 --schedules 200 --budget 600

# The driver's benchmark surface (real TPU when available; CPU otherwise).
bench:
	$(PYTHON) bench.py

# Full benchmark grid (all BASELINE.md configs).
bench-all:
	$(PYTHON) bench.py --suite

clean:
	$(MAKE) -C native clean

.PHONY: all native test test-unit lint verify chaos chaos-campaign obs \
	multichip soak bench bench-all clean
