"""Scheduler throughput benchmark — scheduler_perf density analog.

Reproduces the reference's TestSchedule100Node3KPods shape
(test/integration/scheduler_perf/scheduler_test.go:68 schedulePods:127):
N fake nodes are registered, P pods are created, and we measure the
sustained rate at which the scheduler binds them all.

Baseline: the reference perf harness hard-fails below 30 pods/s and
warns below 100 pods/s on this exact configuration
(scheduler_test.go:35-36); vs_baseline is measured against the 100
pods/s warning level — the throughput the reference considers healthy.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import argparse
import json
import sys
import time


def build_cluster(store, n_nodes):
    from kubernetes_tpu.api import types as api

    for i in range(n_nodes):
        store.create("nodes", api.Node(
            metadata=api.ObjectMeta(name=f"node-{i}", labels={
                "failure-domain.beta.kubernetes.io/zone": f"zone-{i % 3}",
                "kubernetes.io/hostname": f"node-{i}",
            }),
            status=api.NodeStatus(
                allocatable=api.resource_list(cpu="16", memory="32Gi", pods=110,
                                              ephemeral_storage="200Gi"),
                conditions=[api.NodeCondition(api.NODE_READY, api.COND_TRUE)],
            )))


def make_pods(store, n_pods):
    """Density workload: uniform small pods from one RC (the reference's
    testutils.NewCustomCreatePodStrategy default pod)."""
    make_pods_named(store, n_pods, "density-pod")


def make_pods_named(store, n_pods, prefix):
    from kubernetes_tpu.api import types as api

    for i in range(n_pods):
        store.create("pods", api.Pod(
            metadata=api.ObjectMeta(
                name=f"{prefix}-{i}", labels={"type": prefix},
                owner_references=[api.OwnerReference(
                    kind="ReplicationController", name=prefix, uid=f"rc-{prefix}",
                    controller=True)]),
            spec=api.PodSpec(containers=[api.Container(
                resources=api.ResourceRequirements(
                    requests=api.resource_list(cpu="100m", memory="128Mi")))])))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=100)
    ap.add_argument("--pods", type=int, default=3000)
    ap.add_argument("--wave", type=int, default=256)
    ap.add_argument("--cpu", action="store_true", help="force CPU backend")
    args = ap.parse_args()

    if args.cpu:
        import os

        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")

    from kubernetes_tpu.ops.encoding import Caps
    from kubernetes_tpu.runtime.store import ObjectStore
    from kubernetes_tpu.sched.scheduler import Scheduler
    from kubernetes_tpu.state.vocab import bucket_size

    store = ObjectStore()
    caps = Caps(M=bucket_size(args.pods + 64), P=args.wave)
    sched = Scheduler(store, wave_size=args.wave, caps=caps)
    build_cluster(store, args.nodes)

    # warm-up: compile the wave kernel with the same shapes on throwaway
    # pods (first TPU compile is 10-40s and is not a throughput property)
    make_pods_named(store, 32, "warmup")
    sched.schedule_pending()
    for i in range(32):
        store.delete("pods", "default", f"warmup-{i}")

    from kubernetes_tpu.utils import Metrics

    sched.metrics = Metrics()  # drop warm-up/compile observations

    make_pods(store, args.pods)
    t0 = time.time()
    placed = sched.schedule_pending()
    dt = time.time() - t0
    if placed != args.pods:
        print(f"FATAL: placed {placed}/{args.pods}", file=sys.stderr)
        sys.exit(1)
    rate = placed / dt if dt > 0 else 0.0
    p99 = sched.metrics.e2e_scheduling_latency.quantile(0.99)
    print(json.dumps({
        "metric": f"scheduler_density_pods_per_sec_{args.nodes}n_{args.pods}p",
        "value": round(rate, 1),
        "unit": "pods/s",
        "vs_baseline": round(rate / 100.0, 2),
    }))
    print(f"# placed={placed} wall={dt:.2f}s wave={args.wave} "
          f"p99_wave_latency={p99*1e3:.0f}ms", file=sys.stderr)


if __name__ == "__main__":
    main()
