"""Scheduler throughput benchmark — scheduler_perf analog.

Default run reproduces the reference's TestSchedule100Node3KPods shape
(test/integration/scheduler_perf/scheduler_test.go:68 schedulePods:127):
N fake nodes are registered, P pods are created, and we measure the
sustained rate at which the scheduler binds them all. Prints ONE JSON
line: {"metric", "value", "unit", "vs_baseline"}; vs_baseline is
measured against the reference's 100 pods/s "healthy" warning level
(scheduler_test.go:35; hard-fail is 30/s).

--workload selects the BASELINE.md config grid:
  density       uniform small pods (default)
  affinity      node-affinity workload (scheduler_test.go:241-271:
                nodes labeled, pods requiring one of the labels)
  spreading     SelectorSpread via services (priorities workload)
  antiaffinity  required pod anti-affinity on hostname (the quadratic
                scheduler_bench_test.go:56 case)
  mixed         25/25/25/25 mix of the above
  trickle       steady-state regime: pods arrive in sub-wave chunks
                (default 64) and each chunk is drained before the next
                lands — the anti-saturation workload; measures the
                repeated-small-backlog rate, not a big-drain rate
  preempt       preemption drain: saturated nodes + a high-priority
                backlog that only places by evicting. Default flags run
                the batched device what-if (ops/preempt.py) through the
                pipeline; --host-preempt routes round failures through
                the host per-pod what-if instead (the comparison
                baseline), everything else identical, so the pair
                isolates the preemption component. The driver's host
                entry runs --wave 16 — the host path's best measured
                configuration; at the default wave its what-if cascade
                needs many more scheduling cycles and loses by more.
  degraded      breaker-open drain: KTPU_FAULTPOINTS raises at every
                device kernel entry, the circuit breaker trips, and the
                backlog drains through the vectorized numpy host twin
                (ops/hostwave.py) — full host waves + batched host
                preemption, zero device dispatch. Regression-gates the
                old 240x degraded-path cliff.
  paced         non-saturated latency SLO: pods offered at a fixed rate
                (--rate, default 200/s) in chunks; reports the per-pod
                p99 enqueue->bind latency against the reference's 5s
                pod-startup SLO (test/e2e/scalability/density.go:55).
                vs_baseline is SLO headroom (5s / p99).
  partition     zone disruption: one zone fully loaded, then 30% of its
                nodes severed mid-run; measures the nodelifecycle
                detect -> taint -> rate-limited evict -> recreate ->
                re-place loop as pods/s over the severed residents
  storm         trace-replay overload grid (--trace burst|diurnal|
                gangstorm|compound): synthetic arrival traces through
                kubemark's HollowCluster with per-priority-class SLO
                gates (p99 for system/high, zero high-class sheds, no
                permanent starvation) that FAIL the bench on violation
  chaoscampaign fixed-seed chaos campaign (kubernetes_tpu/chaos/): 50
                composed fault schedules replayed against a HollowCluster
                scenario with every cluster invariant checked after each
                round; any violation FAILS the bench and prints its
                shrunk KTPU_FAULTPOINTS reproducer (--seed/--schedules
                override the grid defaults)
  hetero        heterogeneous topology: rack/superpod/accel-gen labeled
                cluster scheduling zone-spread DoNotSchedule pods and
                priority gangs; hard gates on exact spread-skew
                enforcement and on the TopologyCompactness plane beating
                a compactness-zeroed scattered baseline by a rack margin
  soak          resource-exhaustion survival: multi-day node/pod churn
                (fresh hostnames/labels/images every epoch — the vocab
                leak reproducer) compressed onto the virtual clock, with
                housekeeping compactions on cadence and the invariant
                checker armed. Gates: vocab sizes / HBM bytes / host RSS
                / post-warmup recompile count all plateau; a probe
                wave's placements are bit-equal across a mid-run forced
                compaction; an injected device.oom storm ends with zero
                breaker trips, zero mesh reforms, zero pod convictions,
                and every storm pod placed

--suite runs the BASELINE config grid and prints one JSON line each;
a bare `python bench.py` (the driver's command) runs DRIVER_SUITE.
"""

import argparse
import json
import sys
import time


def build_cluster(store, n_nodes, affinity_labels=0):
    from kubernetes_tpu.api import types as api

    for i in range(n_nodes):
        labels = {
            "failure-domain.beta.kubernetes.io/zone": f"zone-{i % 3}",
            "kubernetes.io/hostname": f"node-{i}",
        }
        if affinity_labels:
            # scheduler_test.go:258 — node carries one of K affinity labels
            labels[f"aff-{i % affinity_labels}"] = "yes"
        store.create("nodes", api.Node(
            metadata=api.ObjectMeta(name=f"node-{i}", labels=labels),
            status=api.NodeStatus(
                allocatable=api.resource_list(cpu="16", memory="32Gi", pods=110,
                                              ephemeral_storage="200Gi"),
                conditions=[api.NodeCondition(api.NODE_READY, api.COND_TRUE)],
            )))


def _base_pod(api, name, prefix, labels=None, affinity=None, tolerations=None):
    return api.Pod(
        metadata=api.ObjectMeta(
            name=name, labels=labels or {"type": prefix},
            owner_references=[api.OwnerReference(
                kind="ReplicationController", name=prefix, uid=f"rc-{prefix}",
                controller=True)]),
        spec=api.PodSpec(
            affinity=affinity, tolerations=tolerations or [],
            containers=[api.Container(
                resources=api.ResourceRequirements(
                    requests=api.resource_list(cpu="100m", memory="128Mi")))]))


def make_pods(store, n_pods, workload="density", affinity_labels=10,
              n_services=10):
    """Pod generators for the BASELINE workload grid."""
    from kubernetes_tpu.api import types as api
    from kubernetes_tpu.api.labels import LabelSelector, Requirement

    if workload == "mixed":
        quarter = n_pods // 4
        made = 0
        for wl in ("density", "affinity", "spreading", "antiaffinity"):
            n = quarter if wl != "antiaffinity" else n_pods - 3 * quarter
            make_pods(store, n, wl, affinity_labels, n_services)
            made += n
        return

    if workload == "gang":
        # gang (PodGroup) training-job shape: mixed gang sizes 4/8/16
        # cycling, each gang all-or-nothing at min-available == size —
        # the flagship multi-chip DL-job workload (every gang must fully
        # place or the bench's placed==pods gate fails)
        made = 0
        g = 0
        sizes = (4, 8, 16)
        while made < n_pods:
            size = min(sizes[g % 3], n_pods - made)
            for j in range(size):
                pod = _base_pod(api, f"gang-pod-{made + j}", "gang-pod")
                pod.metadata.annotations = {
                    "pod-group.scheduling.k8s.io/name": f"gang-{g}",
                    "pod-group.scheduling.k8s.io/min-available": str(size),
                }
                store.create("pods", pod)
            made += size
            g += 1
        return

    prefix = f"{workload}-pod"
    if workload == "spreading":
        for s in range(n_services):
            store.create("services", api.Service(
                metadata=api.ObjectMeta(name=f"svc-{s}"),
                spec=api.ServiceSpec(selector={"svc": f"s{s}"})))
    for i in range(n_pods):
        if workload == "density":
            pod = _base_pod(api, f"{prefix}-{i}", prefix)
        elif workload == "affinity":
            # pods requiring one of the K node labels (scheduler_test.go:241)
            aff = api.Affinity(node_affinity=api.NodeAffinity(
                required=api.NodeSelector([api.NodeSelectorTerm(
                    match_expressions=[Requirement(
                        f"aff-{i % affinity_labels}", "In", ("yes",))])])))
            pod = _base_pod(api, f"{prefix}-{i}", prefix, affinity=aff)
        elif workload == "spreading":
            pod = _base_pod(api, f"{prefix}-{i}", prefix,
                            labels={"type": prefix, "svc": f"s{i % n_services}"})
        elif workload == "antiaffinity":
            # required anti-affinity on hostname within small groups —
            # the pod-pod quadratic case (scheduler_bench_test.go:56);
            # group size bounds feasibility on the fixed node count
            group = i % 50
            aff = api.Affinity(pod_anti_affinity=api.PodAntiAffinity(
                required=[api.PodAffinityTerm(
                    label_selector=LabelSelector(
                        match_labels={"anti-group": f"g{group}"}),
                    topology_key="kubernetes.io/hostname")]))
            pod = _base_pod(api, f"{prefix}-{i}", prefix,
                            labels={"type": prefix, "anti-group": f"g{group}"},
                            affinity=aff)
        else:
            raise SystemExit(f"unknown workload {workload!r}")
        store.create("pods", pod)


def _resolve_mesh(spec):
    """--mesh value -> jax.sharding.Mesh or None. "auto" uses every
    visible device (None on a single-device backend — a 1-device mesh
    only adds dispatch overhead); an integer shards over that many
    (clamped to the visible device count with a warning)."""
    if not spec:
        return None
    from kubernetes_tpu.parallel.mesh import mesh_for_devices

    return mesh_for_devices(None if spec == "auto" else int(spec))


# cumulative shadow divergence summary of the measured run (--shadow):
# collected from the scheduler's weight book after the drain, emitted on
# the config's JSON line by emit()
_SHADOW_SUMMARY = None
_MESH_SUMMARY = None


def _arm_device_kill(mesh, ordinal):
    """--kill-device: arm per-device chaos against the mesh's Nth
    device for the measured window (sched/breaker.py lost_device_fault
    via the `device.lost` fault point) — the mid-run device-kill leg of
    the mesh fault plane. No-op without a multi-device mesh."""
    if mesh is None or int(mesh.devices.size) <= 1:
        return
    from kubernetes_tpu.sched.breaker import lost_device_fault
    from kubernetes_tpu.utils import faultpoints

    victim = str(mesh.devices.flat[ordinal % int(mesh.devices.size)])
    faultpoints.activate("device.lost", "corrupt",
                         fn=lost_device_fault(victim))
    print(f"# kill-device: armed device.lost for {victim}",
          file=sys.stderr)


def _collect_mesh(sched):
    """Degradation-ladder summary for the emitted JSON line: how many
    devices the mesh ended on, reforms by direction, quarantined
    devices. None when no mesh fault plane exists. Device count comes
    from the live mesh, not the gauge — run_config swaps in a fresh
    Metrics() after warm-up, which zeroes the gauge until a reform."""
    global _MESH_SUMMARY
    if sched.meshfaults is None:
        return
    _MESH_SUMMARY = {
        "devices": (int(sched.mesh.devices.size)
                    if sched.mesh is not None else 1),
        "reforms_down": int(sched.metrics.mesh_reforms.value(
            direction="down")),
        "reforms_up": int(sched.metrics.mesh_reforms.value(direction="up")),
        "quarantined": sched.meshfaults.quarantined_names(),
    }


def _load_shadow_profiles(store, path):
    """--shadow profile.json: create the WeightProfile objects through
    the object store, exercising the same watch path a live operator
    uses (the scheduler's weightprofiles informer picks them up). Parse
    + construction are the shared sched/weights.py helpers, so this
    path can never drift from --weight-profiles."""
    from kubernetes_tpu.sched.weights import (parse_profiles_file,
                                              profile_objects)

    for obj in profile_objects(parse_profiles_file(path)):
        store.create("weightprofiles", obj)


def _collect_shadow(sched):
    global _SHADOW_SUMMARY
    _SHADOW_SUMMARY = sched.weightbook.summary()


def run_config(nodes, pods, wave, workload="density", warmup=32, mesh=None,
               shadow=None, kill_device=None):
    from kubernetes_tpu.ops.encoding import Caps
    from kubernetes_tpu.runtime.store import ObjectStore
    from kubernetes_tpu.sched.scheduler import Scheduler
    from kubernetes_tpu.state.vocab import bucket_size
    from kubernetes_tpu.utils import Metrics

    from kubernetes_tpu.api import types as api
    from kubernetes_tpu.api.labels import LabelSelector

    store = ObjectStore()
    # pre-size every dim the run will reach: letting M (existing-pod rows)
    # or E (affinity term-table rows) grow mid-run costs a full
    # schedule_wave recompile (~8s on TPU) per power-of-two step — at
    # 2500 anti-affinity pods that's 4 recompiles eating ~90% of the wall
    # clock and looks like a throughput collapse
    has_ipa_load = workload in ("antiaffinity", "mixed")
    # LV: the label-VALUE vocab is dominated by per-node hostname labels,
    # plus workload label values (anti-affinity groups, services, zones);
    # crossing an LV bucket changes num_label_values (a static arg of the
    # wave kernel) and forces a recompile mid-run.
    # E sizing matters doubly: too small recompiles mid-run, but
    # OVER-sizing multiplies the per-wave inter-pod-affinity precompute,
    # which is O(E x N) — mixed has one term per anti-affinity pod, i.e.
    # a quarter of the pods, not all of them.
    n_terms = pods if workload == "antiaffinity" else \
        (pods - 3 * (pods // 4)) if workload == "mixed" else 0
    # gang batches are one GANG wide (4-16 pods), not one wave: P=16
    # keeps every gang size in a single compiled 16-row program instead
    # of padding each gang to the full wave width
    caps = Caps(M=bucket_size(pods + 64),
                P=16 if workload == "gang" else wave,
                E=bucket_size(n_terms + 64) if has_ipa_load else 8,
                LV=bucket_size(nodes + 256, 64))
    sched = Scheduler(store, wave_size=wave, caps=caps, mesh=mesh)
    if shadow:
        _load_shadow_profiles(store, shadow)
    build_cluster(store, nodes,
                  affinity_labels=10 if workload in ("affinity", "mixed") else 0)

    if workload == "gang":
        # gang placement bypasses the device-resident round entirely —
        # warm the joint-assignment kernel (ops/gang.py) per gang-size
        # bucket instead by scheduling + deleting throwaway gangs; their
        # result fetches also absorb the tunneled runtime's one-time
        # degraded-transfer transition outside the measured window
        warm_gangs = []
        for gi, size in enumerate((4, 8, 16)):
            for j in range(size):
                p = _base_pod(api, f"warmup-gang-{gi}-{j}", "warmup")
                p.metadata.annotations = {
                    "pod-group.scheduling.k8s.io/name": f"warm-gang-{gi}",
                    "pod-group.scheduling.k8s.io/min-available": str(size)}
                store.create("pods", p)
                warm_gangs.append(p)
        if sched.schedule_pending() != len(warm_gangs):
            print("FATAL: gang warm-up failed to place", file=sys.stderr)
            sys.exit(1)
        for p in warm_gangs:
            store.delete("pods", "default", p.metadata.name)
        sched.metrics = Metrics()  # drop warm-up/compile observations
        if kill_device is not None:
            _arm_device_kill(mesh, kill_device)
        make_pods(store, pods, workload)
        t0 = time.time()
        placed = sched.schedule_pending()
        dt = time.time() - t0
        p99 = sched.metrics.pod_scheduling_latency.quantile(0.99)
        p99_round = sched.metrics.e2e_scheduling_latency.quantile(0.99)
        _collect_shadow(sched)
        _collect_mesh(sched)
        return placed, dt, p99, p99_round, sched.wave_path()

    # warm-up: compile the resident-pipeline kernel with the same shapes
    # on throwaway pods (first TPU compile is 10-40s and is not a
    # throughput property) — via warm_pipeline, which never fetches
    # results: the first device->host fetch permanently degrades tunneled
    # TPU runtimes' transfer path, so a warm-up that fetched would poison
    # the measured run. The warm batch mirrors the real workload's
    # has_ipa variant: any staged affinity term flips the whole pipeline
    # to the has_ipa=True program.
    from kubernetes_tpu.sched.scheduler import (PIPELINE_MAX_WAVES,
                                                PIPELINE_MAX_WAVES_IPA)

    cap = PIPELINE_MAX_WAVES_IPA if has_ipa_load else PIPELINE_MAX_WAVES
    n_w = min(-(-pods // wave), cap)
    warm_pods = []
    # anti warm pods mirror the real workload's 50 anti-affinity groups:
    # the featurizer's unique-program table (Caps.UI) buckets by the
    # wave's distinct program count, and a warm-up with fewer groups
    # would compile a smaller-UI program than the measured run uses
    # mirror the real per-wave group count: a wave of W anti pods with
    # groups i%50 holds min(W, 50) distinct programs, and a warm-up with
    # fewer would compile a smaller Caps.UI bucket than the measured run
    n_anti_warm = min(50, wave) if has_ipa_load else 0
    warm_n = max(wave - n_anti_warm, 0)
    for i in range(warm_n):
        p = _base_pod(api, f"warmup-{i}", "warmup")
        store.create("pods", p)
        warm_pods.append(p)
    density_warm = list(warm_pods)
    for i in range(n_anti_warm):
        aff = api.Affinity(pod_anti_affinity=api.PodAntiAffinity(
            required=[api.PodAffinityTerm(
                label_selector=LabelSelector(
                    match_labels={"warm-anti": f"w{i % 50}"}),
                topology_key="kubernetes.io/hostname")]))
        p = _base_pod(api, f"warmup-anti-{i}", "warmup",
                      labels={"type": "warmup", "warm-anti": f"w{i % 50}"},
                      affinity=aff)
        store.create("pods", p)
        warm_pods.append(p)
    # the anti-inclusive warm runs FIRST: interning its 50 unique programs
    # grows Caps.UI to the run's final bucket, so the ipa-free variant
    # warmed next compiles with the same UI dim the measured rounds use
    # (warming it before the growth would compile a UI=8 program the run
    # never calls, leaving a 7-20s recompile inside the window)
    sched.warm_pipeline(warm_pods, n_waves=n_w)
    if n_w > 1:
        # tail rounds: stragglers requeued after the big round (exact-
        # recheck losses, post-preemption retries) re-enter the pipeline
        # at the smallest wave bucket — warm it too or a tail of 3 pods
        # pays a full round-program compile inside the measured window
        sched.warm_pipeline(warm_pods, n_waves=1)
    if workload == "mixed":
        # mixed rounds before the anti-affinity block run the ipa-free
        # program variant at the ipa-capped bucket — warm it too
        sched.warm_pipeline(density_warm, n_waves=n_w)
        if n_w > 1:
            sched.warm_pipeline(density_warm, n_waves=1)
    for p in warm_pods:
        store.delete("pods", "default", p.metadata.name)

    sched.metrics = Metrics()  # drop warm-up/compile observations
    if kill_device is not None:
        _arm_device_kill(mesh, kill_device)
    make_pods(store, pods, workload)
    t0 = time.time()
    placed = sched.schedule_pending()
    dt = time.time() - t0
    # per-POD p99 (first-enqueue -> assume+bind-dispatch) is backlog-
    # dominated at saturation-drain scale: the last wave waits the whole
    # drain. Report the per-ROUND p99 beside it so instrument effects and
    # backlog effects stay separable.
    p99 = sched.metrics.pod_scheduling_latency.quantile(0.99)
    p99_round = sched.metrics.e2e_scheduling_latency.quantile(0.99)
    _collect_shadow(sched)
    _collect_mesh(sched)
    return placed, dt, p99, p99_round, sched.wave_path()


def _warmed_scheduler(nodes, wave, extra_pods=0, mesh=None):
    """Cluster + scheduler with the 1-wave round program compiled and the
    degraded-transfer-mode transition absorbed — shared setup for the
    small-backlog configs (trickle/paced), whose rounds never exceed one
    wave per chunk."""
    from kubernetes_tpu.api import types as api
    from kubernetes_tpu.ops.encoding import Caps
    from kubernetes_tpu.runtime.store import ObjectStore
    from kubernetes_tpu.sched.scheduler import Scheduler
    from kubernetes_tpu.state.vocab import bucket_size
    from kubernetes_tpu.utils import Metrics

    store = ObjectStore()
    caps = Caps(M=bucket_size(extra_pods + 64), P=wave,
                LV=bucket_size(nodes + 256, 64))
    sched = Scheduler(store, wave_size=wave, caps=caps, mesh=mesh)
    build_cluster(store, nodes)
    warm = []
    for i in range(min(wave, 64)):
        p = _base_pod(api, f"warmup-{i}", "warmup")
        store.create("pods", p)
        warm.append(p)
    sched.warm_pipeline(warm, n_waves=1)
    for p in warm:
        store.delete("pods", "default", p.metadata.name)
    sched.metrics = Metrics()
    return store, sched, api


def run_trickle_config(nodes, pods, wave, chunk=64, mesh=None):
    """Steady-state regime (round-4 verdict weak #1): the backlog is
    never more than one sub-wave chunk — the scheduler sees `chunk`
    pods, drains them, then the next chunk lands. Total wall time spans
    every drain, so per-round overhead (program dispatch + the single
    end-of-round fetch) is what this measures. The reference's analog is
    its one-pod-at-a-time loop at low queue depth
    (pkg/scheduler/scheduler.go:438)."""
    store, sched, api = _warmed_scheduler(nodes, wave, extra_pods=pods,
                                          mesh=mesh)
    made = 0
    t0 = time.time()
    placed = 0
    while made < pods:
        n = min(chunk, pods - made)
        for i in range(n):
            pod = _base_pod(api, f"trickle-pod-{made + i}", "trickle-pod")
            store.create("pods", pod)
        made += n
        placed += sched.schedule_pending()
    dt = time.time() - t0
    p99 = sched.metrics.pod_scheduling_latency.quantile(0.99)
    p99_round = sched.metrics.e2e_scheduling_latency.quantile(0.99)
    return placed, dt, p99, p99_round, sched.wave_path()


def run_paced_config(nodes, pods, wave, rate=200.0, chunk=100, mesh=None):
    """Non-saturated latency SLO (round-4 verdict item 8): offer pods at
    a fixed rate and measure per-pod p99 enqueue->bind latency. The
    reference's load test paces at 10 pods/s (test/e2e/scalability/
    load.go:124-137) with a 5s pod-startup SLO (density.go:55); this
    runs >=10x that offered load and reports the p99 against the 5s
    SLO. Falling behind the offered rate is *measured, not masked*: a
    chunk that drains slower than its interval delays every later
    chunk's enqueue->bind clock."""
    store, sched, api = _warmed_scheduler(nodes, wave, extra_pods=pods,
                                          mesh=mesh)
    interval = chunk / rate
    made = 0
    placed = 0
    t0 = time.time()
    next_tick = t0
    while made < pods:
        now = time.time()
        if now < next_tick:
            time.sleep(next_tick - now)
        n = min(chunk, pods - made)
        for i in range(n):
            pod = _base_pod(api, f"paced-pod-{made + i}", "paced-pod")
            store.create("pods", pod)
        made += n
        next_tick += interval
        placed += sched.schedule_pending()
    stalled = 0
    while placed < pods:
        time.sleep(0.002)
        n = sched.schedule_pending()
        placed += n
        # an unplaceable remainder makes zero progress forever; bail to
        # the placed!=pods FATAL instead of spinning
        stalled = stalled + 1 if n == 0 else 0
        if stalled > 2000:
            break
    dt = time.time() - t0
    p99 = sched.metrics.pod_scheduling_latency.quantile(0.99)
    offered = pods / dt
    return placed, dt, p99, offered, sched.wave_path()


def run_autoscale_config(nodes, pods, wave, join_latency=0.25, mesh=None):
    """Elastic-cluster drain (the cluster-autoscaler workload): start
    UNDER-provisioned — `nodes` 16-cpu machines against `pods` one-core
    pods — so full placement requires repeated scale-up rounds: the
    autoscaler's on-device what-if (ops/simulate.py) picks a NodeGroup
    expansion, booted instances join after a simulated `join_latency`,
    and the flushed backlog places on the new capacity. Reported pods/s
    spans the WHOLE loop including every join latency. Preemption is
    disabled: elasticity, not eviction, is the remedy being measured."""
    import time as _t

    from kubernetes_tpu.api import types as api
    from kubernetes_tpu.cloud.provider import FakeCloud, node_from_template
    from kubernetes_tpu.controllers.clusterautoscaler import ClusterAutoscaler
    from kubernetes_tpu.ops.encoding import Caps
    from kubernetes_tpu.runtime.store import ObjectStore
    from kubernetes_tpu.sched.scheduler import Scheduler
    from kubernetes_tpu.state.vocab import bucket_size
    from kubernetes_tpu.utils import Metrics
    from kubernetes_tpu.utils.backoff import PodBackoff

    store = ObjectStore()
    # the drain ends near pods/16 extra standard nodes; pre-size N/LV to
    # the final fleet so mid-run growth never recompiles the round
    max_extra = -(-pods // 12)
    caps = Caps(N=bucket_size(nodes + max_extra + 96),
                M=bucket_size(pods + 64), P=wave,
                LV=bucket_size(nodes + max_extra + 256, 64))
    sched = Scheduler(store, wave_size=wave, caps=caps, mesh=mesh)
    sched.profile.disable_preemption = True
    # snappy retry after node joins (the reference 1s-doubling parking
    # would dominate a workload that is ALL failure->retry cycles)
    sched.backoff = PodBackoff(initial=0.01, maximum=0.1)
    cloud = FakeCloud()
    joins = []  # (ready_at, node): instances registering after latency
    cloud.joiner = lambda g, name: joins.append(
        (_t.time() + join_latency, node_from_template(g, name)))

    def tmpl(name, cpu, mem):
        return api.Node(
            metadata=api.ObjectMeta(name=name),
            status=api.NodeStatus(allocatable=api.resource_list(
                cpu=cpu, memory=mem, pods=110, ephemeral_storage="200Gi")))

    cloud.add_node_group("standard", tmpl("t-standard", "16", "32Gi"),
                         max_size=nodes + max_extra, price=1.0)
    cloud.add_node_group("large", tmpl("t-large", "32", "64Gi"),
                         max_size=max_extra, price=2.1)
    ca = ClusterAutoscaler(store, cloud, sched, scale_up_cooldown=0.0,
                           max_virtual_per_group=32, max_pods_per_pass=wave)
    # the initial (under-sized) fleet joins instantly
    cloud.increase_size("standard", nodes)
    for _, node in joins:
        store.create("nodes", node)
    joins.clear()

    # warm outside the window: the round program per wave bucket, and
    # the what-if program via pods NO template can host (the simulation
    # runs full-shape but buys nothing)
    warm = []
    for i in range(wave):
        p = _base_pod(api, f"warmup-{i}", "warmup")
        store.create("pods", p)
        warm.append(p)
    sched.warm_pipeline(warm, n_waves=min(-(-pods // wave), 128))
    sched.warm_pipeline(warm, n_waves=1)
    for i in range(wave):
        p = _base_pod(api, f"warmup-sim-{i}", "warmup-sim")
        p.spec.containers[0].resources.requests["cpu"] = 500_000
        store.create("pods", p)
        warm.append(p)
    sched.schedule_pending()  # parks the oversized pods unschedulable
    # warm pass must neither buy nor REMOVE nodes (the barely-loaded
    # warm fleet would otherwise scale down): no node is ever below a
    # negative utilization threshold
    threshold, ca.utilization_threshold = ca.utilization_threshold, -1.0
    ca.run_once()  # compiles the scale-up what-if; resizes nothing
    ca.utilization_threshold = threshold
    assert ca.last_scale_up is None, "warm-up must not buy nodes"
    assert ca.last_scale_down is None, "warm-up must not remove nodes"
    for p in warm:
        store.delete("pods", "default", p.metadata.name)
    sched.metrics = Metrics()
    ca.metrics = sched.metrics

    for i in range(pods):
        p = _base_pod(api, f"scale-pod-{i}", "scale-pod")
        p.spec.containers[0].resources.requests["cpu"] = 1000
        store.create("pods", p)
    t0 = _t.time()
    placed = 0
    stalled = 0
    while placed < pods and stalled < 200:
        n = sched.schedule_pending()
        placed += n
        if placed >= pods:
            break
        now = _t.time()
        due = [j for j in joins if j[0] <= now]
        if due:
            joins[:] = [j for j in joins if j[0] > now]
            for _, node in due:
                store.create("nodes", node)
            stalled = 0
            continue
        if joins:
            # nothing to do until the booted instances register — the
            # join latency is PART of the measured wall clock
            _t.sleep(max(min(r for r, _ in joins) - now, 0.0) + 1e-3)
            continue
        r = ca.run_once()
        stalled = 0 if (n or r["scaled_up"] or r["scaled_down"]) \
            else stalled + 1
        if not r["scaled_up"]:
            _t.sleep(0.005)  # let pod backoffs expire
    dt = _t.time() - t0
    p99 = sched.metrics.pod_scheduling_latency.quantile(0.99)
    p99_round = sched.metrics.e2e_scheduling_latency.quantile(0.99)
    print(f"# autoscale: final_nodes={store.count('nodes')} "
          f"nodes_added={int(sched.metrics.autoscaler_scale_ups.value)} "
          f"join_latency={join_latency}s", file=sys.stderr)
    return placed, dt, p99, p99_round, sched.wave_path()


def run_partition_config(nodes, pods, wave, sever_fraction=0.3, mesh=None):
    """Zone-disruption re-placement drain (the eviction storm-control
    workload): a single-zone cluster fully loaded with `pods`, then 30%
    of the zone's nodes are severed mid-run (heartbeats stop). The
    nodelifecycle controller detects staleness, taints NoExecute, and
    drains evictions through the zone's token bucket (a high configured
    rate — the machinery, not the throttle, is what's measured); a
    ReplicaSet stand-in recreates each evicted pod and the scheduler
    re-places it on surviving capacity. Reported pods/s spans the whole
    detect -> evict -> recreate -> re-place loop. 30% severed keeps the
    zone below the 55% unhealthy threshold, so the zone stays Normal
    and drains at the primary rate — the storm-control suspension paths
    are covered by tests/test_partition.py, not timed here."""
    import time as _t

    from kubernetes_tpu.api import types as api
    from kubernetes_tpu.controllers.nodelifecycle import (
        HEARTBEAT_ANNOTATION, NodeLifecycleController, zone_display)
    from kubernetes_tpu.ops.encoding import Caps
    from kubernetes_tpu.runtime.store import ObjectStore
    from kubernetes_tpu.sched.scheduler import Scheduler
    from kubernetes_tpu.state.vocab import bucket_size
    from kubernetes_tpu.utils import Metrics
    from kubernetes_tpu.utils.backoff import PodBackoff

    store = ObjectStore()
    vclock = [1000.0]
    caps = Caps(N=bucket_size(nodes + 8), M=bucket_size(2 * pods + 64),
                P=wave, LV=bucket_size(nodes + 256, 64))
    sched = Scheduler(store, wave_size=wave, caps=caps, mesh=mesh)
    sched.backoff = PodBackoff(initial=0.01, maximum=0.1)
    for i in range(nodes):
        store.create("nodes", api.Node(
            metadata=api.ObjectMeta(
                name=f"node-{i}",
                labels={api.LABEL_ZONE: "zone-0",
                        api.LABEL_HOSTNAME: f"node-{i}"},
                annotations={HEARTBEAT_ANNOTATION: str(vclock[0])}),
            status=api.NodeStatus(
                allocatable=api.resource_list(cpu="16", memory="32Gi",
                                              pods=110,
                                              ephemeral_storage="200Gi"),
                conditions=[api.NodeCondition(api.NODE_READY,
                                              api.COND_TRUE)])))
    ctrl = NodeLifecycleController(
        store, clock=lambda: vclock[0], grace_period=20.0,
        eviction_rate_qps=500.0, eviction_burst=float(max(wave, 64)))
    for i in range(pods):
        store.create("pods", _base_pod(api, f"load-{i}", "load"))
    placed = sched.schedule_pending()
    stalled = 0
    while placed < pods and stalled < 2000:
        n = sched.schedule_pending()
        placed += n
        stalled = stalled + 1 if n == 0 else 0
    assert placed == pods, f"pre-sever fill placed {placed}/{pods}"
    ctrl.monitor()  # zone observed Normal before the cut

    severed = {f"node-{i}" for i in range(int(nodes * sever_fraction))}
    alive = [f"node-{i}" for i in range(nodes)
             if f"node-{i}" not in severed]
    target = sum(1 for p in store.list("pods")
                 if p.spec.node_name in severed)
    sched.metrics = Metrics()
    t0 = _t.time()
    vclock[0] += 30.0  # past grace: the severed 30% are now stale
    replaced = 0
    evicted_seen = ctrl.evictions
    seq = 0
    stalled = 0
    while replaced < target and stalled < 2000:
        for name in alive:  # surviving kubelets keep heartbeating
            node = store.get("nodes", "default", name)
            node.metadata.annotations[HEARTBEAT_ANNOTATION] = str(vclock[0])
            store.update("nodes", node)
        ctrl.monitor()
        newly = ctrl.evictions - evicted_seen
        evicted_seen = ctrl.evictions
        for _ in range(newly):  # the ReplicaSet stand-in recreates
            store.create("pods", _base_pod(api, f"re-{seq}", "re"))
            seq += 1
        n = sched.schedule_pending()
        replaced += n
        stalled = stalled + 1 if (n == 0 and newly == 0) else 0
        vclock[0] += 1.0  # drives grace/toleration clocks + the bucket
    dt = _t.time() - t0
    p99 = sched.metrics.pod_scheduling_latency.quantile(0.99)
    p99_round = sched.metrics.e2e_scheduling_latency.quantile(0.99)
    print(f"# partition: severed={len(severed)}/{nodes} nodes "
          f"evicted={ctrl.evictions} replaced={replaced}/{target} "
          f"zone_states="
          f"{ {zone_display(z): s for z, s in ctrl.zone_states.items()} }",
          file=sys.stderr)
    return replaced, dt, p99, p99_round, sched.wave_path(), target


def run_degraded_config(nodes, pods, wave, mesh=None):
    """Breaker-open degraded drain (the ISSUE 7 regression gate):
    KTPU_FAULTPOINTS arms a raise at every device kernel entry — exactly
    how an operator would chaos-test a live binary — so the circuit
    breaker trips within its threshold and the whole backlog drains
    through the vectorized numpy host twin (ops/hostwave.py): full host
    waves, batched host preemption, no device dispatch. Before the twin
    this path ran the per-pod golden loop at ~3 orders of magnitude
    under the device rate; the SUITE entry keeps it from regressing."""
    import os

    # the env var is the operator surface being exercised (and covers a
    # not-yet-imported faultpoints module); the explicit activate calls
    # cover the already-imported case through the public API
    os.environ["KTPU_FAULTPOINTS"] = (
        "kernel.round=raise,kernel.wave=raise,kernel.gang=raise")
    from kubernetes_tpu.utils import faultpoints

    for point in ("kernel.round", "kernel.wave", "kernel.gang"):
        faultpoints.activate(point, "raise")

    from kubernetes_tpu.ops.encoding import Caps
    from kubernetes_tpu.runtime.store import ObjectStore
    from kubernetes_tpu.sched.scheduler import Scheduler
    from kubernetes_tpu.state.vocab import bucket_size

    store = ObjectStore()
    caps = Caps(M=bucket_size(pods + 64), P=wave,
                LV=bucket_size(nodes + 256, 64))
    # no warm-up: device attempts die at the fault point before any
    # compile, and the host twin has nothing to compile
    sched = Scheduler(store, wave_size=wave, caps=caps, mesh=mesh)
    build_cluster(store, nodes)
    make_pods(store, pods, "density")
    t0 = time.time()
    placed = sched.schedule_pending()
    stalled = 0
    while placed < pods:
        time.sleep(0.002)
        n = sched.schedule_pending()
        placed += n
        stalled = stalled + 1 if n == 0 else 0
        if stalled > 2000:
            break
    dt = time.time() - t0
    from kubernetes_tpu.sched.breaker import OPEN

    state = sched.breaker.state
    print(f"# degraded: breaker={state} trips={sched.breaker.trips} "
          f"host_waves={int(sched.metrics.waves_total.value(path='host'))}",
          file=sys.stderr)
    if state != OPEN and sched.breaker.trips == 0:
        print("FATAL: degraded: breaker never tripped — the run measured "
              "the device path", file=sys.stderr)
        sys.exit(1)
    p99 = sched.metrics.pod_scheduling_latency.quantile(0.99)
    p99_round = sched.metrics.e2e_scheduling_latency.quantile(0.99)
    return placed, dt, p99, p99_round, sched.wave_path()


def run_preempt_config(nodes, pods, wave, device=True, mesh=None):
    """Preemption-heavy drain: every node saturated by low-priority
    hogs, then a high-priority backlog that can only place by evicting
    them. device=False routes the batched what-if through the
    vectorized numpy twin (ops/hostwave.py preemption_stats_host)
    instead of the device kernel — everything else identical, so the
    pair isolates the preemption backend. (Before ISSUE 7 this flag
    meant the per-pod host what-if cascade: 0.8 pods/s at 50n/100p,
    the BENCH_r05 cliff.)"""
    import jax
    import jax.numpy as jnp

    from kubernetes_tpu.api import types as api
    from kubernetes_tpu.ops.encoding import Caps
    from kubernetes_tpu.runtime.store import ObjectStore
    from kubernetes_tpu.sched.scheduler import (PREEMPT_LEVELS, Scheduler)
    from kubernetes_tpu.state.vocab import bucket_size
    from kubernetes_tpu.utils import Metrics
    from kubernetes_tpu.utils.backoff import PodBackoff

    store = ObjectStore()
    caps = Caps(M=bucket_size(2 * nodes + pods + 64), P=wave,
                LV=bucket_size(nodes + 256, 64))
    sched = Scheduler(store, wave_size=wave, caps=caps, mesh=mesh)
    # the ONLY knob that differs between the two measured paths:
    # device=False sends round failures through the host per-pod what-if
    # (sched/preemption.py preempt) instead of the batched device stats
    # (ops/preempt.py); placement stays pipelined in both so the
    # comparison isolates the preemption component
    sched.device_preemption = device
    # a near-zero initial backoff so the measurement is work, not the
    # reference's 1s parking window (identical for both paths)
    sched.backoff = PodBackoff(initial=0.001)
    build_cluster(store, nodes)
    # two hogs fill each node's 16 cpu
    for i in range(2 * nodes):
        p = _base_pod(api, f"hog-{i}", "hog")
        p.spec.containers[0].resources.requests["cpu"] = 8000
        p.spec.priority = 1
        store.create("pods", p)
    placed = sched.schedule_pending()
    assert placed == 2 * nodes, f"fill placed {placed}"
    # warm the round + preemption programs outside the window
    warm = []
    for i in range(wave):
        p = _base_pod(api, f"warmup-{i}", "warmup")
        store.create("pods", p)
        warm.append(p)
    sched.warm_pipeline(warm, n_waves=min(-(-pods // wave), 128))
    from kubernetes_tpu.ops.preempt import preemption_stats

    pb = sched.featurizer.featurize(warm[:1])
    nt, pm, tt = sched.snapshot.to_device()
    out = preemption_stats(nt, pm, pb,
                           jnp.asarray([2] * PREEMPT_LEVELS, jnp.int32),
                           num_levels=PREEMPT_LEVELS)
    jax.block_until_ready(out)
    for p in warm:
        store.delete("pods", "default", p.metadata.name)

    sched.metrics = Metrics()
    for i in range(pods):
        p = _base_pod(api, f"vip-{i}", "vip")
        p.spec.containers[0].resources.requests["cpu"] = 8000
        p.spec.priority = 100
        store.create("pods", p)
    t0 = time.time()
    done = sched.schedule_pending()
    stalled = 0
    while done < pods:
        time.sleep(0.002)
        n = sched.schedule_pending()
        done += n
        # an unplaceable remainder makes zero progress forever; bail to
        # the placed!=pods FATAL instead of hanging the driver suite
        stalled = stalled + 1 if n == 0 else 0
        if stalled > 2000:
            break
    dt = time.time() - t0
    evicted = int(sched.metrics.pod_preemption_victims.value)
    p99 = sched.metrics.pod_scheduling_latency.quantile(0.99)
    p99_round = sched.metrics.e2e_scheduling_latency.quantile(0.99)
    print(f"# preempt[{'device' if device else 'host'}]: placed={done} "
          f"evicted={evicted} pipeline={sched.pipeline_preemptions} "
          f"preempt_eval={sched.metrics.preemption_evaluation.sum:.2f}s",
          file=sys.stderr)
    return done, dt, p99, p99_round, sched.wave_path()


# -- trace-replay storm harness (--trace) ------------------------------------
#
# Synthetic arrival traces replayed through kubemark's HollowCluster
# against per-priority-class SLO gates that FAIL the bench on violation
# — "handles as many scenarios as you can imagine" as a regression
# grid, not a claim. Each trace is a list of ticks; a tick arrives
# pods by class, optionally fires chaos, then the scheduler gets ONE
# wave (run_once) — so sustained capacity is wave pods/tick and a
# "5x burst" genuinely outruns the scheduler instead of being absorbed
# by an unbounded drain. Gates: p99 enqueue->bind latency per class,
# shed-rate ceiling ZERO for system/high classes, and full eventual
# placement for every class (shedding must delay low pods, never
# starve them).

# The class->priority map and the protected-class p99 gates are shared
# with the autopilot's promotion CI (autopilot/replay.py holds the
# canonical copies) so the bench gates and the gates a candidate weight
# profile must clear before going live cannot drift apart. Rationale:
# normal/low sit below the shed threshold, shed legitimately under
# storms, and are gated on eventual placement instead (their p99 is
# still reported). The floor of high-class latency is one wave's wall
# time (~1.3s on an otherwise-idle CPU backend at the suite shape, ~3s
# under CPU contention) — the gates carry that headroom while still
# failing loudly on starvation, which shows as tens-of-seconds p99
# (low's burst p99 is ~80-120s while it sheds).
from kubernetes_tpu.autopilot.replay import (STORM_PRIORITY,  # noqa: E402
                                             STORM_SLO_P99)


def _storm_traces(wave):
    """Trace grid keyed by name. Each tick: {cls: count} arrivals plus
    optional control keys ("sever"/"heal" for the compound trace).
    Sustained capacity S == one wave per tick."""
    S = wave
    sustained = {"low": S // 2, "normal": S // 8, "high": 8, "system": 2}
    burst = {"low": 5 * S, "high": 8, "system": 2}
    traces = {}
    # burst storm: 10 sustained ticks, then 10 ticks at 5x capacity of
    # pure low-class arrivals with the high/system trickle continuing
    traces["burst"] = [dict(sustained)] * 10 + [dict(burst)] * 10
    # diurnal ramp: arrival rate sweeps 0.2x -> 1.5x capacity and back
    # (sin^2 profile over 40 ticks) — transient overload at the peaks
    import math

    traces["diurnal"] = [
        {"low": int(S * (0.2 + 1.3 * math.sin(math.pi * t / 40) ** 2)),
         "high": 8, "system": 2}
        for t in range(40)]
    # gang+preempt interleave: low-priority gangs of 8 (4-core members,
    # 4 per node) fill the cpu-bound cluster, then high-priority 4-core
    # preemptors arrive — each must evict a gang member, which breaks
    # the whole gang (min-available == size) and frees its 8 slots.
    # Gang atomicity and preemption under storm, not raw overload: at
    # 100 nodes demand is 48x8 + 32 = 416 pods against 400 slots, so
    # the run only converges if preemption actually evicts gangs whole
    traces["gangstorm"] = [{"gang": 4}] * 12 + [{"high": 4}] * 8
    # partition-during-storm compound chaos: the 5x burst PLUS 30% of
    # the HollowCluster severed mid-storm (heartbeats stop ->
    # nodelifecycle taints+evicts -> evicted pods recreated and
    # re-placed on survivors), healed before the drain
    traces["compound"] = (
        [dict(sustained)] * 5
        + [dict(burst)] * 3
        + [dict(burst, sever=0.3)]
        + [dict(burst)] * 6
        + [dict(sustained, heal=True)] * 2)
    return traces


def _storm_pod(api, name, cls):
    p = _base_pod(api, name, f"storm-{cls}")
    p.spec.priority = STORM_PRIORITY[cls]
    return p


def _p99(samples):
    if not samples:
        return 0.0
    s = sorted(samples)
    return s[min(int(len(s) * 0.99), len(s) - 1)]


def run_storm_config(nodes, wave, trace="burst", mesh=None,
                     kill_device=None, poison_frac=0.0):
    """Replay one synthetic arrival trace through a HollowCluster with
    the overload-control plane armed (shed watermark 2 waves, 1s shed
    aging) and gate the run on per-class SLOs. Returns the gate report;
    violations FAIL the bench.

    poison_frac > 0 is the `poisonstorm` leg: that fraction of the
    low-class arrivals carry a genuinely malformed spec (NaN cpu
    request — the input-fault class the poison-isolation plane exists
    for). The SLO gates for the CLEAN classes are IDENTICAL to the
    plain storm's, and three poison gates are added: every poison pod
    convicted (never placed), ZERO device-path breaker trips, and zero
    mesh reforms — bad work must cost the bad pods, not the device
    plane or the protected classes."""
    import time as _t

    from kubernetes_tpu.api import types as api
    from kubernetes_tpu.controllers.nodelifecycle import \
        NodeLifecycleController
    from kubernetes_tpu.kubemark.hollow import HollowCluster
    from kubernetes_tpu.ops.encoding import Caps
    from kubernetes_tpu.runtime.store import ObjectStore
    from kubernetes_tpu.sched.scheduler import Scheduler
    from kubernetes_tpu.state.vocab import bucket_size
    from kubernetes_tpu.utils import Metrics
    from kubernetes_tpu.utils.backoff import PodBackoff

    ticks = _storm_traces(wave)[trace]
    gang_trace = trace == "gangstorm"
    compound = trace == "compound"
    total_arrivals = sum(n for tk in ticks for c, n in tk.items()
                         if c in STORM_PRIORITY) \
        + sum(8 * tk.get("gang", 0) for tk in ticks)
    store = ObjectStore()
    caps = Caps(M=bucket_size(2 * total_arrivals + 64),
                P=16 if gang_trace else wave,
                LV=bucket_size(nodes + 256, 64))
    sched = Scheduler(store, wave_size=wave, caps=caps, mesh=mesh,
                      # the overload plane under test: watermark 2
                      # waves, low-class sheds age back after 1s
                      shed_watermark=2 * wave, shed_age_s=1.0)
    sched.backoff = PodBackoff(initial=0.01, maximum=0.1)

    # node plane: kubemark hollow nodes on a virtual clock (the
    # compound trace partitions a fraction of them mid-storm and the
    # nodelifecycle controller drives eviction off their stale
    # heartbeats); pod-slot capacity bounds the storm, cpu bounds the
    # gang trace (4-core members, 4 per node)
    vclock = [1000.0]
    cluster = HollowCluster(store, nodes, clock=lambda: vclock[0])
    for n in cluster.nodes:
        n.kubelet.register_node()
    ctrl = None
    if compound:
        ctrl = NodeLifecycleController(
            store, clock=lambda: vclock[0], grace_period=20.0,
            eviction_rate_qps=500.0, eviction_burst=float(max(wave, 64)))
        ctrl.monitor()

    # warm every program the replay dispatches OUTSIDE the gated
    # window: the per-wave kernel (run_once path), the 1-wave round
    # program, and for the gang trace the joint-assignment + batched
    # preemption programs — a first-shape compile inside the window
    # would bust the high-class p99 gate with compile time, which is
    # not a storm property
    warm = []
    for i in range(min(wave, 64)):
        p = _base_pod(api, f"warmup-{i}", "warmup")
        store.create("pods", p)
        warm.append(p)
    sched.warm_pipeline(warm, n_waves=1)
    while sched.run_once(timeout=0.0):
        pass
    if gang_trace:
        import jax
        import jax.numpy as jnp

        from kubernetes_tpu.ops.preempt import preemption_stats
        from kubernetes_tpu.sched.scheduler import PREEMPT_LEVELS

        for j in range(8):
            p = _base_pod(api, f"warmup-gang-{j}", "warmup")
            p.metadata.annotations = {
                "pod-group.scheduling.k8s.io/name": "warm-gang",
                "pod-group.scheduling.k8s.io/min-available": "8"}
            store.create("pods", p)
            warm.append(p)
        sched.schedule_pending()
        pb = sched.featurizer.featurize(warm[:1])
        nt, pm, tt = sched.snapshot.to_device()
        out = preemption_stats(
            nt, pm, pb, jnp.asarray([2] * PREEMPT_LEVELS, jnp.int32),
            num_levels=PREEMPT_LEVELS)
        jax.block_until_ready(out)
    for p in warm:
        try:
            store.delete("pods", "default", p.metadata.name)
        except KeyError:
            pass
    sched.metrics = Metrics()  # drop warm-up observations (the queue's
    # on_shed hook reads sched.metrics at call time — no rebind needed)
    # continuously-checked invariants ride every storm leg: strict=False
    # records violations without aborting mid-trace, and the gate below
    # fails the bench if any round ever broke one
    from kubernetes_tpu.chaos.invariants import InvariantChecker
    checker = InvariantChecker(metrics=sched.metrics, strict=False)
    sched.invariants = checker
    if kill_device is not None:
        # mesh fault leg: the first storm dispatch loses a device — the
        # tick salvages through the twin, the mesh reforms down a rung,
        # and the SLO gates must still hold on the smaller mesh
        _arm_device_kill(mesh, kill_device)

    created = {}  # uid -> (cls, wall time created)
    latency = {c: [] for c in STORM_PRIORITY}
    bound_seen = {}
    severed = []
    seq = [0]
    # poisonstorm bookkeeping: poison pods are tracked SEPARATELY from
    # `created` — they can never place, so the starvation/drain gates
    # must not wait on them; their own gate is conviction
    poison_uids = {}
    low_seen = [0]
    poison_every = int(round(1.0 / poison_frac)) if poison_frac > 0 else 0

    def _arrive(cls, count):
        for _ in range(count):
            p = _storm_pod(api, f"{cls}-{seq[0]}", cls)
            if gang_trace:
                # cpu-bound preemptors: 4 cores each, 4 per node — a
                # high single can only place by evicting gang members
                p.spec.containers[0].resources.requests["cpu"] = 4000
            seq[0] += 1
            poisoned = False
            if poison_every and cls == "low":
                low_seen[0] += 1
                if low_seen[0] % poison_every == 0:
                    # a genuinely malformed spec (the canonical-map
                    # constructors reject NaN, so this models a
                    # corrupted object reaching the scheduler)
                    p.spec.containers[0].resources.requests["cpu"] = \
                        float("nan")
                    poisoned = True
            store.create("pods", p)
            if poisoned:
                poison_uids[p.uid] = None
            else:
                created[p.uid] = (cls, _t.time())

    def _account():
        now = _t.time()
        for p in store.list("pods"):
            if (p.uid in created and p.uid not in bound_seen
                    and p.spec.node_name):
                cls, t0 = created[p.uid]
                bound_seen[p.uid] = True
                latency[cls].append(now - t0)

    evicted_seen = 0
    t0 = _t.time()
    for tick in ticks:
        vclock[0] += 5.0  # drives heartbeat staleness + grace clocks
        if tick.get("sever"):
            severed = cluster.partition(fraction=tick["sever"])
        if tick.get("heal"):
            cluster.heal(severed)
        if compound:
            for n in cluster.nodes:  # live kubelets keep heartbeating
                if not n.kubelet.partitioned:
                    n.kubelet.heartbeat()
            ctrl.monitor()
            newly = ctrl.evictions - evicted_seen
            evicted_seen = ctrl.evictions
            for _ in range(newly):
                # the ReplicaSet stand-in: an evicted storm pod comes
                # back as a fresh low-class pod and re-places
                _arrive("low", 1)
        for cls in ("system", "high", "normal", "low"):
            if tick.get(cls):
                _arrive(cls, tick[cls])
        for _ in range(tick.get("gang", 0)):
            gname = f"gang-{seq[0]}"
            seq[0] += 1
            for j in range(8):
                p = _storm_pod(api, f"{gname}-m{j}", "low")
                p.spec.containers[0].resources.requests["cpu"] = 4000
                p.metadata.annotations = {
                    "pod-group.scheduling.k8s.io/name": gname,
                    "pod-group.scheduling.k8s.io/min-available": "8"}
                store.create("pods", p)
                created[p.uid] = ("low", _t.time())
        if gang_trace:
            # the interleave chaos (atomicity + preemption), not raw
            # overload, is this trace's subject: full pipeline drain
            sched.schedule_pending()
        else:
            sched.run_once(timeout=0.0)  # ONE wave: capacity == wave/tick
        _account()
    # drain: the storm is over; every survivor (including aged-back
    # shed pods) must eventually place — the no-permanent-starvation
    # gate. Wall-bounded so a wedge fails loudly instead of hanging.
    stalled = 0
    while stalled < 2000:
        if compound:
            vclock[0] += 5.0
            for n in cluster.nodes:
                if not n.kubelet.partitioned:
                    n.kubelet.heartbeat()
            ctrl.monitor()
            newly = ctrl.evictions - evicted_seen
            evicted_seen = ctrl.evictions
            for _ in range(newly):
                _arrive("low", 1)
        n = sched.schedule_pending()
        _account()
        live = [p for p in store.list("pods") if p.uid in created]
        unbound = [p for p in live if not p.spec.node_name]
        if not unbound:
            break
        stalled = stalled + 1 if n == 0 else 0
        _t.sleep(0.002)  # let shed aging / backoffs expire
    dt = _t.time() - t0

    # -- the SLO gates ---------------------------------------------------------
    m = sched.metrics
    sheds = {c: int(m.shed_total.value(**{"class": c}))
             for c in STORM_PRIORITY}
    live = [p for p in store.list("pods") if p.uid in created]
    unbound = [p for p in live if not p.spec.node_name]
    placed = len(bound_seen)
    failures = []
    for c in ("system", "high"):
        if sheds[c]:
            failures.append(f"{c}-class pods were shed ({sheds[c]})"
                            " — shed ceiling for high classes is 0")
    for c, slo in STORM_SLO_P99.items():
        p99c = _p99(latency[c])
        if latency[c] and p99c > slo:
            failures.append(
                f"{c}-class p99 {p99c*1e3:.0f}ms over its "
                f"{slo*1e3:.0f}ms SLO gate")
    if unbound:
        failures.append(f"{len(unbound)} pods never placed "
                        f"(permanent starvation)")
    if checker.violations:
        v = checker.violations[0]
        failures.append(
            f"{len(checker.violations)} cluster-invariant violation(s) "
            f"across {checker.checks} checks — first: {v.invariant}: "
            f"{v.detail}")
    if trace == "burst" and not sheds["low"]:
        failures.append("burst never engaged the shed plane "
                        "(low-class sheds == 0)")
    if gang_trace:
        # atomicity gate: no gang may survive partially placed
        groups = {}
        for p in live:
            g = (p.metadata.annotations or {}).get(
                "pod-group.scheduling.k8s.io/name")
            if g:
                groups.setdefault(g, []).append(p)
        for g, members in groups.items():
            nb = sum(1 for p in members if p.spec.node_name)
            if nb not in (0, 8):
                failures.append(f"gang {g} partially placed ({nb}/8)")
    if poison_uids:
        # the poisonstorm gates: every poison pod convicted and never
        # placed, and the device plane never blamed for bad work —
        # breaker trips and mesh reforms both pinned at zero.
        # Conviction is gated PER POD (the Poisoned condition each
        # conviction stamps), not on the cumulative counter — one pod
        # re-convicted twice must not cover for another that escaped
        # the isolation plane entirely
        bound_poison = 0
        unconvicted = dict(poison_uids)
        for p in store.list("pods"):
            if p.uid not in poison_uids:
                continue
            if p.spec.node_name:
                bound_poison += 1
            if any("poisoned" in c[1] for c in p.status.conditions
                   if c[0] == "PodScheduled"):
                unconvicted.pop(p.uid, None)
        if bound_poison:
            failures.append(f"{bound_poison} poison pods were PLACED")
        if unconvicted:
            failures.append(
                f"{len(unconvicted)} of {len(poison_uids)} poison pods "
                f"were never convicted")
        if sched.breaker.trips:
            failures.append(
                f"poison work tripped the device-path breaker "
                f"{sched.breaker.trips}x (gate: 0)")
        if int(m.mesh_reforms.total()):
            failures.append("poison work reformed the mesh (gate: 0)")
    detail = " ".join(
        f"{c}:p99={_p99(latency[c])*1e3:.0f}ms/shed={sheds[c]}"
        for c in ("system", "high", "normal", "low"))
    poison_note = (f" poison={len(poison_uids)} "
                   f"convictions={sched.poison_convictions} "
                   f"quarantined={sched.queue.quarantine_count()}"
                   if poison_uids else "")
    print(f"# storm[{trace}]: arrivals={len(created)} placed={placed} "
          f"wall={dt:.2f}s {detail} "
          f"evicted={evicted_seen if compound else 0}{poison_note}",
          file=sys.stderr)
    for f in failures:
        print(f"FATAL: storm[{trace}]: {f}", file=sys.stderr)
    if failures:
        sys.exit(1)
    _collect_mesh(sched)
    return placed, dt, _p99(latency["high"]), len(created)


def run_chaoscampaign_config(seed=7, schedules=50, ticks=8, budget_s=None):
    """Fixed-seed chaos campaign as a bench gate: sample `schedules`
    composed fault schedules, replay each against the HollowCluster
    scenario with the invariant checker armed strict, and FAIL the
    bench on any violation (each finding prints its shrunk
    KTPU_FAULTPOINTS reproducer first). A campaign that injected zero
    faults is also a failure — a silently-dead injector would turn
    this gate into a no-op."""
    from kubernetes_tpu.chaos.campaign import run_campaign

    t0 = time.perf_counter()
    res = run_campaign(seed, schedules, ticks=ticks, budget_s=budget_s)
    dt = time.perf_counter() - t0
    failures = []
    if res.injected_total == 0:
        failures.append("campaign injected 0 faults (dead injector?)")
    for f in res.findings:
        failures.append(
            f"invariant {f.outcome.violation}: {f.outcome.detail} — "
            f"repro: KTPU_FAULTPOINTS='{f.env}' python -m "
            f"kubernetes_tpu.chaos --repro --seed {f.seed} "
            f"(env re-triggers: {f.env_retriggers})")
    print(f"# chaoscampaign: seed={res.seed} schedules={res.schedules} "
          f"checks={res.checks_total} injected={res.injected_total} "
          f"findings={len(res.findings)} wall={dt:.2f}s", file=sys.stderr)
    for f in failures:
        print(f"FATAL: chaoscampaign: {f}", file=sys.stderr)
    if failures:
        sys.exit(1)
    return res, dt


def run_outagestorm_config(nodes, pods, wave):
    """Control-plane outage survival under load: a steady arrival
    stream through a HollowCluster with the store path SEVERED
    mid-run (duration-armed `store.outage` raise — every bind POST and
    truth GET fails until healed). The scheduler must keep scoring
    against its cache, spool bind intents into the durable journal,
    and drain the spool through the bind-ambiguity path after the
    heal. Gates (any violation FAILS the bench):

      - the outage actually engaged: store-path breaker tripped >= 1
        and binds_spooled > 0 (a run that never disconnected would
        turn this gate into a no-op)
      - zero cluster-invariant violations across every round (the
        checker's double-bind / conservation / capacity sweeps run
        strict=False and are tallied here)
      - spool drained within OUTAGE_DRAIN_ROUNDS post-heal rounds
      - every pod placed exactly once: no lost pods (all arrivals
        bound), no double-binds (store node_name is the single bind
        each uid ever got; journal fully resolved, assumptions empty)
    """
    import os as _os
    import tempfile
    import time as _t

    from kubernetes_tpu.api import types as api
    from kubernetes_tpu.chaos.invariants import InvariantChecker
    from kubernetes_tpu.kubemark.hollow import HollowCluster
    from kubernetes_tpu.ops.encoding import Caps
    from kubernetes_tpu.runtime.store import ObjectStore
    from kubernetes_tpu.sched.scheduler import Scheduler
    from kubernetes_tpu.state.vocab import bucket_size
    from kubernetes_tpu.utils import Metrics, faultpoints
    from kubernetes_tpu.utils.backoff import PodBackoff

    OUTAGE_DRAIN_ROUNDS = 8  # post-heal rounds the spool may take

    store = ObjectStore()
    vclock = [1000.0]
    jdir = tempfile.mkdtemp(prefix="ktpu-outagestorm-")
    jpath = _os.path.join(jdir, "bind.journal")
    caps = Caps(M=bucket_size(2 * pods + 64), P=wave,
                LV=bucket_size(nodes + 256, 64))
    sched = Scheduler(store, wave_size=wave, caps=caps,
                      clock=lambda: vclock[0],
                      # short cooldown + pinned jitter: the heal tick's
                      # 5s vclock step is always past retry_at, so the
                      # first post-heal housekeep probes and drains
                      store_breaker_cooldown=2.0,
                      bind_journal_path=jpath)
    sched.storehealth.jitter = lambda: 0.5
    sched.backoff = PodBackoff(initial=0.01, maximum=0.1)
    cluster = HollowCluster(store, nodes, clock=lambda: vclock[0])
    for n in cluster.nodes:
        n.kubelet.register_node()

    # warm the wave kernel outside the measured window — compile time
    # is a backend property, not an outage property
    warm = []
    for i in range(min(wave, 64)):
        p = _base_pod(api, f"warmup-{i}", "warmup")
        store.create("pods", p)
        warm.append(p)
    sched.warm_pipeline(warm, n_waves=1)
    while sched.run_once(timeout=0.0):
        pass
    for p in warm:
        try:
            store.delete("pods", "default", p.metadata.name)
        except KeyError:
            pass
    sched.metrics = Metrics()
    checker = InvariantChecker(metrics=sched.metrics, strict=False)
    sched.invariants = checker

    created = set()
    seq = [0]

    def _arrive(count):
        for _ in range(count):
            p = _base_pod(api, f"outage-{seq[0]}", "outage")
            seq[0] += 1
            store.create("pods", p)
            created.add(p.uid)

    # 10 arrival ticks; the store is dark for ticks [3, 8) — arrivals
    # keep flowing THROUGH the outage (the informer mirror is a
    # separate path from the bind/truth writes the fault severs)
    arrive_ticks = 10
    sever_at, heal_at = 3, 8
    per_tick = max(1, pods // arrive_ticks)
    spool_peak = 0
    heal_rounds = -1
    t0 = _t.time()
    try:
        for t in range(arrive_ticks):
            vclock[0] += 5.0
            if t == sever_at:
                faultpoints.activate("store.outage", "raise",
                                     times=10 ** 6)
            if t == heal_at:
                faultpoints.deactivate("store.outage")
            want = per_tick if t < arrive_ticks - 1 \
                else pods - per_tick * (arrive_ticks - 1)
            _arrive(want)
            sched.run_once(timeout=0.0)
            spool_peak = max(spool_peak, sched.spool_count())
        # post-heal: the spool must drain within its bounded round
        # budget, then every survivor must place (wall-bounded so a
        # wedge fails loudly instead of hanging)
        rounds = 0
        stalled = 0
        while stalled < 2000:
            vclock[0] += 5.0
            n = sched.schedule_pending()
            rounds += 1
            if heal_rounds < 0 and sched.spool_count() == 0:
                heal_rounds = rounds
            live = [p for p in store.list("pods") if p.uid in created]
            unbound = [p for p in live if not p.spec.node_name]
            if not unbound and sched.spool_count() == 0:
                break
            stalled = stalled + 1 if n == 0 else 0
            _t.sleep(0.002)
    finally:
        faultpoints.reset()
    dt = _t.time() - t0

    # -- the gates -------------------------------------------------------------
    m = sched.metrics
    trips = sched.storehealth.trips
    spooled = int(m.binds_spooled.value)
    bound = {}
    for p in store.list("pods"):
        if p.uid in created and p.spec.node_name:
            bound[p.uid] = p.spec.node_name
    placed = len(bound)
    failures = []
    if trips < 1:
        failures.append("store-path breaker never tripped "
                        "(outage never engaged?)")
    if spooled == 0:
        failures.append("no binds were spooled during the outage "
                        "(disconnected mode never engaged?)")
    if heal_rounds < 0 or heal_rounds > OUTAGE_DRAIN_ROUNDS:
        failures.append(
            f"spool not drained within {OUTAGE_DRAIN_ROUNDS} post-heal "
            f"rounds (drained after "
            f"{'never' if heal_rounds < 0 else heal_rounds})")
    if placed != len(created):
        failures.append(f"{len(created) - placed} pods never placed "
                        f"(lost across the outage)")
    leftover = sched.cache.assumed_pods()
    if leftover:
        failures.append(f"{len(leftover)} assumption(s) outlived the "
                        f"drain (bind intent leaked)")
    unresolved = sched.journal.unresolved() if sched.journal else []
    if unresolved:
        failures.append(f"{len(unresolved)} journal intent(s) never "
                        f"resolved after the heal")
    if checker.violations:
        v = checker.violations[0]
        failures.append(
            f"{len(checker.violations)} cluster-invariant violation(s) "
            f"across {checker.checks} checks — first: {v.invariant}: "
            f"{v.detail}")
    print(f"# outagestorm: arrivals={len(created)} placed={placed} "
          f"wall={dt:.2f}s trips={trips} spooled={spooled} "
          f"spool_peak={spool_peak} heal_rounds={heal_rounds} "
          f"journal={jpath}", file=sys.stderr)
    for f in failures:
        print(f"FATAL: outagestorm: {f}", file=sys.stderr)
    if failures:
        sys.exit(1)
    return placed, dt, spool_peak, heal_rounds


# -- resource-exhaustion soak (--workload soak) -------------------------------

def run_soak_config(nodes, pods, wave, epochs=None):
    """Resource-exhaustion survival under multi-day churn, compressed
    onto the virtual clock: every epoch retires a slice of nodes and
    bound pods and joins replacements with FRESH hostnames, zone/label
    values, and image names — the vocabulary-leak reproducer (interners
    are append-only between compactions). The memory-governance plane
    (HBM budget governor + cadence compaction, state/scrubber.py) must
    hold every footprint flat. Gates (any violation FAILS the bench):

      - vocab plateau: every interner's final size stays within a fixed
        band of its post-warmup baseline (the un-compacted leak grows
        linearly in epochs)
      - HBM plateau: the projected device footprint ends <= 2x baseline
      - host RSS: ru_maxrss grows < SOAK_MAX_RSS_MB past the warmup
      - recompile plateau: jit cache misses after the first quarter of
        epochs stay under SOAK_MAX_RECOMPILES (grow/shrink cycles must
        re-use the bucketed shapes, not mint new ones)
      - compaction parity: a probe wave's placements (by node NAME) are
        bit-equal immediately before and after a forced mid-run
        compaction
      - capacity-fault storm: device.oom armed for a burst — ZERO
        breaker trips, ZERO mesh reforms, ZERO pod convictions, every
        storm pod placed
      - zero cluster-invariant violations, and compactions actually ran
    """
    import resource as _resource
    import time as _t

    import numpy as np

    from kubernetes_tpu.api import types as api
    from kubernetes_tpu.chaos.invariants import InvariantChecker
    from kubernetes_tpu.ops.encoding import Caps
    from kubernetes_tpu.runtime.store import ObjectStore
    from kubernetes_tpu.sched.scheduler import Scheduler
    from kubernetes_tpu.state.vocab import bucket_size
    from kubernetes_tpu.utils import faultpoints

    SOAK_MAX_RSS_MB = 512       # backstop: a real leak grows unbounded
    SOAK_MAX_RECOMPILES = 24    # post-warmup jit misses (shape churn)
    SOAK_VOCAB_BAND = 64        # entries a vocab may drift past baseline
    epochs = epochs or 48
    churn_nodes = max(1, nodes // 8)
    churn_pods = max(4, pods // (2 * epochs))

    store = ObjectStore()
    vclock = [1000.0]
    caps = Caps(M=bucket_size(2 * pods + 64), P=wave,
                LV=bucket_size(4 * nodes + 256, 64))
    sched = Scheduler(store, wave_size=wave, caps=caps,
                      clock=lambda: vclock[0],
                      # cadence compaction every ~2 epochs of vclock; a
                      # generous budget keeps the governor out of the
                      # way unless a leak actually grows the footprint
                      compact_interval=100.0,
                      hbm_budget_bytes=256 * 1024 * 1024)
    checker = InvariantChecker(metrics=sched.metrics, strict=False)
    sched.invariants = checker

    def _mk_node(i, epoch):
        name = f"soak-{epoch}-{i}"
        return api.Node(
            metadata=api.ObjectMeta(name=name, labels={
                api.LABEL_HOSTNAME: name,
                api.LABEL_ZONE: f"zone-{epoch}-{i % 3}",
                "soak/rev": f"r{epoch}",
            }),
            status=api.NodeStatus(
                allocatable=api.resource_list(cpu="16", memory="32Gi",
                                              pods=110),
                conditions=[api.NodeCondition(type="Ready",
                                              status="True")]))

    def _mk_pod(name, epoch):
        p = _base_pod(api, name, "soak",
                      labels={"type": "soak", "rev": f"r{epoch}"})
        p.spec.containers[0].image = f"registry.example/app:{epoch}.{name}"
        return p

    def _miss_count():
        return sum(c.value
                   for c in sched.metrics.device_jit_events.children()
                   if 'event="miss"' in c.name)

    def _twin_names(probe):
        # non-committing placement probe through the numpy twin (the
        # same replay the input-fault verdict uses): placements by node
        # NAME, because compaction renumbers rows but must preserve
        # relative order (argmax tie-breaks)
        from kubernetes_tpu.ops import hostwave

        gating, wvec, _wver = sched._weights_kw()
        pb = sched.featurizer.featurize(probe)
        nt, pm, tt = sched.snapshot.host_tensors()
        extra = np.ones((pb.req.shape[0], nt.valid.shape[0]), bool)
        res, _usage = hostwave.schedule_wave_host(
            nt, pm, tt, pb, extra, sched._host_rr, None,
            weights=gating, num_zones=sched.snapshot.caps.Z,
            num_label_values=sched.snapshot.num_label_values,
            has_ipa=False, weight_vec=wvec)
        chosen = np.asarray(res.chosen)
        return [sched.snapshot.node_names[c] if c >= 0 else None
                for c in chosen[:len(probe)]]

    # -- warmup: base cluster + first waves + a settling compaction ----------
    node_ring = []  # (epoch, index) join order, oldest first
    for i in range(nodes):
        store.create("nodes", _mk_node(i, 0))
        node_ring.append(f"soak-0-{i}")
    for i in range(min(pods, 2 * wave)):
        store.create("pods", _mk_pod(f"warm-{i}", 0))
    t0 = _t.time()
    sched._housekeep()
    sched.schedule_pending()
    sched.scrubber.compact(trigger="cadence", force=True)
    base_vocabs = dict(sched.snapshot.vocabs.sizes())
    base_hbm = sched.snapshot.projected_hbm_bytes()
    base_rss_kb = _resource.getrusage(_resource.RUSAGE_SELF).ru_maxrss
    warm_misses = None  # sampled after the first quarter of epochs

    storm = {"trips": 0.0, "reforms": 0.0, "convictions": 0,
             "placed": 0, "pods": 0}
    parity = None
    seq = [0]
    failures = []
    try:
        for epoch in range(1, epochs + 1):
            vclock[0] += 60.0
            # retire the oldest nodes (their pods go with them) and
            # join fresh ones: new hostnames, new zone values, new rev
            for name in node_ring[:churn_nodes]:
                for p in store.list("pods"):
                    if p.spec.node_name == name:
                        try:
                            store.delete("pods", p.metadata.namespace,
                                         p.metadata.name)
                        except KeyError:
                            pass
                try:
                    store.delete("nodes", "default", name)
                except KeyError:
                    pass
            node_ring = node_ring[churn_nodes:]
            for i in range(churn_nodes):
                store.create("nodes", _mk_node(i, epoch))
                node_ring.append(f"soak-{epoch}-{i}")
            # fresh pods with fresh labels + image names
            for _ in range(churn_pods):
                store.create("pods", _mk_pod(f"churn-{seq[0]}", epoch))
                seq[0] += 1
            sched._housekeep()
            sched.schedule_pending()
            if epoch == max(2, epochs // 4) and warm_misses is None:
                warm_misses = _miss_count()
            if epoch == epochs // 2:
                # compaction parity: probe placements bit-equal across
                # a forced sweep (pods NOT created in the store — the
                # twin probe commits nothing)
                probe = [_mk_pod(f"probe-{i}", epoch) for i in range(8)]
                before = _twin_names(probe)
                summary = sched.scrubber.compact(trigger="governor",
                                                 force=True)
                after = _twin_names(probe)
                parity = (before == after, before, after,
                          summary is not None)
                # capacity-fault storm on the live path
                trips0 = sched.metrics.device_path_trips.value
                reforms0 = sched.metrics.mesh_reforms.total()
                conv0 = sched.poison_convictions
                storm_pods = [_mk_pod(f"storm-{i}", epoch)
                              for i in range(16)]
                for p in storm_pods:
                    store.create("pods", p)
                faultpoints.activate("device.oom", "raise", times=3)
                try:
                    sched._housekeep()
                    sched.schedule_pending()
                finally:
                    faultpoints.deactivate("device.oom")
                bound = {p.uid for p in store.list("pods")
                         if p.spec.node_name}
                storm = {
                    "trips": sched.metrics.device_path_trips.value
                             - trips0,
                    "reforms": sched.metrics.mesh_reforms.total()
                               - reforms0,
                    "convictions": sched.poison_convictions - conv0,
                    "placed": sum(1 for p in storm_pods
                                  if p.uid in bound),
                    "pods": len(storm_pods),
                }
    finally:
        faultpoints.reset()
    dt = _t.time() - t0

    # -- the gates -------------------------------------------------------------
    final_vocabs = sched.snapshot.vocabs.sizes()
    final_hbm = sched.snapshot.projected_hbm_bytes()
    rss_grow_mb = (_resource.getrusage(_resource.RUSAGE_SELF).ru_maxrss
                   - base_rss_kb) / 1024.0
    compactions = sched.metrics.snapshot_compactions_total.total()
    post_warm_misses = (_miss_count() - warm_misses
                        if warm_misses is not None else 0.0)
    for vocab, size in final_vocabs.items():
        if size > base_vocabs.get(vocab, 0) + SOAK_VOCAB_BAND:
            failures.append(
                f"vocab {vocab} leaked: {base_vocabs.get(vocab)} -> "
                f"{size} (band {SOAK_VOCAB_BAND})")
    if final_hbm > 2 * base_hbm:
        failures.append(f"HBM footprint grew {base_hbm} -> {final_hbm} "
                        f"bytes (> 2x baseline)")
    if rss_grow_mb > SOAK_MAX_RSS_MB:
        failures.append(f"host RSS grew {rss_grow_mb:.0f} MB past the "
                        f"warmup (> {SOAK_MAX_RSS_MB} MB)")
    if post_warm_misses > SOAK_MAX_RECOMPILES:
        failures.append(f"{post_warm_misses:.0f} post-warmup jit "
                        f"recompiles (> {SOAK_MAX_RECOMPILES}: the "
                        f"grow/shrink cycle is thrashing shapes)")
    if compactions < 2:
        failures.append(f"only {compactions:.0f} compaction(s) ran — "
                        f"the cadence never engaged, the soak gated "
                        f"nothing")
    if parity is None or not parity[3]:
        failures.append("mid-run forced compaction did not run "
                        "(parity gate is a no-op)")
    elif not parity[0]:
        failures.append(f"placements diverged across the mid-run "
                        f"compaction: {parity[1]} != {parity[2]}")
    if storm["pods"] == 0:
        failures.append("device.oom storm never ran")
    if storm["trips"] != 0:
        failures.append(f"device.oom storm tripped the breaker "
                        f"{storm['trips']:.0f}x (capacity faults must "
                        f"never convict the device path)")
    if storm["reforms"] != 0:
        failures.append(f"device.oom storm reformed the mesh "
                        f"{storm['reforms']:.0f}x")
    if storm["convictions"] != 0:
        failures.append(f"device.oom storm convicted "
                        f"{storm['convictions']} pod(s)")
    if storm["pods"] and storm["placed"] != storm["pods"]:
        failures.append(f"device.oom storm: only {storm['placed']}/"
                        f"{storm['pods']} storm pods placed")
    if checker.violations:
        v = checker.violations[0]
        failures.append(
            f"{len(checker.violations)} cluster-invariant violation(s) "
            f"across {checker.checks} checks — first: {v.invariant}: "
            f"{v.detail}")
    print(f"# soak: epochs={epochs} churn={churn_nodes}n/"
          f"{churn_pods}p per epoch wall={dt:.2f}s "
          f"compactions={compactions:.0f} "
          f"vocabs={base_vocabs}->{final_vocabs} "
          f"hbm={base_hbm}->{final_hbm} rss_grow={rss_grow_mb:.0f}MB "
          f"recompiles_post_warm={post_warm_misses:.0f}", file=sys.stderr)
    for f in failures:
        print(f"FATAL: soak: {f}", file=sys.stderr)
    if failures:
        sched.close()
        sys.exit(1)
    sched.close()
    return epochs, dt, compactions, final_hbm


# -- heterogeneous topology workload (--workload hetero) ----------------------
#
# A rack/superpod/accel-gen labeled cluster (state/snapshot.py's dense
# topology columns, ops/topology.py's kernels) under two hard gates:
#   1. spread skew gate: zone-spread pods under a maxSkew=1
#      DoNotSchedule constraint must land with per-zone counts
#      differing by <= 1 — checked from the STORE's bindings after the
#      drain, not from the kernel's own claim
#   2. compactness margin gate: priority gangs placed under the default
#      profile (TopologyCompactness on) must use fewer distinct racks
#      per gang than the identical workload with the plane zeroed (the
#      scattered baseline), by >= HETERO_MARGIN racks on average

HETERO_MARGIN = 0.25
HETERO_GANG = 6


def _hetero_store(nodes, racks=8, gens=3):
    """Cluster with the full topology label set: 3 zones, `racks` racks
    nested pairwise under superpods, accel generations cycling by rack
    (whole racks share a generation, like real pod-slice deployments)."""
    from kubernetes_tpu.api import types as api
    from kubernetes_tpu.runtime.store import ObjectStore

    store = ObjectStore()
    for i in range(nodes):
        rack = i % racks
        labels = {
            api.LABEL_HOSTNAME: f"node-{i}",
            api.LABEL_ZONE: f"zone-{i % 3}",
            api.LABEL_RACK: f"rack-{rack}",
            api.LABEL_SUPERPOD: f"sp-{rack // 2}",
            api.LABEL_ACCEL_GEN: str(1 + rack % gens),
        }
        store.create("nodes", api.Node(
            metadata=api.ObjectMeta(name=f"node-{i}", labels=labels),
            status=api.NodeStatus(
                allocatable=api.resource_list(cpu="16", memory="32Gi",
                                              pods=110,
                                              ephemeral_storage="200Gi"),
                conditions=[api.NodeCondition(api.NODE_READY,
                                              api.COND_TRUE)])))
    return store


def _gang_rack_mean(store, api):
    """Mean distinct racks per placed gang — the compactness observable."""
    node_rack = {n.metadata.name: (n.metadata.labels or {}).get(
        api.LABEL_RACK, "") for n in store.list("nodes")}
    gangs = {}
    for p in store.list("pods"):
        g = (p.metadata.annotations or {}).get(
            "pod-group.scheduling.k8s.io/name")
        if g and p.spec.node_name:
            gangs.setdefault(g, set()).add(node_rack[p.spec.node_name])
    if not gangs:
        return 0.0
    return sum(len(r) for r in gangs.values()) / len(gangs)


def run_hetero_config(nodes, pods, wave, mesh=None, margin=HETERO_MARGIN):
    """Phase 1: pods//2 zone-spread DoNotSchedule pods (skew gate).
    Phase 2: the gang workload placed twice against fresh stores —
    default profile vs TopologyCompactness zeroed — for the margin
    gate. Returns (placed, dt, compact_racks, scattered_racks, skew)."""
    from kubernetes_tpu.api import types as api
    from kubernetes_tpu.api.labels import LabelSelector
    from kubernetes_tpu.ops.encoding import Caps
    from kubernetes_tpu.plugins.registry import default_profile
    from kubernetes_tpu.sched.scheduler import Scheduler
    from kubernetes_tpu.state.vocab import bucket_size

    n_spread = pods // 2
    n_gang = pods - n_spread

    def sched_for(store, compact=True):
        prof = default_profile(store)
        if not compact:
            prof.score_weights = dict(prof.score_weights)
            # weight 0 compiles the plane out entirely (the kernel's
            # static weight gate) — the baseline is scattered by
            # construction, not merely down-weighted
            prof.score_weights["TopologyCompactnessPriority"] = 0
        # P=16 keeps each gang in one joint program, like run_config's
        # gang leg; spread pods drain through 16-wide waves
        caps = Caps(M=bucket_size(pods + 64), P=16, E=8,
                    LV=bucket_size(nodes + 256, 64))
        return Scheduler(store, profile=prof, wave_size=wave, caps=caps,
                         mesh=mesh)

    t0 = time.time()
    store_s = _hetero_store(nodes)
    sched_s = sched_for(store_s)
    for i in range(n_spread):
        pod = _base_pod(api, f"hetero-spread-{i}", "hetero-spread")
        pod.spec.topology_spread_constraints = [api.TopologySpreadConstraint(
            max_skew=1, topology_key=api.LABEL_ZONE,
            when_unsatisfiable=api.DO_NOT_SCHEDULE,
            label_selector=LabelSelector(
                match_labels={"type": "hetero-spread"}))]
        store_s.create("pods", pod)
    placed_s = sched_s.schedule_pending()
    node_zone = {n.metadata.name: (n.metadata.labels or {}).get(
        api.LABEL_ZONE, "") for n in store_s.list("nodes")}
    counts = {z: 0 for z in set(node_zone.values())}
    for p in store_s.list("pods"):
        if p.spec.node_name and (p.metadata.labels or {}).get(
                "type") == "hetero-spread":
            counts[node_zone[p.spec.node_name]] += 1
    skew = max(counts.values()) - min(counts.values())

    def make_gangs(store):
        made, g = 0, 0
        while made < n_gang:
            size = min(HETERO_GANG, n_gang - made)
            for j in range(size):
                p = _base_pod(api, f"hetero-gang-{made + j}", "hetero-gang")
                p.spec.priority = 5  # accel-gen steering needs prio > 0
                p.metadata.annotations = {
                    "pod-group.scheduling.k8s.io/name": f"hgang-{g}",
                    "pod-group.scheduling.k8s.io/min-available": str(size)}
                store.create("pods", p)
            made += size
            g += 1

    store_c = _hetero_store(nodes)
    sched_c = sched_for(store_c, compact=True)
    make_gangs(store_c)
    placed_c = sched_c.schedule_pending()
    store_x = _hetero_store(nodes)
    sched_x = sched_for(store_x, compact=False)
    make_gangs(store_x)
    placed_x = sched_x.schedule_pending()
    dt = time.time() - t0

    compact_racks = _gang_rack_mean(store_c, api)
    scattered_racks = _gang_rack_mean(store_x, api)

    failures = []
    if placed_s != n_spread:
        failures.append(f"spread phase placed {placed_s}/{n_spread}")
    if skew > 1:
        failures.append(f"DoNotSchedule zone skew {skew} > maxSkew 1 "
                        f"(zone counts {counts})")
    if placed_c != n_gang or placed_x != n_gang:
        failures.append(f"gang phase placed compact={placed_c} "
                        f"scattered={placed_x} of {n_gang}")
    if scattered_racks - compact_racks < margin:
        failures.append(
            f"compactness margin {scattered_racks - compact_racks:.2f} < "
            f"{margin} (compact {compact_racks:.2f} vs scattered "
            f"{scattered_racks:.2f} racks/gang)")
    for f in failures:
        print(f"FATAL: hetero: {f}", file=sys.stderr)
    if failures:
        sys.exit(1)
    return placed_s + placed_c, dt, compact_racks, scattered_racks, skew


def stage_breakdown(top=12):
    """Per-stage wall-time totals from the step profiler (fed by every
    Trace the scheduler emits) — the bench json carries WHERE the run's
    seconds went, not just the throughput. Includes warm-up/fill phases:
    this attributes the whole process's scheduling work."""
    from kubernetes_tpu.utils import profiling

    prof = profiling.active()
    if prof is None:
        return None
    return {k: round(v, 3) for k, v in prof.step_totals(top=top).items()}


def telemetry_trajectory(max_points=32):
    """Fragmentation/utilization trajectory from the flight recorder's
    round ledger (present when --telemetry enabled the recorder): the
    last ring-buffer's worth of rounds, downsampled to max_points —
    enough to see whether a drain fragments the cluster as it fills."""
    from kubernetes_tpu.utils import tracing

    rec = tracing.active()
    if rec is None:
        return None
    rows = [r["telemetry"] for r in rec.ledger_rows() if "telemetry" in r]
    if not rows:
        return None
    if len(rows) > max_points:
        step = (len(rows) - 1) / (max_points - 1)
        rows_s = [rows[round(i * step)] for i in range(max_points)]
    else:
        rows_s = rows
    return {
        "rounds": len(rows),
        "cpu_util": [t["util"].get("cpu") for t in rows_s],
        "cpu_frag": [t["frag"].get("cpu") for t in rows_s],
        "mem_frag": [t["frag"].get("memory") for t in rows_s],
        "headroom_final": rows[-1]["headroom"],
    }


def emit(name, nodes, pods, placed, dt, p99, p99_round, wave, path="?"):
    if placed != pods:
        print(f"FATAL: {name}: placed {placed}/{pods}", file=sys.stderr)
        sys.exit(1)
    rate = placed / dt if dt > 0 else 0.0
    rec = {
        "metric": f"scheduler_{name}_pods_per_sec_{nodes}n_{pods}p",
        "value": round(rate, 1),
        "unit": "pods/s",
        "vs_baseline": round(rate / 100.0, 2),
        # the wave size the config actually ran (preempt_host runs 16,
        # the host path's best measured configuration, while everything
        # else runs the default 256) — recorded so BENCH rounds stay
        # comparable across configs without unifying the knob
        "wave": wave,
    }
    stages = stage_breakdown()
    if stages:
        rec["stages"] = stages
    tele = telemetry_trajectory()
    if tele:
        rec["telemetry"] = tele
    if _SHADOW_SUMMARY:
        # per-candidate-profile counterfactual divergence over the whole
        # run (--shadow profile.json): {profile: {pods, flips,
        # margin_delta, exact?}} — flips are a top-K lower bound
        rec["shadow"] = _SHADOW_SUMMARY
    if _MESH_SUMMARY:
        # mesh fault plane (--kill-device / any reform during the run):
        # final device count, reforms by direction, quarantined devices
        rec["mesh"] = _MESH_SUMMARY
    print(json.dumps(rec), flush=True)
    print(f"# {name}: placed={placed} wall={dt:.2f}s wave={wave} "
          f"path={path} p99_pod_latency={p99*1e3:.0f}ms "
          f"p99_round_latency={p99_round*1e3:.0f}ms", file=sys.stderr)


# BASELINE.md config grid + the preempt/trickle regimes; entries are
# (name, nodes, pods, workload, extra_flags)
SUITE = [
    ("basic", 500, 1000, "density", []),
    ("affinity", 100, 3000, "affinity", []),
    ("spreading", 500, 3000, "spreading", []),
    ("antiaffinity", 500, 2500, "antiaffinity", []),
    ("trickle", 500, 2048, "trickle", []),
    ("preempt", 50, 100, "preempt", []),
    # breaker-open degraded mode: KTPU_FAULTPOINTS kills every device
    # kernel entry, the breaker trips, and the backlog drains through
    # the vectorized numpy host twin — regression-gates the 240x
    # host-path cliff (`make bench-all`)
    ("degraded", 500, 2000, "degraded", []),
    # gang coscheduling: 72 gangs cycling sizes 4/8/16 (28 pods/cycle),
    # each placed all-or-nothing through ops/gang.py
    ("gang", 500, 2016, "gang", []),
    # elastic cluster: 50 nodes vs 2000 one-core pods across 2 node
    # groups — pods/s to full placement including the autoscaler's
    # on-device what-ifs and simulated node join latency
    ("autoscale", 50, 2000, "autoscale", []),
    # zone disruption: one zone, 30% of nodes severed mid-run — the
    # detect -> taint -> rate-limited evict -> recreate -> re-place loop
    ("partition", 200, 2000, "partition", []),
    # trace-replay storm grid: the 5x low-class burst through kubemark's
    # HollowCluster with per-priority-class SLO gates (p99 by class,
    # zero high-class sheds, no permanent starvation) that FAIL the
    # bench on violation — the overload-control regression gate
    # shape pinned to 100n/wave 64: storm capacity is one wave/tick and
    # the high-class p99 floor is one wave's wall time (~1.3s on an
    # idle CPU backend at this shape, ~3s under CPU contention —
    # inside the 5s STORM_SLO_P99 gate either way); wider waves on CPU
    # would spend the SLO gate on wave cost, not storm behavior
    ("storm", 100, 0, "storm", ["--trace", "burst", "--wave", "64"]),
    # poison-work isolation under load: the same burst trace with 1% of
    # the low-class arrivals carrying malformed (NaN request) specs.
    # Gates: the CLEAN classes hold the identical storm SLOs (a poison
    # pod must not cost its wavemates), every poison pod is convicted
    # and quarantined, and the device plane is never blamed — breaker
    # trips and mesh reforms both pinned at ZERO
    ("poisonstorm", 100, 0, "storm", ["--trace", "burst", "--wave", "64",
                                      "--poison", "0.01"]),
    # chaos campaign: 50 seeded composed fault schedules against the
    # HollowCluster scenario with every cluster invariant checked after
    # each round — any violation fails the bench and prints its shrunk
    # KTPU_FAULTPOINTS reproducer (nodes/pods come from the campaign
    # scenario, not the grid numbers)
    ("chaoscampaign", 2, 0, "chaoscampaign", []),
    # control-plane outage survival: the store path severed for half
    # the arrival window (store.outage raise) — scheduling continues
    # against the cache, binds spool into the durable intent journal,
    # and the spool must drain within 8 post-heal rounds with zero
    # double-binds, zero lost pods, and zero invariant violations
    ("outagestorm", 100, 400, "outagestorm", ["--wave", "64"]),
    # resource-exhaustion soak: multi-day node/pod churn (fresh
    # hostnames / zone values / images every epoch — the vocab-leak
    # reproducer) compressed onto the virtual clock; gates vocab/HBM/
    # RSS/recompile plateaus, a bit-equal probe wave across a forced
    # compaction, and a device.oom storm surviving with zero breaker
    # trips / mesh reforms / pod convictions
    ("soak", 32, 256, "soak", ["--wave", "32"]),
    # heterogeneous topology: rack/superpod/accel-gen labeled cluster;
    # hard gates on DoNotSchedule zone skew (<= maxSkew, read back from
    # the store) and on gang rack-compactness beating the
    # compactness-zeroed scattered baseline by >= HETERO_MARGIN
    ("hetero", 24, 240, "hetero", ["--wave", "16"]),
    ("mixed5k", 5000, 30000, "mixed", []),
    # fleet scale: 50k nodes / 200k pod churn under the mesh-sharded
    # scheduling plane (--mesh auto shards the node axis across every
    # visible device; single-device backends run it unsharded). Gated
    # behind the bench surface — NOT tier-1 — like every other config;
    # kept out of DRIVER_SUITE so the driver's fixed command stays
    # bounded (run via `make bench-all` / an explicit --workload mixed
    # --nodes 50000 --pods 200000 invocation).
    ("mixed50k", 50000, 200000, "mixed", ["--mesh", "auto"]),
    # mesh fault tolerance: the mixed workload under --mesh auto with a
    # mid-run device kill — the round salvages through the twin, the
    # mesh reforms down a rung, and the run must still place everything
    # (the JSON line's `mesh` summary records the ladder)
    ("meshfault", 500, 2000, "mixed", ["--mesh", "auto",
                                       "--kill-device", "1"]),
]

# what a bare `python bench.py` (the driver's fixed command) runs: the
# reference's density shape, the steady-state regimes (trickle, preempt
# at DEFAULT flags — the round-4 verdict's 0.3 pods/s cliff, now
# guarded), the device-vs-host preemption pair (host at wave=16, its
# best measured configuration), the paced latency SLO, and the 5k/30k
# north-star config LAST so the parsed headline stays the number that
# matters
DRIVER_SUITE = [
    ("density", 100, 3000, "density", []),
    ("trickle", 500, 2048, "trickle", []),
    ("preempt", 50, 100, "preempt", []),
    # host preemption baseline (ISSUE 7 acceptance gate: >= 50 pods/s):
    # the batched what-if on the numpy twin instead of the device
    # kernel. Kept at wave=16 — the r05 host entry's configuration — so
    # the series stays comparable across rounds
    ("preempt_host", 50, 100, "preempt", ["--host-preempt",
                                          "--wave", "16"]),
    ("gang", 500, 2016, "gang", []),
    ("paced", 5000, 4000, "paced", []),
    ("mixed5k", 5000, 30000, "mixed", []),
]


def run_subprocess_suite(suite, wave, cpu, tracing=False, trace_ledger=None,
                         telemetry=False, shadow=None):
    # one subprocess per config: a run's end-of-round result fetch
    # leaves the tunneled TPU runtime in its degraded transfer mode,
    # which would taint every subsequent config in this process
    import os
    import subprocess

    for name, nodes, pods, workload, extra in suite:
        cmd = [sys.executable, os.path.abspath(__file__),
               "--nodes", str(nodes), "--pods", str(pods),
               "--workload", workload, "--name", name]
        if "--wave" not in extra:
            cmd += ["--wave", str(wave)]
        cmd += extra
        cmd.append("--skip-backend-probe")  # the parent already probed
        if tracing:
            cmd.append("--tracing")
        if telemetry:
            cmd.append("--telemetry")
        if shadow:
            # threaded through every child: configs that drain through
            # run_config shadow-score the run and emit the divergence
            # summary on their JSON line; the rest accept and ignore it
            cmd += ["--shadow", shadow]
        if trace_ledger:
            # per-config ledgers: concurrent-process appends would
            # interleave otherwise, and per-config files are what the
            # offline scoring analysis wants anyway
            cmd += ["--trace-ledger", f"{trace_ledger}.{name}"]
        if cpu:
            cmd.append("--cpu")
        r = subprocess.run(cmd, capture_output=True, text=True)
        sys.stdout.write(r.stdout)
        sys.stdout.flush()
        if r.returncode != 0:
            # full child stderr: a crash's traceback is the only
            # diagnostic there is
            sys.stderr.write(r.stderr)
            sys.exit(r.returncode)
        for line in r.stderr.splitlines():
            if line.startswith("#") or "FATAL" in line:
                print(line, file=sys.stderr)


def tpu_backend_alive(timeout: float = 180.0) -> bool:
    """Probe device discovery in a THROWAWAY subprocess with a hard
    timeout. The axon TPU tunnel can wedge machine-wide (observed: every
    new process hangs in jax.devices() indefinitely, for hours); a bench
    that hangs records nothing, so on a dead tunnel we fall back to CPU
    and say so, which beats an empty artifact."""
    import subprocess

    try:
        r = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout, capture_output=True)
    except subprocess.TimeoutExpired:
        print(f"# TPU probe: device discovery HUNG >{timeout:.0f}s "
              f"(wedged tunnel)", file=sys.stderr)
        return False
    if r.returncode != 0:
        tail = (r.stderr or b"").decode(errors="replace").strip()
        print(f"# TPU probe: device discovery FAILED rc={r.returncode}: "
              f"{tail[-300:]}", file=sys.stderr)
        return False
    return True


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=None)
    ap.add_argument("--pods", type=int, default=None)
    ap.add_argument("--wave", type=int, default=None,
                    help="wave size (default 256; the storm workload "
                         "defaults to its validated 64 instead — one "
                         "wave per tick IS storm capacity, and a "
                         "256-wide CPU wave would spend the SLO gate "
                         "on wave cost)")
    ap.add_argument("--workload", default=None,
                    choices=["density", "affinity", "spreading",
                             "antiaffinity", "mixed", "gang", "preempt",
                             "trickle", "paced", "autoscale", "partition",
                             "degraded", "storm", "chaoscampaign",
                             "outagestorm", "soak", "hetero"])
    ap.add_argument("--trace", default=None,
                    choices=["burst", "diurnal", "gangstorm", "compound"],
                    help="storm workload: which synthetic arrival trace "
                         "to replay through the HollowCluster (implies "
                         "--workload storm); SLO-gate violations FAIL "
                         "the bench")
    ap.add_argument("--mesh", default=None,
                    help="shard the scheduling plane's node axis across "
                         "devices: an integer count, or 'auto' for every "
                         "visible device (placements stay bit-identical "
                         "to single-device; tests/test_mesh.py)")
    ap.add_argument("--kill-device", type=int, default=None,
                    metavar="ORDINAL",
                    help="mesh fault leg: arm a device.lost fault for "
                         "the mesh's Nth device during the measured run "
                         "— the round salvages through the twin and the "
                         "mesh reforms down one rung (requires --mesh); "
                         "the JSON line gains a `mesh` ladder summary")
    ap.add_argument("--poison", type=float, default=0.0, metavar="FRAC",
                    help="storm workload: poison this fraction of the "
                         "low-class arrivals with a malformed (NaN "
                         "request) spec — the poisonstorm leg; gates "
                         "add every-poison-convicted + zero breaker "
                         "trips + zero mesh reforms on top of the "
                         "plain storm's clean-class SLOs")
    ap.add_argument("--seed", type=int, default=7,
                    help="chaoscampaign workload: campaign seed "
                         "(workload derivation + schedule sampling)")
    ap.add_argument("--schedules", type=int, default=50,
                    help="chaoscampaign workload: fault schedules to "
                         "sample and replay")
    ap.add_argument("--host-preempt", action="store_true",
                    help="preempt workload: run the batched what-if on "
                         "the vectorized numpy host twin instead of the "
                         "device kernel (the host baseline)")
    ap.add_argument("--rate", type=float, default=200.0,
                    help="paced workload: offered load in pods/s")
    ap.add_argument("--chunk", type=int, default=None,
                    help="trickle/paced: pods per arrival chunk "
                         "(default: trickle 64, paced 100)")
    ap.add_argument("--suite", action="store_true",
                    help="run the BASELINE config grid plus the "
                         "trickle/preempt regimes (7 configs)")
    ap.add_argument("--name", default="",
                    help="metric name override (suite subprocesses)")
    ap.add_argument("--cpu", action="store_true", help="force CPU backend")
    ap.add_argument("--tracing", action="store_true",
                    help="flight recorder on for the run (per-pod span "
                         "tracing; ~no cost when off)")
    ap.add_argument("--trace-ledger", default=None,
                    help="append per-round JSONL ledger records here "
                         "(implies --tracing)")
    ap.add_argument("--telemetry", action="store_true",
                    help="per-round cluster-state telemetry (implies "
                         "--tracing): the emitted JSON lines carry "
                         "fragmentation/utilization trajectories and "
                         "final feasibility headroom")
    ap.add_argument("--shadow", default=None, metavar="PROFILE_JSON",
                    help="shadow-score the run under the candidate "
                         "WeightProfiles in this JSON file (implies "
                         "--tracing); the emitted JSON lines grow a "
                         "`shadow` divergence summary per profile")
    ap.add_argument("--skip-backend-probe", action="store_true",
                    help=argparse.SUPPRESS)  # suite children: parent probed
    args = ap.parse_args()
    if args.trace and args.workload is None:
        args.workload = "storm"
    if args.wave is None:
        args.wave = 64 if args.workload == "storm" else 256
    # a bare invocation (no config selection) runs the driver pair
    # (density + north star); judged on PARSED values so abbreviated
    # flags like --pod count as explicit too
    explicit = (args.suite or args.name
                or any(v is not None for v in (args.nodes, args.pods,
                                               args.workload)))
    if args.nodes is None:
        args.nodes = 100
    if args.pods is None:
        args.pods = 3000
    if args.workload is None:
        args.workload = "density"

    if not args.cpu and not args.skip_backend_probe:
        # EVERY non-cpu invocation probes the device backend first —
        # explicit single-config runs would otherwise hang forever on a
        # wedged tunnel exactly like the suite would. Suite children
        # skip it (the parent probed).
        if not tpu_backend_alive():
            print("# WARNING: TPU backend unreachable (probe details "
                  "above) — falling back to CPU; values below are "
                  "backend=cpu, NOT TPU numbers", file=sys.stderr)
            args.cpu = True

    if args.cpu:
        import os

        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")

    if args.suite:
        run_subprocess_suite(SUITE, args.wave, args.cpu,
                             tracing=args.tracing,
                             trace_ledger=args.trace_ledger,
                             telemetry=args.telemetry,
                             shadow=args.shadow)
        return
    if not explicit:
        run_subprocess_suite(DRIVER_SUITE, args.wave, args.cpu,
                             tracing=args.tracing,
                             trace_ledger=args.trace_ledger,
                             telemetry=args.telemetry,
                             shadow=args.shadow)
        return

    # the measured child: the step profiler feeds the per-stage
    # wall-time breakdown in the emitted json; the flight recorder is
    # opt-in (its off-cost is one attribute read per site)
    from kubernetes_tpu.utils import profiling

    profiling.enable()
    if args.tracing or args.trace_ledger or args.telemetry or args.shadow:
        # --shadow implies tracing: the shadow pass re-weights the
        # per-priority decomposition, which only rides out of traced
        # rounds
        from kubernetes_tpu.utils import tracing as _tracing

        _tracing.enable(ledger_path=args.trace_ledger or None)

    if args.workload == "chaoscampaign":
        res, dt = run_chaoscampaign_config(seed=args.seed,
                                           schedules=args.schedules)
        name = args.name or "chaoscampaign"
        rec = {
            # the headline is clean schedules survived — the gate
            # already sys.exit(1)'d if any schedule violated an
            # invariant or the injector went dead
            "metric": f"scheduler_{name}_clean_schedules_"
                      f"seed{res.seed}",
            "value": res.schedules,
            "unit": "schedules",
            "vs_baseline": 1.0,
            "checks": res.checks_total,
            "injected": res.injected_total,
            "wall_s": round(dt, 2),
        }
        print(json.dumps(rec), flush=True)
        return
    if args.workload == "outagestorm":
        placed, dt, spool_peak, heal_rounds = run_outagestorm_config(
            args.nodes or 100, args.pods or 400, args.wave or 64)
        name = args.name or "outagestorm"
        rec = {
            # the headline is post-heal drain rounds — how fast the
            # spooled outage backlog reconciles once the store returns
            # (the hard gates — zero double-binds / lost pods /
            # invariant violations — already sys.exit(1)'d above)
            "metric": f"scheduler_{name}_heal_rounds_"
                      f"{args.nodes or 100}n_{placed}p",
            "value": heal_rounds,
            "unit": "rounds",
            "vs_baseline": (round(8.0 / heal_rounds, 2)
                            if heal_rounds > 0 else 0.0),
            "spool_peak": spool_peak,
            "wall_s": round(dt, 2),
        }
        print(json.dumps(rec), flush=True)
        return
    if args.workload == "soak":
        epochs, dt, compactions, final_hbm = run_soak_config(
            args.nodes or 32, args.pods or 256, args.wave or 32)
        name = args.name or "soak"
        rec = {
            # the headline is compactions per epoch — how often the
            # memory-governance plane had to sweep to hold the
            # footprints flat (the hard gates — vocab/HBM/RSS/recompile
            # plateaus, probe parity across a compaction, zero-trip
            # device.oom storm — already sys.exit(1)'d above)
            "metric": f"scheduler_{name}_compactions_"
                      f"{args.nodes or 32}n_{epochs}e",
            "value": compactions,
            "unit": "compactions",
            "vs_baseline": round(compactions / epochs, 3),
            "hbm_bytes": final_hbm,
            "wall_s": round(dt, 2),
        }
        print(json.dumps(rec), flush=True)
        return
    if args.workload == "hetero":
        placed, dt, compact_racks, scattered_racks, skew = run_hetero_config(
            args.nodes, args.pods, args.wave, mesh=_resolve_mesh(args.mesh))
        name = args.name or "hetero"
        rec = {
            # the headline is the rack-compactness margin over the
            # scattered baseline — the hard gates (skew <= maxSkew,
            # margin >= HETERO_MARGIN, full placement in every phase)
            # already sys.exit(1)'d inside run_hetero_config
            "metric": f"scheduler_{name}_rack_margin_"
                      f"{args.nodes}n_{args.pods}p",
            "value": round(scattered_racks - compact_racks, 2),
            "unit": "racks/gang",
            "vs_baseline": (round(scattered_racks / compact_racks, 2)
                            if compact_racks else 0.0),
            "compact_racks": round(compact_racks, 2),
            "scattered_racks": round(scattered_racks, 2),
            "spread_skew": skew,
            "wave": args.wave,
        }
        print(json.dumps(rec), flush=True)
        print(f"# {name}: placed={placed} wall={dt:.2f}s "
              f"compact={compact_racks:.2f} scattered={scattered_racks:.2f} "
              f"racks/gang skew={skew}", file=sys.stderr)
        return
    if args.workload == "storm":
        trace = args.trace or "burst"
        placed, dt, high_p99, arrivals = run_storm_config(
            args.nodes, args.wave, trace=trace,
            mesh=_resolve_mesh(args.mesh), kill_device=args.kill_device,
            poison_frac=args.poison)
        name = args.name or "storm"
        rec = {
            # the headline is the high-class p99 against its SLO gate —
            # under a storm, protecting the high classes IS the product
            "metric": f"scheduler_{name}_{trace}_high_p99_ms_"
                      f"{args.nodes}n_{arrivals}p",
            "value": round(high_p99 * 1e3, 1),
            "unit": "ms",
            "vs_baseline": (round(STORM_SLO_P99["high"] / high_p99, 2)
                            if high_p99 > 0 else 0.0),
            "wave": args.wave,
        }
        stages = stage_breakdown()
        if stages:
            rec["stages"] = stages
        if _MESH_SUMMARY:
            rec["mesh"] = _MESH_SUMMARY
        print(json.dumps(rec), flush=True)
        return
    if args.workload == "preempt":
        placed, dt, p99, p99_round, path = run_preempt_config(
            args.nodes, args.pods, args.wave,
            device=not args.host_preempt, mesh=_resolve_mesh(args.mesh))
    elif args.workload == "degraded":
        placed, dt, p99, p99_round, path = run_degraded_config(
            args.nodes, args.pods, args.wave,
            mesh=_resolve_mesh(args.mesh))
    elif args.workload == "autoscale":
        placed, dt, p99, p99_round, path = run_autoscale_config(
            args.nodes, args.pods, args.wave,
            mesh=_resolve_mesh(args.mesh))
    elif args.workload == "partition":
        replaced, dt, p99, p99_round, path, target = run_partition_config(
            args.nodes, args.pods, args.wave,
            mesh=_resolve_mesh(args.mesh))
        # the "pods" of this workload are the severed zone's residents:
        # each must be evicted, recreated, and re-placed
        emit(args.name or "partition", args.nodes, target, replaced, dt,
             p99, p99_round, args.wave, path)
        return
    elif args.workload == "trickle":
        placed, dt, p99, p99_round, path = run_trickle_config(
            args.nodes, args.pods, args.wave, chunk=args.chunk or 64,
            mesh=_resolve_mesh(args.mesh))
    elif args.workload == "paced":
        placed, dt, p99, offered, path = run_paced_config(
            args.nodes, args.pods, args.wave, rate=args.rate,
            chunk=args.chunk or 100, mesh=_resolve_mesh(args.mesh))
        if placed != args.pods:
            print(f"FATAL: paced: placed {placed}/{args.pods}",
                  file=sys.stderr)
            sys.exit(1)
        name = args.name or "paced"
        rec = {
            "metric": f"scheduler_{name}_p99_ms_{args.nodes}n_"
                      f"{int(args.rate)}pps",
            "value": round(p99 * 1e3, 1),
            "unit": "ms",
            # headroom under the reference's 5s pod-startup SLO at
            # >=10x its 10 pods/s offered load (load.go:124, density.go:55)
            "vs_baseline": round(5.0 / p99, 2) if p99 > 0 else 0.0,
            "wave": args.wave,
        }
        stages = stage_breakdown()
        if stages:
            rec["stages"] = stages
        tele = telemetry_trajectory()
        if tele:
            rec["telemetry"] = tele
        print(json.dumps(rec), flush=True)
        print(f"# {name}: placed={placed} wall={dt:.2f}s "
              f"offered={offered:.0f}pods/s (target {args.rate:.0f}) "
              f"wave={args.wave} path={path} p99_pod_latency={p99*1e3:.0f}ms",
              file=sys.stderr)
        return
    else:
        placed, dt, p99, p99_round, path = run_config(
            args.nodes, args.pods, args.wave, args.workload,
            mesh=_resolve_mesh(args.mesh), shadow=args.shadow,
            kill_device=args.kill_device)
    emit(args.name or args.workload, args.nodes, args.pods, placed, dt, p99,
         p99_round, args.wave, path)


if __name__ == "__main__":
    main()
