"""kubernetes_tpu — a TPU-native cluster scheduling framework.

A ground-up redesign of the Kubernetes control-plane scheduling stack
(reference: kubernetes v1.11-dev) built on JAX/XLA: cluster state is
mirrored into HBM as dense tensors and the scheduler's Filter+Score
pipeline runs as a single batched (pending-pods x nodes) computation,
while the behavioral contracts of the reference (priority queue,
assume/bind pipeline, preemption, extension points) are kept host-side.

Layout:
  api/      -- object model: Pod, Node, labels/selectors, quantities
               (analog of staging/src/k8s.io/api + apimachinery)
  state/    -- scheduler cache, NodeInfo, vocab interning, tensor snapshot
               (analog of pkg/scheduler/schedulercache)
  ops/      -- batched filter (predicate) and score (priority) kernels
               (analog of pkg/scheduler/algorithm/{predicates,priorities})
  sched/    -- scheduling queue, scheduler loop, preemption, binding
               (analog of pkg/scheduler/{core,scheduler.go})
  plugins/  -- extension-point registry, default profiles, extenders
               (analog of pkg/scheduler/{factory/plugins.go,algorithmprovider})
  runtime/  -- in-process object store, watch, informers, workqueues
               (analog of client-go + the apiserver edge)
  parallel/ -- device mesh / pjit sharding of the (pods x nodes) compute
  utils/    -- tracing, metrics, feature gates, backoff
"""

__version__ = "0.1.0"
