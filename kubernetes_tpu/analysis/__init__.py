"""ktpu-lint: invariant-enforcing static analysis for the scheduling plane.

The reference leans on `go vet` and `go test -race` as standing
correctness infrastructure (SURVEY §5). This package is the
reproduction's analog: an AST-based rule engine that machine-checks the
invariants this codebase has earned the hard way, instead of trusting
review to remember them:

  jit-purity       no fault points, metrics, clocks, logging, or self-
                   mutation inside functions reachable from a jax.jit /
                   lax.scan boundary in ops/ (the PR 2 rule: a fire()
                   inside a jitted body only runs at trace time, so
                   injected faults silently vanish once the compile
                   cache warms)
  determinism      no iteration over set-typed values in scheduling-
                   order-sensitive packages (the PR 8 bug: gang members
                   in a set made placements vary run-to-run with the
                   uid hash seed)
  twin-coverage    every public device kernel has a numpy host twin in
                   ops/hostwave.py and a parity test naming both (the
                   degraded path must never silently lose coverage)
  f32-reduction    raw jnp.sum/np.sum over f32 planes in ops/ must use
                   the _pairwise_sum fixed halving tree so numpy == XLA
                   == GSPMD bit-for-bit
  lock-discipline  the statically-extracted lock acquisition graph has
                   no order inversions, no blocking I/O under component
                   locks, and no device dispatch under the scheduler
                   lock from outside the scheduler (the PR 4 rule);
                   the graph is exported for the runtime LockOrderWatcher
                   superset check (tests/test_racecheck.py)
  metrics-hygiene  labeled metric families declare a bounded label set
                   (values=/open_labels= at construction) or route
                   dynamic values through utils.metrics.bounded_label
                   (the PR 9 "Other" bucketing)

Run it:

    python -m kubernetes_tpu.analysis            # whole tree, exit != 0
                                                 # on non-baselined findings
    make lint                                    # same, from the Makefile

Per-line suppression (same line or the line directly above):

    for f in list(self._inflight):  # ktpu: allow[determinism] drain-all

Grandfathered findings live in analysis/baseline.json; refresh it with
`python -m kubernetes_tpu.analysis --update-baseline` after reviewing
that every newly-baselined finding is intentional. The determinism and
jit-purity baselines are kept EMPTY by policy — findings there are
fixed, not grandfathered (tests/test_analysis.py enforces it).
"""

from .core import Baseline, Finding, Report, load_corpus, run_analysis
from .rules import ALL_RULES

__all__ = ["ALL_RULES", "Baseline", "Finding", "Report", "load_corpus",
           "run_analysis"]
