"""CLI: python -m kubernetes_tpu.analysis [paths...]

Exit 0 when every finding is suppressed or baselined; 1 otherwise.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from .core import Baseline, load_corpus, run_analysis
from .rules import ALL_RULES, RULES_BY_NAME


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="ktpu-lint",
        description="invariant-enforcing static analysis for the "
                    "device/host scheduling plane")
    ap.add_argument("paths", nargs="*",
                    help="repo-relative path prefixes to report on "
                         "(default: everything)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule names (default: all)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: "
                         "kubernetes_tpu/analysis/baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline: report every finding")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline to the current findings "
                         "(review the diff — grandfathering is debt)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in ALL_RULES:
            doc = (r.__doc__ or "").strip().split("\n")[0]
            print(f"{r.name:16s} {doc}")
        return 0

    rules = None
    if args.rules:
        names = [n.strip() for n in args.rules.split(",") if n.strip()]
        unknown = [n for n in names if n not in RULES_BY_NAME]
        if unknown:
            print(f"unknown rule(s): {', '.join(unknown)}", file=sys.stderr)
            return 2
        rules = [RULES_BY_NAME[n] for n in names]

    corpus = load_corpus()
    baseline_path = (Path(args.baseline) if args.baseline
                     else Baseline.default_path(corpus.root))
    baseline = (Baseline() if args.no_baseline
                else Baseline.load(baseline_path))
    # the baseline is a whole-tree artifact: updating it through a path
    # filter would silently drop every out-of-path entry
    paths = () if args.update_baseline else tuple(args.paths)
    if args.update_baseline and args.paths:
        print("note: path filters are ignored with --update-baseline "
              "(the baseline always covers the whole tree)",
              file=sys.stderr)
    report = run_analysis(rules=rules, baseline=baseline,
                          paths=paths, corpus=corpus)

    if args.update_baseline:
        # entries for rules that did not run this invocation are kept
        # verbatim — a --rules filter refreshes only its own rules
        kept = [e for e in baseline.entries
                if e["rule"] not in set(report.rules_run)]
        fresh = Baseline.from_findings(report.new + report.baselined)
        Baseline(kept + fresh.entries).save(baseline_path)
        print(f"baseline updated: {baseline_path} "
              f"({len(kept) + len(fresh.entries)} entries)")
        return 0

    if args.as_json:
        print(json.dumps({
            "new": [vars(f) for f in report.new],
            "baselined": [vars(f) for f in report.baselined],
            "suppressed": [vars(f) for f in report.suppressed],
            "stale_baseline": report.stale_baseline,
            "rules": report.rules_run,
        }, indent=2))
    else:
        for f in report.new:
            print(f.render())
            print(f"    {f.snippet}")
        print(f"ktpu-lint: {report.summary()}")
        if report.new:
            print("    (suppress a reviewed exemption with "
                  "`# ktpu: allow[<rule>] <reason>` on the line or the "
                  "line above)")
    return 0 if report.ok() else 1


if __name__ == "__main__":
    sys.exit(main())
