"""Rule engine core: corpus loading, suppressions, baseline, runner.

Design notes:

  * Findings are matched against the baseline by (rule, path, snippet) —
    the stripped source line — NOT by line number, so unrelated edits
    above a grandfathered finding don't resurrect it. Matching is
    multiset one-to-one: a second identical line is a NEW finding.
  * Suppressions are per-line comments `# ktpu: allow[rule]` (comma list
    or `all`), honored on the finding's line or the line directly above
    it. A suppression is an acknowledged, reviewed exemption; the
    baseline is unreviewed debt — keep the distinction.
"""

from __future__ import annotations

import ast
import json
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

SUPPRESS_RE = re.compile(r"#\s*ktpu:\s*allow\[([a-z0-9_,\- ]+)\]")


def repo_root() -> Path:
    """The directory holding the kubernetes_tpu package (and tests/)."""
    return Path(__file__).resolve().parents[2]


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-relative, forward slashes
    line: int
    message: str
    snippet: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.snippet)


class SourceFile:
    """One parsed python file plus its suppression map."""

    def __init__(self, path: Path, relpath: str):
        self.path = path
        self.relpath = relpath
        self.source = path.read_text()
        self.lines = self.source.splitlines()
        self.tree = ast.parse(self.source, filename=str(path))
        # line -> set of suppressed rule names ('all' wildcards)
        self.suppressions: Dict[int, set] = {}
        for i, text in enumerate(self.lines, start=1):
            m = SUPPRESS_RE.search(text)
            if m:
                rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
                self.suppressions[i] = rules

    def snippet(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def suppressed(self, rule: str, line: int) -> bool:
        for ln in (line, line - 1):
            rules = self.suppressions.get(ln)
            if rules and (rule in rules or "all" in rules):
                return True
        return False

    def finding(self, rule: str, node_or_line, message: str) -> Finding:
        line = getattr(node_or_line, "lineno", node_or_line)
        return Finding(rule=rule, path=self.relpath, line=line,
                       message=message, snippet=self.snippet(line))


class Corpus:
    """Every analyzable file, keyed by repo-relative path."""

    def __init__(self, root: Path):
        self.root = root
        self.files: Dict[str, SourceFile] = {}
        # raw text of tests/*.py for rules that check test coverage
        self.test_texts: Dict[str, str] = {}

    def under(self, prefix: str) -> List[SourceFile]:
        return [sf for rel, sf in sorted(self.files.items())
                if rel.startswith(prefix)]


def load_corpus(root: Optional[Path] = None) -> Corpus:
    root = root or repo_root()
    corpus = Corpus(root)
    pkg = root / "kubernetes_tpu"
    for path in sorted(pkg.rglob("*.py")):
        rel = path.relative_to(root).as_posix()
        try:
            corpus.files[rel] = SourceFile(path, rel)
        except SyntaxError as e:  # a broken file is itself a finding
            raise SystemExit(f"ktpu-lint: cannot parse {rel}: {e}")
    tests = root / "tests"
    if tests.is_dir():
        for path in sorted(tests.glob("*.py")):
            corpus.test_texts[path.name] = path.read_text()
    return corpus


class Baseline:
    """Checked-in multiset of grandfathered findings."""

    def __init__(self, entries: Sequence[dict] = ()):
        self.entries: List[dict] = list(entries)

    @staticmethod
    def default_path(root: Optional[Path] = None) -> Path:
        return (root or repo_root()) / "kubernetes_tpu" / "analysis" / \
            "baseline.json"

    @classmethod
    def load(cls, path: Optional[Path] = None) -> "Baseline":
        path = path or cls.default_path()
        if not Path(path).exists():
            return cls()
        data = json.loads(Path(path).read_text())
        return cls(data.get("entries", []))

    def save(self, path: Optional[Path] = None) -> None:
        path = path or self.default_path()
        data = {"version": 1,
                "comment": "grandfathered ktpu-lint findings; regenerate "
                           "with python -m kubernetes_tpu.analysis "
                           "--update-baseline",
                "entries": self.entries}
        Path(path).write_text(json.dumps(data, indent=2, sort_keys=False)
                              + "\n")

    @staticmethod
    def from_findings(findings: Sequence[Finding]) -> "Baseline":
        return Baseline([
            {"rule": f.rule, "path": f.path, "snippet": f.snippet}
            for f in sorted(findings, key=lambda f: (f.rule, f.path, f.line))
        ])

    def split(self, findings: Sequence[Finding]
              ) -> Tuple[List[Finding], List[Finding], List[dict]]:
        """(new, baselined, stale_entries) — one-to-one multiset match."""
        budget: Dict[Tuple[str, str, str], int] = {}
        for e in self.entries:
            k = (e["rule"], e["path"], e["snippet"])
            budget[k] = budget.get(k, 0) + 1
        new: List[Finding] = []
        matched: List[Finding] = []
        for f in findings:
            k = f.key()
            if budget.get(k, 0) > 0:
                budget[k] -= 1
                matched.append(f)
            else:
                new.append(f)
        stale = []
        for e in self.entries:
            k = (e["rule"], e["path"], e["snippet"])
            if budget.get(k, 0) > 0:
                budget[k] -= 1
                stale.append(e)
        return new, matched, stale


@dataclass
class Report:
    new: List[Finding] = field(default_factory=list)
    baselined: List[Finding] = field(default_factory=list)
    suppressed: List[Finding] = field(default_factory=list)
    stale_baseline: List[dict] = field(default_factory=list)
    rules_run: List[str] = field(default_factory=list)

    @property
    def all_findings(self) -> List[Finding]:
        return self.new + self.baselined + self.suppressed

    def ok(self) -> bool:
        return not self.new

    def summary(self) -> str:
        per_rule: Dict[str, int] = {}
        for f in self.new:
            per_rule[f.rule] = per_rule.get(f.rule, 0) + 1
        parts = [f"{len(self.new)} finding(s)"]
        if per_rule:
            parts.append("(" + ", ".join(
                f"{r}: {n}" for r, n in sorted(per_rule.items())) + ")")
        parts.append(f"{len(self.baselined)} baselined")
        parts.append(f"{len(self.suppressed)} suppressed")
        if self.stale_baseline:
            parts.append(f"{len(self.stale_baseline)} stale baseline "
                         "entr(y/ies) — run --update-baseline")
        return ", ".join(parts)


def run_analysis(root: Optional[Path] = None,
                 rules: Optional[Sequence] = None,
                 baseline: Optional[Baseline] = None,
                 paths: Sequence[str] = (),
                 corpus: Optional[Corpus] = None) -> Report:
    """Run `rules` (default: all) over the tree; classify findings
    against suppressions and the baseline. `paths` filters findings to
    repo-relative prefixes (the corpus is always loaded whole — cross-
    file rules need it)."""
    from .rules import ALL_RULES

    corpus = corpus or load_corpus(root)
    rules = list(rules) if rules is not None else list(ALL_RULES)
    baseline = baseline if baseline is not None else Baseline.load(
        Baseline.default_path(corpus.root))
    findings: List[Finding] = []
    for rule in rules:
        findings.extend(rule.run(corpus))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    live: List[Finding] = []
    suppressed: List[Finding] = []
    for f in findings:
        sf = corpus.files.get(f.path)
        if sf is not None and sf.suppressed(f.rule, f.line):
            suppressed.append(f)
        else:
            live.append(f)
    # classify against the baseline over the WHOLE tree, then filter for
    # reporting — a path filter must never make out-of-path baseline
    # entries look stale (they'd get dropped on --update-baseline)
    new, baselined, stale = baseline.split(live)
    if paths:
        def within(fs):
            return [f for f in fs
                    if any(f.path.startswith(p) for p in paths)]
        new, baselined, suppressed = (within(new), within(baselined),
                                      within(suppressed))
    return Report(new=new, baselined=baselined, suppressed=suppressed,
                  stale_baseline=stale,
                  rules_run=[r.name for r in rules])
