"""Static extraction of the lock acquisition graph.

Scans sched/, state/, client/ (and the cluster autoscaler, which takes
the scheduler's lock) for:

  * lock attributes: `self.x = threading.Lock()/RLock()/Condition()`
    (including `lock or threading.RLock()` default patterns), named
    `Class.attr` — e.g. `Scheduler._mu`, `SchedulingQueue._lock`;
  * component typing: `self.queue = SchedulingQueue(...)` in a method
    body types `self.queue`, so `self.queue.push()` resolves to
    `SchedulingQueue.push`;
  * per-method acquired-lock sets, closed transitively over resolvable
    calls (self.m(), self.<typed attr>.m(), <typed local>.m());
  * edges (A, B): lock B is acquired (directly or via a resolved call)
    inside a `with`/acquire() region holding lock A.

The runtime LockOrderWatcher (utils/racecheck.py), when enabled via the
scheduler's `racecheck=True` / `--racecheck`, instruments the same locks
under the same `Class.attr` names — tests/test_racecheck.py asserts the
edges it observes under live traffic are a SUBSET of this static graph,
so the static analysis provably covers what runtime race checking can
see (and keeps seeing paths tests didn't happen to exercise).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import Corpus, SourceFile
from .rules import dotted

SCOPES = ("kubernetes_tpu/sched/", "kubernetes_tpu/state/",
          "kubernetes_tpu/client/",
          "kubernetes_tpu/controllers/clusterautoscaler.py")

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore"}


def _is_lock_ctor(node: ast.AST) -> bool:
    if isinstance(node, ast.Call):
        name = (dotted(node.func) or "").split(".")[-1]
        return name in _LOCK_CTORS
    if isinstance(node, ast.BoolOp):  # lock or threading.RLock()
        return any(_is_lock_ctor(v) for v in node.values)
    return False


class LockGraph:
    def __init__(self):
        # (lock_a, lock_b) -> [(SourceFile, line), ...] where b is taken
        # while a is held
        self.edges: Dict[Tuple[str, str], List[Tuple[SourceFile, int]]] = {}
        # every call made lexically under a lock: (file, line, lock, call)
        self.calls_under_locks: List[Tuple[SourceFile, int, str, str]] = []
        # classes whose methods hold each lock natively
        self.lock_owners: Dict[str, str] = {}  # "Scheduler._mu" -> "Scheduler"
        self._scheduler_spans: List[Tuple[str, int, int]] = []

    def edge_set(self) -> Set[Tuple[str, str]]:
        return set(self.edges.keys())

    def add_edge(self, a: str, b: str, sf: SourceFile, line: int):
        self.edges.setdefault((a, b), []).append((sf, line))

    def site_in_scheduler(self, sf: SourceFile, line: int) -> bool:
        for rel, lo, hi in self._scheduler_spans:
            if rel == sf.relpath and lo <= line <= hi:
                return True
        return False


class _ClassInfo:
    def __init__(self, sf: SourceFile, node: ast.ClassDef):
        self.sf = sf
        self.node = node
        self.name = node.name
        self.lock_attrs: Set[str] = set()
        self.typed_attrs: Dict[str, str] = {}  # attr -> class name
        self.methods: Dict[str, ast.FunctionDef] = {}
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.methods[item.name] = item
        for item in ast.walk(node):
            if isinstance(item, ast.Assign) and len(item.targets) == 1:
                t = item.targets[0]
                if isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and t.value.id == "self":
                    value = item.value
                    if isinstance(value, ast.IfExp):
                        # self.ecache = (EquivalenceCache() if ... else None)
                        value = (value.body if isinstance(value.body, ast.Call)
                                 else value.orelse)
                    if _is_lock_ctor(item.value):
                        self.lock_attrs.add(t.attr)
                    elif isinstance(value, ast.Call):
                        ctor = (dotted(value.func) or "").split(".")[-1]
                        self.typed_attrs[t.attr] = ctor

    def lock_id(self, attr: str) -> str:
        return f"{self.name}.{attr}"


def extract_lock_graph(corpus: Corpus) -> LockGraph:
    classes: Dict[str, _ClassInfo] = {}
    for scope in SCOPES:
        for sf in corpus.under(scope):
            for node in sf.tree.body:
                if isinstance(node, ast.ClassDef):
                    info = _ClassInfo(sf, node)
                    classes[info.name] = info
    graph = LockGraph()
    for info in classes.values():
        for attr in info.lock_attrs:
            graph.lock_owners[info.lock_id(attr)] = info.name
        if info.name == "Scheduler":
            end = max((n.lineno for n in ast.walk(info.node)
                       if hasattr(n, "lineno")), default=info.node.lineno)
            graph._scheduler_spans.append(
                (info.sf.relpath, info.node.lineno, end))

    # unique lock-attr names let `sched._mu` resolve without type info
    attr_counts: Dict[str, List[str]] = {}
    for info in classes.values():
        for attr in info.lock_attrs:
            attr_counts.setdefault(attr, []).append(info.lock_id(attr))
    unique_attr = {a: ids[0] for a, ids in attr_counts.items()
                   if len(ids) == 1}

    resolver = _Resolver(classes, unique_attr)
    acquires = _method_acquire_fixpoint(classes, resolver)
    for info in classes.values():
        for mname, method in info.methods.items():
            _walk_method(graph, info, method, resolver, acquires)
    return graph


class _Resolver:
    def __init__(self, classes: Dict[str, _ClassInfo],
                 unique_attr: Dict[str, str]):
        self.classes = classes
        self.unique_attr = unique_attr

    def lock_of_expr(self, info: _ClassInfo, expr: ast.AST,
                     local_types: Dict[str, str]) -> Optional[str]:
        """Resolve a with-context / acquire() receiver to a lock id."""
        name = dotted(expr)
        if name is None:
            return None
        parts = name.split(".")
        if len(parts) == 2 and parts[0] == "self":
            if parts[1] in info.lock_attrs:
                return info.lock_id(parts[1])
            return None
        if len(parts) == 3 and parts[0] == "self":
            comp = self.classes.get(
                local_types.get(parts[1])
                or info.typed_attrs.get(parts[1], ""))
            if comp and parts[2] in comp.lock_attrs:
                return comp.lock_id(parts[2])
        if len(parts) >= 2:
            # typed local (`sched = self.scheduler` has no type) — fall
            # back to globally-unique lock attr names
            attr = parts[-1]
            cname = local_types.get(parts[0])
            comp = self.classes.get(cname or "")
            if comp and attr in comp.lock_attrs:
                return comp.lock_id(attr)
            return self.unique_attr.get(attr)
        return None

    def method_of_call(self, info: _ClassInfo, call: ast.Call,
                       local_types: Dict[str, str]
                       ) -> Optional[Tuple[str, str]]:
        """Resolve a call to ('Class', 'method') when possible."""
        name = dotted(call.func)
        if name is None:
            return None
        parts = name.split(".")
        if len(parts) == 2 and parts[0] == "self":
            if parts[1] in info.methods:
                return (info.name, parts[1])
            comp = self.classes.get(info.typed_attrs.get(parts[1], ""))
            if comp is not None and "__call__" in comp.methods:
                return (comp.name, "__call__")
            return None
        if len(parts) == 3 and parts[0] == "self":
            comp = self.classes.get(info.typed_attrs.get(parts[1], ""))
            if comp and parts[2] in comp.methods:
                return (comp.name, parts[2])
            return None
        if len(parts) == 2:
            comp = self.classes.get(local_types.get(parts[0], ""))
            if comp and parts[1] in comp.methods:
                return (comp.name, parts[1])
        return None


def _local_types(info: _ClassInfo, method) -> Dict[str, str]:
    """`sched = self.scheduler` style aliases: local name -> class name,
    via the enclosing class's typed attrs or direct constructions."""
    out: Dict[str, str] = {}
    for node in ast.walk(method):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            tgt = node.targets[0].id
            src = dotted(node.value)
            if src and src.startswith("self.") and src.count(".") == 1:
                attr = src.split(".")[1]
                if attr in info.typed_attrs:
                    out[tgt] = info.typed_attrs[attr]
            elif isinstance(node.value, ast.Call):
                ctor = (dotted(node.value.func) or "").split(".")[-1]
                out[tgt] = ctor
    return out


def _method_acquire_fixpoint(classes: Dict[str, _ClassInfo],
                             resolver: _Resolver
                             ) -> Dict[Tuple[str, str], Set[str]]:
    """(class, method) -> every lock the method may acquire, transitively
    over resolvable calls."""
    acquires: Dict[Tuple[str, str], Set[str]] = {}
    direct: Dict[Tuple[str, str], Set[str]] = {}
    callees: Dict[Tuple[str, str], Set[Tuple[str, str]]] = {}
    for info in classes.values():
        for mname, method in info.methods.items():
            key = (info.name, mname)
            locks: Set[str] = set()
            calls: Set[Tuple[str, str]] = set()
            ltypes = _local_types(info, method)
            for node in ast.walk(method):
                if isinstance(node, ast.With):
                    for item in node.items:
                        lk = resolver.lock_of_expr(info, item.context_expr,
                                                   ltypes)
                        if lk:
                            locks.add(lk)
                elif isinstance(node, ast.Call):
                    name = dotted(node.func) or ""
                    if name.endswith(".acquire"):
                        lk = resolver.lock_of_expr(
                            info, node.func.value, ltypes)
                        if lk:
                            locks.add(lk)
                    else:
                        m = resolver.method_of_call(info, node, ltypes)
                        if m:
                            calls.add(m)
            direct[key] = locks
            callees[key] = calls
            acquires[key] = set(locks)
    for _ in range(len(acquires)):
        grew = False
        for key, locks in acquires.items():
            for callee in callees.get(key, ()):
                extra = acquires.get(callee, set()) - locks
                if extra:
                    locks.update(extra)
                    grew = True
        if not grew:
            break
    return acquires


def _walk_method(graph: LockGraph, info: _ClassInfo, method,
                 resolver: _Resolver, acquires) -> None:
    ltypes = _local_types(info, method)

    def visit(node: ast.AST, held: Tuple[str, ...]):
        if isinstance(node, ast.With):
            new_locks = []
            for item in node.items:
                lk = resolver.lock_of_expr(info, item.context_expr, ltypes)
                if lk:
                    # earlier items of the SAME `with a, b:` statement
                    # are already held when b is acquired — they form
                    # edges too, exactly like lexical nesting
                    for h in held + tuple(new_locks):
                        if h != lk:
                            graph.add_edge(h, lk, info.sf, node.lineno)
                    new_locks.append(lk)
            inner = held + tuple(new_locks)
            for child in node.body:
                visit(child, inner)
            return
        if isinstance(node, ast.Call):
            name = dotted(node.func)
            if name and held:
                for h in held:
                    graph.calls_under_locks.append(
                        (info.sf, node.lineno, h, name))
                m = resolver.method_of_call(info, node, ltypes)
                if m:
                    for lk in acquires.get(m, ()):
                        for h in held:
                            if h != lk:
                                graph.add_edge(h, lk, info.sf, node.lineno)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested defs (callbacks) execute later, not under the lock
            return
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    for stmt in method.body:
        visit(stmt, ())


def static_lock_graph(corpus: Optional[Corpus] = None) -> Set[Tuple[str, str]]:
    """The edge set, for the runtime-superset assertion in tests."""
    from .core import load_corpus

    return extract_lock_graph(corpus or load_corpus()).edge_set()
