"""The rule set. Each rule is grounded in a bug class this repo hit:

  jit-purity       PR 2: fault points must stay outside the jit boundary
  determinism      PR 8: gang members in a `set` made placements vary
                   run-to-run with the uid hash seed
  twin-coverage    PR 7: the degraded path is only as good as the twin
  f32-reduction    PR 9: f32 sums must associate identically on numpy,
                   XLA and GSPMD (_pairwise_sum halving tree)
  lock-discipline  PR 4: no device dispatch under the scheduler lock
                   from outside the scheduler; no blocking I/O under
                   component locks; no static lock-order inversions
  metrics-hygiene  PR 9: labeled metrics declare a bounded label set or
                   bucket free text into "Other" (utils.metrics.bounded_label)
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .core import Corpus, Finding, SourceFile


def dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def walk_skipping_nested_functions(body: Iterable[ast.AST]):
    """Walk statements without descending into nested function/class
    defs (their bodies are separate scopes)."""
    stack = list(body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


# ---------------------------------------------------------------------------
# jit-purity
# ---------------------------------------------------------------------------


class JitPurityRule:
    """Functions reachable from a jax.jit / pallas boundary in ops/ must
    be pure tracers: no fault points (fire() only runs at trace time and
    silently stops firing once the compile cache warms — the PR 2 bug),
    no metrics/tracing/logging/print, no wall clocks or RNG, no file
    I/O, no mutation of `self`."""

    name = "jit-purity"
    SCOPE = "kubernetes_tpu/ops/"

    def run(self, corpus: Corpus) -> List[Finding]:
        modules = {}
        for sf in corpus.under(self.SCOPE):
            modules[_module_key(sf)] = _OpsModule(sf)
        findings: List[Finding] = []
        roots: List[Tuple[_OpsModule, ast.AST]] = []
        for mod in modules.values():
            roots.extend((mod, fn) for fn in mod.jit_roots)
        seen: Set[Tuple[str, int]] = set()
        queue = list(roots)
        while queue:
            mod, fn = queue.pop()
            key = (mod.sf.relpath, fn.lineno)
            if key in seen:
                continue
            seen.add(key)
            findings.extend(self._check_body(mod, fn))
            for callee_mod, callee in mod.resolve_calls(fn, modules):
                queue.append((callee_mod, callee))
        return findings

    def _check_body(self, mod: "_OpsModule", fn) -> List[Finding]:
        out: List[Finding] = []
        sf = mod.sf

        def bad(node, what):
            out.append(sf.finding(
                self.name, node,
                f"{what} inside the jit boundary (reachable from a "
                f"jax.jit/lax.scan root; hoist it to the host-side entry "
                f"wrapper like ops/kernel.py schedule_wave)"))

        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                name = dotted(node.func)
                if name is None:
                    continue
                root = name.split(".")[0]
                if name.endswith(".fire") or name == "fire":
                    if mod.names_module(root, "faultpoints") or name == "fire":
                        bad(node, f"fault point `{name}(...)`")
                elif mod.names_module(root, "time"):
                    bad(node, f"wall-clock call `{name}(...)`")
                elif root == "random" or \
                        name.startswith(("np.random.", "numpy.random.")):
                    # stdlib/numpy RNG draws fresh state at trace time
                    # only; jax.random is the trace-pure functional PRNG
                    # and is deliberately NOT flagged
                    bad(node, f"RNG call `{name}(...)`")
                elif name == "print":
                    bad(node, "print(...)")
                elif name == "open":
                    bad(node, "file I/O `open(...)`")
                elif mod.names_module(root, "logging") or \
                        mod.names_module(root, "tracing"):
                    bad(node, f"host-side call `{name}(...)`")
                elif isinstance(node.func, ast.Attribute) and \
                        node.func.attr in ("inc", "observe", "labels"):
                    recv = dotted(node.func.value) or "<expr>"
                    bad(node, f"metric call `{recv}.{node.func.attr}(...)`")
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == "self":
                        bad(node, f"mutation of `self.{t.attr}`")
            elif isinstance(node, ast.Global):
                bad(node, "global statement (trace-time-only side effect)")
        return out


def _module_key(sf: SourceFile) -> str:
    # 'kubernetes_tpu/ops/kernel.py' -> 'kernel'
    return sf.relpath.rsplit("/", 1)[-1][:-3]


class _OpsModule:
    """Symbol/import index of one ops/ module for the purity walk."""

    def __init__(self, sf: SourceFile):
        self.sf = sf
        self.functions: Dict[str, ast.AST] = {}
        for node in sf.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = node
        # alias -> module key it names (both `from . import encoding as
        # enc` and `from ..utils import faultpoints` land here), and
        # name -> (modkey, origname) for `from .filters import resource_fit`
        self.module_aliases: Dict[str, str] = {}
        self.from_imports: Dict[str, Tuple[str, str]] = {}
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.module_aliases[a.asname or a.name.split(".")[0]] = \
                        a.name.split(".")[-1] if a.asname else \
                        a.name.split(".")[0]
            elif isinstance(node, ast.ImportFrom):
                modkey = (node.module or "").split(".")[-1]
                for a in node.names:
                    if node.module is None and node.level:
                        # from . import encoding as enc
                        self.module_aliases[a.asname or a.name] = a.name
                    else:
                        self.from_imports[a.asname or a.name] = \
                            (modkey, a.name)
                        # `from ..utils import faultpoints` imports a
                        # MODULE through ImportFrom — record the alias too
                        self.module_aliases.setdefault(a.asname or a.name,
                                                       a.name)
        self.jit_roots = self._find_jit_roots()

    def names_module(self, alias: str, modname: str) -> bool:
        return self.module_aliases.get(alias) == modname

    def _find_jit_roots(self) -> List[ast.AST]:
        roots: List[ast.AST] = []
        jitted_names: Set[str] = set()
        for node in ast.walk(self.sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if self._is_jit_expr(dec):
                        roots.append(node)
                        break
            elif isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call) and \
                    self._is_jit_expr(node.value.func):
                # f = jax.jit(g)
                for arg in node.value.args[:1]:
                    name = dotted(arg)
                    if name:
                        jitted_names.add(name.split(".")[-1])
        for name in jitted_names:
            if name in self.functions:
                roots.append(self.functions[name])
        return roots

    def _is_jit_expr(self, node: ast.AST) -> bool:
        name = dotted(node)
        if name in ("jax.jit", "jit", "pallas_call", "pl.pallas_call"):
            return True
        if isinstance(node, ast.Call):
            fname = dotted(node.func)
            if fname in ("functools.partial", "partial"):
                return any(self._is_jit_expr(a) for a in node.args)
            return self._is_jit_expr(node.func)
        return False

    def resolve_calls(self, fn, modules: Dict[str, "_OpsModule"]
                      ) -> List[Tuple["_OpsModule", ast.AST]]:
        """Callees of `fn` that resolve to functions in the ops corpus
        (same module by name, cross-module via from-imports / module
        aliases)."""
        out: List[Tuple[_OpsModule, ast.AST]] = []
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func)
            if name is None:
                continue
            if "." not in name:
                if name in self.functions and self.functions[name] is not fn:
                    out.append((self, self.functions[name]))
                elif name in self.from_imports:
                    modkey, orig = self.from_imports[name]
                    target = modules.get(modkey)
                    if target and orig in target.functions:
                        out.append((target, target.functions[orig]))
            else:
                root, attr = name.split(".")[0], name.split(".")[-1]
                modkey = self.module_aliases.get(root)
                target = modules.get(modkey) if modkey else None
                if target and attr in target.functions:
                    out.append((target, target.functions[attr]))
        return out


# ---------------------------------------------------------------------------
# determinism
# ---------------------------------------------------------------------------


class DeterminismRule:
    """No iteration over set-typed values on scheduling-order-sensitive
    paths (sched/, state/, controllers/, server/). Python string hashes
    are randomized per process, so set order is not even stable
    run-to-run — the PR 8 bug class. Order-insensitive consumers
    (len/any/all/min/max/sum/sorted/set-to-set) are fine; `for` loops,
    list()/tuple() materialization, and join() are not."""

    name = "determinism"
    SCOPES = ("kubernetes_tpu/sched/", "kubernetes_tpu/state/",
              "kubernetes_tpu/controllers/", "kubernetes_tpu/server/")
    ORDER_FREE_CALLS = {"len", "any", "all", "min", "max", "sum", "sorted",
                        "set", "frozenset", "bool"}
    MATERIALIZERS = {"list", "tuple", "enumerate", "iter"}
    SET_METHODS = {"union", "difference", "intersection",
                   "symmetric_difference", "copy"}

    def run(self, corpus: Corpus) -> List[Finding]:
        findings: List[Finding] = []
        for scope in self.SCOPES:
            for sf in corpus.under(scope):
                findings.extend(self._check_file(sf))
        return findings

    def _check_file(self, sf: SourceFile) -> List[Finding]:
        out: List[Finding] = []
        for cls, fn in _functions_with_class(sf.tree):
            set_attrs = _set_attributes(cls) if cls is not None else set()
            local_sets = self._local_sets(fn, set_attrs)
            env = (local_sets, set_attrs)
            for node in walk_skipping_nested_functions(fn.body):
                self._check_node(sf, node, env, out)
        return out

    def _local_sets(self, fn, set_attrs: Set[str]) -> Set[str]:
        """Names assigned a set-typed expression anywhere in `fn`
        (fixpoint so chains like a = set(); b = a propagate)."""
        local: Set[str] = set()
        for _ in range(4):
            grew = False
            for node in walk_skipping_nested_functions(fn.body):
                if isinstance(node, ast.Assign):
                    if self._is_set(node.value, (local, set_attrs)):
                        for t in node.targets:
                            if isinstance(t, ast.Name) and t.id not in local:
                                local.add(t.id)
                                grew = True
                elif isinstance(node, ast.AnnAssign) and node.value is not None:
                    ann = dotted(node.annotation) or ""
                    if (self._is_set(node.value, (local, set_attrs))
                            or ann.split(".")[-1] in ("set", "Set",
                                                      "FrozenSet")) and \
                            isinstance(node.target, ast.Name) and \
                            node.target.id not in local:
                        local.add(node.target.id)
                        grew = True
            if not grew:
                break
        return local

    def _is_set(self, node: ast.AST, env) -> bool:
        local_sets, set_attrs = env
        if isinstance(node, (ast.Set, ast.SetComp)):
            return True
        if isinstance(node, ast.Call):
            name = dotted(node.func)
            if name in ("set", "frozenset"):
                return True
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr in self.SET_METHODS:
                return self._is_set(node.func.value, env)
            return False
        if isinstance(node, ast.BinOp) and isinstance(
                node.op, (ast.BitOr, ast.BitAnd, ast.Sub, ast.BitXor)):
            return self._is_set(node.left, env) or \
                self._is_set(node.right, env)
        if isinstance(node, ast.Name):
            return node.id in local_sets
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and node.value.id == "self":
            return node.attr in set_attrs
        if isinstance(node, ast.IfExp):
            return self._is_set(node.body, env) or \
                self._is_set(node.orelse, env)
        return False

    def _describe(self, node: ast.AST) -> str:
        name = dotted(node)
        if name:
            return f"`{name}`"
        if isinstance(node, ast.SetComp):
            return "a set comprehension"
        if isinstance(node, ast.BinOp):
            return "a set expression"
        if isinstance(node, ast.Call):
            return f"`{dotted(node.func) or 'set'}(...)`"
        return "a set"

    def _check_node(self, sf: SourceFile, node: ast.AST, env, out):
        msg = ("iterates %s in unstable hash order — scheduling-order-"
               "sensitive paths must sort or use a dict-as-ordered-set "
               "(the PR 8 gang-members bug class)")
        if isinstance(node, (ast.For, ast.AsyncFor)):
            if self._is_set(node.iter, env):
                out.append(sf.finding(self.name, node,
                                      msg % self._describe(node.iter)))
        elif isinstance(node, (ast.ListComp, ast.DictComp, ast.GeneratorExp)):
            # SetComp over a set stays order-free and is exempt
            for gen in node.generators:
                if self._is_set(gen.iter, env):
                    out.append(sf.finding(self.name, node,
                                          msg % self._describe(gen.iter)))
        elif isinstance(node, ast.Call):
            name = dotted(node.func)
            if name in self.MATERIALIZERS and node.args and \
                    self._is_set(node.args[0], env):
                out.append(sf.finding(
                    self.name, node,
                    f"`{name}()` materializes {self._describe(node.args[0])} "
                    f"in unstable hash order — wrap in sorted() or keep it "
                    f"a set"))
            elif isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "join" and node.args and \
                    self._is_set(node.args[0], env):
                out.append(sf.finding(
                    self.name, node,
                    f"join() over {self._describe(node.args[0])} renders in "
                    f"unstable hash order — sort first"))


def _functions_with_class(tree: ast.Module):
    """Yield (enclosing ClassDef or None, FunctionDef) pairs, including
    methods and module-level functions (nested defs are visited through
    their own entry)."""
    def visit(body, cls):
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield (cls, node)
                yield from visit(node.body, cls)
            elif isinstance(node, ast.ClassDef):
                yield from visit(node.body, node)
            elif hasattr(node, "body") and not isinstance(node, ast.Lambda):
                inner = list(getattr(node, "body", ()))
                inner += list(getattr(node, "orelse", ()))
                inner += list(getattr(node, "finalbody", ()))
                for h in getattr(node, "handlers", ()):
                    inner += list(h.body)
                yield from visit(inner, cls)
    yield from visit(tree.body, None)


def _set_attributes(cls: ast.ClassDef) -> Set[str]:
    """Attributes of `cls` assigned set-typed values anywhere in the
    class (self.x = set(), or `x: Set[...] = field(default_factory=set)`
    dataclass fields)."""
    attrs: Set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and _is_plain_set_expr(node.value):
            for t in node.targets:
                if isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and t.value.id == "self":
                    attrs.add(t.attr)
        elif isinstance(node, ast.AnnAssign):
            ann = dotted(node.annotation)
            base = None
            if ann:
                base = ann.split(".")[-1]
            elif isinstance(node.annotation, ast.Subscript):
                base = (dotted(node.annotation.value) or "").split(".")[-1]
            is_set_ann = base in ("Set", "FrozenSet", "set", "frozenset")
            target = node.target
            if isinstance(target, ast.Attribute) and \
                    isinstance(target.value, ast.Name) and \
                    target.value.id == "self":
                if is_set_ann or (node.value is not None
                                  and _is_plain_set_expr(node.value)):
                    attrs.add(target.attr)
            elif isinstance(target, ast.Name) and is_set_ann:
                # dataclass field at class level
                attrs.add(target.id)
    return attrs


def _is_plain_set_expr(node: ast.AST) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call) and dotted(node.func) in ("set",
                                                            "frozenset"):
        return True
    return False


# ---------------------------------------------------------------------------
# twin-coverage
# ---------------------------------------------------------------------------


class TwinCoverageRule:
    """Every public device kernel in the twinned ops modules must have a
    numpy host twin in ops/hostwave.py (same name or `<name>_host`) and
    a parity test that names both — the degraded path's coverage is a
    checked invariant, not a convention."""

    name = "twin-coverage"
    KERNEL_MODULES = ("kubernetes_tpu/ops/kernel.py",
                      "kubernetes_tpu/ops/gang.py",
                      "kubernetes_tpu/ops/preempt.py",
                      "kubernetes_tpu/ops/scores.py",
                      "kubernetes_tpu/ops/telemetry.py",
                      "kubernetes_tpu/ops/topology.py")
    HOSTWAVE = "kubernetes_tpu/ops/hostwave.py"

    def run(self, corpus: Corpus) -> List[Finding]:
        if corpus.files.get(self.HOSTWAVE) is None:
            return []
        findings: List[Finding] = []
        for sf, fn, twin in self.kernel_twins(corpus):
            if twin is None:
                findings.append(sf.finding(
                    self.name, fn,
                    f"public kernel `{fn.name}` has no host twin in "
                    f"ops/hostwave.py (expected `{fn.name}_host` or "
                    f"`{fn.name}`) — degraded mode silently loses it"))
                continue
            if not self._parity_test_exists(corpus, fn.name, twin):
                findings.append(sf.finding(
                    self.name, fn,
                    f"kernel `{fn.name}` / twin `{twin}` have no parity "
                    f"test naming both under tests/"))
        return findings

    def kernel_twins(self, corpus: Corpus
                     ) -> List[Tuple[SourceFile, ast.FunctionDef,
                                     Optional[str]]]:
        """(file, kernel fn, twin name or None) for every public kernel.
        A 'kernel' is a public module-level function that references
        jnp/lax (directly or through same-module callees) — host-side
        utilities like dispatch accounting don't need twins."""
        hostwave = corpus.files.get(self.HOSTWAVE)
        twin_names = {n.name for n in hostwave.tree.body
                      if isinstance(n, ast.FunctionDef)} if hostwave else set()
        out = []
        for rel in self.KERNEL_MODULES:
            sf = corpus.files.get(rel)
            if sf is None:
                continue
            fns = {n.name: n for n in sf.tree.body
                   if isinstance(n, ast.FunctionDef)}
            device_fns = self._device_functions(fns)
            for name, fn in sorted(fns.items()):
                if name.startswith("_") or name not in device_fns:
                    continue
                twin = None
                if f"{name}_host" in twin_names:
                    twin = f"{name}_host"
                elif name in twin_names:
                    twin = name
                out.append((sf, fn, twin))
        return out

    def _device_functions(self, fns: Dict[str, ast.FunctionDef]) -> Set[str]:
        """Fixpoint: functions textually using jnp./lax. or calling a
        same-module function that does."""
        device: Set[str] = set()
        for name, fn in fns.items():
            for node in ast.walk(fn):
                if isinstance(node, ast.Name) and node.id in ("jnp", "lax"):
                    device.add(name)
                    break
        for _ in range(len(fns)):
            grew = False
            for name, fn in fns.items():
                if name in device:
                    continue
                for node in ast.walk(fn):
                    if isinstance(node, ast.Call):
                        callee = dotted(node.func)
                        if callee in device:
                            device.add(name)
                            grew = True
                            break
            if not grew:
                break
        return device

    def _parity_test_exists(self, corpus: Corpus, kernel: str,
                            twin: str) -> bool:
        for text in corpus.test_texts.values():
            if twin == kernel:
                # same-name twin: the test must reference the name AND
                # the hostwave module explicitly
                if kernel in text and "hostwave" in text:
                    return True
            elif kernel in text and twin in text:
                return True
        return False


# ---------------------------------------------------------------------------
# f32-reduction
# ---------------------------------------------------------------------------


class F32ReductionRule:
    """Raw jnp.sum/np.sum over f32 planes in ops/ reassociate
    differently on numpy vs XLA vs GSPMD; route them through the
    _pairwise_sum fixed halving tree (ops/telemetry.py). Integer/bool
    sums are exact in any order and exempt, as are explicit f64
    accumulations (`dtype=np.float64`, rounded once — exact for the
    integer-valued planes that use them)."""

    name = "f32-reduction"
    SCOPE = "kubernetes_tpu/ops/"
    NUMPY_NAMES = {"np", "jnp", "xp", "numpy"}
    INT_DTYPES = {"int8", "int16", "int32", "int64", "uint8", "uint16",
                  "uint32", "uint64", "bool", "bool_"}

    def run(self, corpus: Corpus) -> List[Finding]:
        findings: List[Finding] = []
        for sf in corpus.under(self.SCOPE):
            for cls, fn in _functions_with_class(sf.tree):
                if fn.name == "_pairwise_sum":
                    continue
                bool_locals = self._bool_locals(fn)
                for node in walk_skipping_nested_functions(fn.body):
                    if not isinstance(node, ast.Call):
                        continue
                    name = dotted(node.func)
                    if name is None or not name.endswith(".sum"):
                        continue
                    if name.split(".")[0] not in self.NUMPY_NAMES:
                        continue
                    if self._exempt(node, bool_locals):
                        continue
                    findings.append(sf.finding(
                        self.name, node,
                        f"raw `{name}(...)` over a (possibly) f32 plane — "
                        f"route through the _pairwise_sum halving tree so "
                        f"numpy == XLA == GSPMD bit-for-bit, or cast to an "
                        f"integer dtype if the plane is integral"))
        return findings

    def _bool_locals(self, fn) -> Set[str]:
        """Names assigned integer/bool-typed expressions (fixpoint so
        `a = x > 0; b = a & y` propagates)."""
        out: Set[str] = set()
        for _ in range(3):
            grew = False
            for node in walk_skipping_nested_functions(fn.body):
                if isinstance(node, ast.Assign) and \
                        self._int_typed(node.value, out):
                    for t in node.targets:
                        if isinstance(t, ast.Name) and t.id not in out:
                            out.add(t.id)
                            grew = True
            if not grew:
                break
        return out

    def _int_typed(self, node: ast.AST, bool_locals: Set[str]) -> bool:
        """Type the EXPRESSION, not its subtree: `where(mask, f32, 0.0)`
        is f32 no matter how boolean the mask is."""
        if isinstance(node, ast.Compare):
            return True
        if isinstance(node, ast.Name):
            return node.id in bool_locals
        if isinstance(node, ast.UnaryOp) and \
                isinstance(node.op, (ast.Invert, ast.Not)):
            return self._int_typed(node.operand, bool_locals)
        if isinstance(node, ast.BoolOp):
            return all(self._int_typed(v, bool_locals) for v in node.values)
        if isinstance(node, ast.BinOp) and isinstance(
                node.op, (ast.BitAnd, ast.BitOr, ast.BitXor)):
            # numpy bitwise ops reject float operands, so one int/bool
            # side proves the whole expression integral
            return self._int_typed(node.left, bool_locals) or \
                self._int_typed(node.right, bool_locals)
        if isinstance(node, ast.Call):
            name = dotted(node.func) or ""
            short = (node.func.attr if isinstance(node.func, ast.Attribute)
                     else name.split(".")[-1])
            if name == "bool":
                return True
            if short == "astype" and node.args:
                dt = (dotted(node.args[0]) or "").split(".")[-1]
                return dt in self.INT_DTYPES
            if short == "where" and len(node.args) == 3:
                return self._int_typed(node.args[1], bool_locals) and \
                    self._int_typed(node.args[2], bool_locals)
            return False
        if isinstance(node, ast.Subscript):
            return self._int_typed(node.value, bool_locals)
        return False

    def _exempt(self, call: ast.Call, bool_locals: Set[str]) -> bool:
        for kw in call.keywords:
            if kw.arg == "dtype":
                dt = (dotted(kw.value) or "").split(".")[-1]
                # explicit f64 accumulation rounded once is the
                # documented exact-for-integer-planes pattern
                # (ops/hostwave.py module doc)
                if dt in self.INT_DTYPES or dt in ("float64", "double"):
                    return True
        if not call.args:
            return False
        return self._int_typed(call.args[0], bool_locals)


# ---------------------------------------------------------------------------
# lock-discipline
# ---------------------------------------------------------------------------


class LockDisciplineRule:
    """Three checks over the statically-extracted lock graph (see
    lockgraph.py): (a) no pair of locks is acquired in both orders
    (latent deadlock — what `go test -race`'s happens-before analysis
    would flag); (b) no blocking I/O (sleep/network/subprocess) under a
    component lock; (c) no device dispatch under the scheduler's lock
    from OUTSIDE the Scheduler (the PR 4 autoscaler rule — what-ifs
    build their shadow under `_mu` but must dispatch after release)."""

    name = "lock-discipline"

    BLOCKING = {"time.sleep", "subprocess.run", "subprocess.check_call",
                "subprocess.check_output", "subprocess.Popen",
                "urllib.request.urlopen", "urlopen", "socket.create_connection"}
    BLOCKING_ATTRS = {"request", "urlopen"}  # .request( on rest clients
    DEVICE_DISPATCH = {"schedule_wave", "schedule_round", "schedule_gang",
                       "preemption_stats", "cluster_telemetry", "zone_tally",
                       "simulate_placements", "simulate_refit",
                       "taint_ports_masks", "block_until_ready"}

    def run(self, corpus: Corpus) -> List[Finding]:
        from .lockgraph import extract_lock_graph

        graph = extract_lock_graph(corpus)
        findings: List[Finding] = []
        reported: Set[frozenset] = set()
        for (a, b), sites in sorted(graph.edges.items()):
            if (b, a) in graph.edges and a != b:
                key = frozenset((a, b))
                if key in reported:
                    continue
                reported.add(key)
                sf, line = sites[0]
                other = graph.edges[(b, a)][0]
                findings.append(sf.finding(
                    self.name, line,
                    f"lock-order inversion: `{a}` -> `{b}` here but "
                    f"`{b}` -> `{a}` at {other[0].relpath}:{other[1]} "
                    f"(potential deadlock)"))
        for sf, line, lock, call in graph.calls_under_locks:
            short = call.split(".")[-1]
            if call in self.BLOCKING or \
                    (short in self.BLOCKING_ATTRS and "." in call):
                findings.append(sf.finding(
                    self.name, line,
                    f"blocking call `{call}(...)` under `{lock}` — move "
                    f"I/O outside the lock (binds and REST calls stall "
                    f"every thread contending for it)"))
            elif short in self.DEVICE_DISPATCH and \
                    lock == "Scheduler._mu" and \
                    not graph.site_in_scheduler(sf, line):
                findings.append(sf.finding(
                    self.name, line,
                    f"device dispatch `{call}(...)` under the scheduler "
                    f"lock from outside the Scheduler — build the shadow "
                    f"under `_mu`, dispatch after release (PR 4 rule: a "
                    f"first-compile must not stall scheduling)"))
        return findings


# ---------------------------------------------------------------------------
# metrics-hygiene
# ---------------------------------------------------------------------------


class MetricsHygieneRule:
    """Label values must be statically bounded: a dynamic value minted
    per unique string (pod names, free-text errors) grows /metrics
    without bound and can break exposition parsing. A family declares
    its closed set via `values={...}` or its intentionally-open,
    pruned-on-removal labels via `open_labels=(...)` at construction;
    dynamic call-site values must come from literals, a declared set, or
    `utils.metrics.bounded_label` (the PR 9 "Other" bucketing)."""

    name = "metrics-hygiene"
    SCOPE = "kubernetes_tpu/"
    FAMILY_TYPES = {"LabeledCounter", "LabeledGauge"}

    def run(self, corpus: Corpus) -> List[Finding]:
        families = self._collect_families(corpus)
        findings: List[Finding] = []
        for sf in corpus.under(self.SCOPE):
            for cls, fn in _functions_with_class(sf.tree):
                literal_locals = self._literal_locals(fn)
                for node in walk_skipping_nested_functions(fn.body):
                    if isinstance(node, ast.Call) and \
                            isinstance(node.func, ast.Attribute) and \
                            node.func.attr == "labels":
                        self._check_site(sf, fn, node, families,
                                         literal_locals, findings)
        return findings

    def _collect_families(self, corpus: Corpus) -> Dict[str, dict]:
        """family attr name -> {'values': {label: set-or-None},
        'open': set(labels)} from every LabeledCounter/Gauge
        construction assigned to an attribute or name."""
        families: Dict[str, dict] = {}
        for sf in corpus.under(self.SCOPE):
            for node in ast.walk(sf.tree):
                if not (isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Call)):
                    continue
                ctor = (dotted(node.value.func) or "").split(".")[-1]
                if ctor not in self.FAMILY_TYPES:
                    continue
                decl = {"values": {}, "open": set(), "kind": ctor}
                for kw in node.value.keywords:
                    if kw.arg == "values" and isinstance(kw.value, ast.Dict):
                        for k, v in zip(kw.value.keys, kw.value.values):
                            if isinstance(k, ast.Constant):
                                vals = {e.value for e in ast.walk(v)
                                        if isinstance(e, ast.Constant)
                                        and isinstance(e.value, str)}
                                decl["values"][k.value] = vals
                    elif kw.arg == "open_labels":
                        decl["open"] = {e.value for e in ast.walk(kw.value)
                                        if isinstance(e, ast.Constant)
                                        and isinstance(e.value, str)}
                for t in node.targets:
                    name = dotted(t)
                    if name:
                        families[name.split(".")[-1]] = decl
        return families

    def _literal_locals(self, fn) -> Set[str]:
        """Names whose every assignment in `fn` is a string literal, an
        IfExp over such, or a bounded_label(...) call — statically
        bounded values."""
        assigned: Dict[str, bool] = {}

        def bounded_expr(v) -> bool:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                return True
            if isinstance(v, ast.IfExp):
                return bounded_expr(v.body) and bounded_expr(v.orelse)
            if isinstance(v, ast.Call):
                return (dotted(v.func) or "").split(".")[-1] == \
                    "bounded_label"
            return False

        for node in walk_skipping_nested_functions(fn.body):
            if isinstance(node, ast.Assign):
                ok = bounded_expr(node.value)
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        assigned[t.id] = assigned.get(t.id, True) and ok
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                for t in ast.walk(node.target):
                    if isinstance(t, ast.Name):
                        assigned[t.id] = False
        return {n for n, ok in assigned.items() if ok}

    def _resolve_family(self, recv: ast.AST, fn) -> Optional[str]:
        """`self.metrics.waves_total.labels(...)` -> 'waves_total';
        follows one local alias hop (`g = self.metrics.pending_pods`)."""
        name = dotted(recv)
        if name is None:
            return None
        attr = name.split(".")[-1]
        if "." in name:
            return attr
        # bare Name: find its assignment in the function
        for node in walk_skipping_nested_functions(fn.body):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == name:
                        src = dotted(node.value)
                        if src and "." in src:
                            return src.split(".")[-1]
        return attr

    def _check_site(self, sf: SourceFile, fn, call: ast.Call,
                    families: Dict[str, dict], literal_locals: Set[str],
                    findings: List[Finding]):
        family_attr = self._resolve_family(call.func.value, fn)
        decl = families.get(family_attr or "")
        if decl is None:
            return  # not a known metric family (e.g. sharding API)
        for kw in call.keywords:
            label = kw.arg
            if label is None:
                continue
            v = kw.value
            if label in decl["open"]:
                continue
            declared = decl["values"].get(label)
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                if declared is not None and v.value not in declared:
                    findings.append(sf.finding(
                        self.name, call,
                        f"label {label}={v.value!r} not in the declared "
                        f"value set of `{family_attr}` — add it to the "
                        f"family's values= declaration"))
                continue
            if isinstance(v, ast.Call) and \
                    (dotted(v.func) or "").split(".")[-1] == "bounded_label":
                continue
            if isinstance(v, ast.Name) and v.id in literal_locals:
                continue
            if declared is not None:
                # the family declares a closed set for this label —
                # labels() enforces it at runtime, so a dynamic value
                # here is bounded by construction
                continue
            findings.append(sf.finding(
                self.name, call,
                f"dynamic value for label `{label}` of `{family_attr}` — "
                f"declare the bounded set (values=/open_labels= at "
                f"construction) or bucket through "
                f"utils.metrics.bounded_label (PR 9 'Other' bucketing)"))


ALL_RULES = (JitPurityRule(), DeterminismRule(), TwinCoverageRule(),
             F32ReductionRule(), LockDisciplineRule(), MetricsHygieneRule())

RULES_BY_NAME = {r.name: r for r in ALL_RULES}
