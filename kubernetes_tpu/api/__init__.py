from . import labels, resources, types  # noqa: F401
