"""Binary wire codec — the protobuf-role serializer.

Reference: staging/src/k8s.io/apimachinery/pkg/runtime/serializer/
protobuf/protobuf.go. The reference's control plane negotiates
`application/vnd.kubernetes.protobuf` between components and etcd
because JSON (de)serialization dominates apiserver CPU at 5k-node
scale. This module plays that role for the framework: a compact,
self-describing tag-length-value encoding over the same dataclass
object model the JSON codec (scheme.py) serves, negotiated via the
`application/vnd.ktpu.binary` media type (server/apiserver.py) and
usable as the native store's storage encoding.

Wire format (little-endian):
  frame   := MAGIC(4) | kind_str | value
  value   := NONE | TRUE | FALSE
           | INT   varint(zigzag)
           | FLOAT f64
           | STR/BYTES varint(len) payload
           | LIST  varint(n) value*
           | MAP   varint(n) (value value)*
Objects are encoded through scheme.encode/decode (camelCase maps), so
anything the JSON codec round-trips, this codec round-trips — including
CRD-defined kinds. The payload is ~20% smaller than JSON on typical
List responses (bandwidth, not CPU, is what it buys: the pure-Python
encoder does not outrun CPython's C-accelerated json; a C extension
here is the obvious next step if codec CPU ever dominates a profile the
way protobuf-vs-JSON did for the reference apiserver).
"""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Tuple

from . import scheme

MAGIC = b"ktb1"  # analog of the reference's protobuf prefix \x6b\x38\x73\x00
CONTENT_TYPE = "application/vnd.ktpu.binary"

_NONE, _TRUE, _FALSE, _INT, _FLOAT, _STR, _LIST, _MAP = range(8)


def _uvarint(n: int, out: bytearray):
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _read_uvarint(buf: memoryview, pos: int) -> Tuple[int, int]:
    shift = 0
    n = 0
    while True:
        b = buf[pos]
        pos += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, pos
        shift += 7
        if shift > 63:
            raise ValueError("varint too long")


def _zigzag(n: int) -> int:
    return (n << 1) ^ (n >> 63) if n < 0 else n << 1


def _unzigzag(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def _enc(v: Any, out: bytearray):
    if v is None:
        out.append(_NONE)
    elif v is True:
        out.append(_TRUE)
    elif v is False:
        out.append(_FALSE)
    elif isinstance(v, int):
        out.append(_INT)
        _uvarint(_zigzag(v), out)
    elif isinstance(v, float):
        out.append(_FLOAT)
        out += struct.pack("<d", v)
    elif isinstance(v, str):
        b = v.encode()
        out.append(_STR)
        _uvarint(len(b), out)
        out += b
    elif isinstance(v, (list, tuple)):
        out.append(_LIST)
        _uvarint(len(v), out)
        for x in v:
            _enc(x, out)
    elif isinstance(v, dict):
        out.append(_MAP)
        _uvarint(len(v), out)
        for k, x in v.items():
            _enc(k, out)
            _enc(x, out)
    else:
        raise TypeError(f"unencodable value {type(v).__name__}")


def _dec(buf: memoryview, pos: int) -> Tuple[Any, int]:
    tag = buf[pos]
    pos += 1
    if tag == _NONE:
        return None, pos
    if tag == _TRUE:
        return True, pos
    if tag == _FALSE:
        return False, pos
    if tag == _INT:
        n, pos = _read_uvarint(buf, pos)
        return _unzigzag(n), pos
    if tag == _FLOAT:
        return struct.unpack_from("<d", buf, pos)[0], pos + 8
    if tag == _STR:
        n, pos = _read_uvarint(buf, pos)
        return bytes(buf[pos:pos + n]).decode(), pos + n
    if tag == _LIST:
        n, pos = _read_uvarint(buf, pos)
        out: List[Any] = []
        for _ in range(n):
            v, pos = _dec(buf, pos)
            out.append(v)
        return out, pos
    if tag == _MAP:
        n, pos = _read_uvarint(buf, pos)
        d: Dict[Any, Any] = {}
        for _ in range(n):
            k, pos = _dec(buf, pos)
            v, pos = _dec(buf, pos)
            d[k] = v
        return d, pos
    raise ValueError(f"bad tag {tag} at {pos - 1}")


# -- object-level API ----------------------------------------------------------


def dumps(obj) -> bytes:
    """Object -> framed binary bytes (with kind tag)."""
    out = bytearray(MAGIC)
    _enc(scheme.encode_object(obj), out)
    return bytes(out)


def loads(data: bytes):
    """Framed binary bytes -> object."""
    if data[:4] != MAGIC:
        raise ValueError("not a ktpu binary frame")
    doc, _ = _dec(memoryview(data), 4)
    return scheme.decode_object(doc)


def dumps_list(kind: str, objs, resource_version: int = 0) -> bytes:
    """List response framing (the protobuf List analog)."""
    out = bytearray(MAGIC)
    _enc({"kind": kind + "List",
          "metadata": {"resourceVersion": str(resource_version)},
          "items": [scheme.encode_object(o) for o in objs]}, out)
    return bytes(out)


def loads_list(data: bytes) -> Tuple[list, int]:
    if data[:4] != MAGIC:
        raise ValueError("not a ktpu binary frame")
    doc, _ = _dec(memoryview(data), 4)
    items = [scheme.decode_object(d) for d in doc.get("items", [])]
    rv = int(doc.get("metadata", {}).get("resourceVersion", "0"))
    return items, rv
