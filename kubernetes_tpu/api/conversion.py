"""Multi-version serving: wire-level conversion between API versions.

Analog of the reference's conversion machinery
(staging/src/k8s.io/apimachinery/pkg/conversion/converter.go:40 Converter;
pkg/apis/apps/v1beta1/conversion.go, pkg/apis/autoscaling/v1/conversion.go):
each kind has ONE hub schema (the dataclass model in api/types.py, which
is also the storage schema, like the reference's internal version) and
any number of additional served versions. A served version is a pair of
wire-dict transforms:

    to_hub(data)   request body at that version -> hub wire form
    from_hub(data) hub wire form -> response body at that version

Conversions operate on the encoded (camelCase JSON) representation, not
on dataclasses — the hub dataclasses stay the single in-memory model, so
informers, controllers, and the scheduler never see versioned types
(exactly the reference's "everything internal speaks internal types"
rule, SURVEY.md L1).

Registered pairs mirror real reference conversions:

  * apps/v1beta1 Deployment (pkg/apis/apps/v1beta1/): a nil selector
    defaults from template labels on the way in; spec.rollbackTo is
    preserved through the hub as the deprecated rollback annotation.
  * autoscaling/v2beta1 HorizontalPodAutoscaler
    (pkg/apis/autoscaling/v1/conversion.go:62
    Convert_v1_HorizontalPodAutoscalerSpec_To_autoscaling_...): the v1
    targetCPUUtilizationPercentage field <-> a v2beta1 Resource metric
    on cpu with targetAverageUtilization.
  * batch/v2alpha1 CronJob: schema-identical, tag-only (the reference
    served both batch/v1beta1 and v2alpha1 in 1.11).
"""

from __future__ import annotations

import copy
from typing import Any, Callable, Dict, Optional, Tuple

WireFn = Callable[[Dict[str, Any]], Dict[str, Any]]

# kind -> {version -> (to_hub, from_hub)}; None = identity (tag-only)
_VERSIONS: Dict[str, Dict[str, Tuple[Optional[WireFn], Optional[WireFn]]]] = {}


def register_version(kind: str, version: str,
                     to_hub: Optional[WireFn] = None,
                     from_hub: Optional[WireFn] = None):
    """Serve `kind` at an additional apiVersion. to_hub/from_hub are
    wire-dict transforms; None means the schemas are identical and only
    the apiVersion tag differs."""
    _VERSIONS.setdefault(kind, {})[version] = (to_hub, from_hub)


def unregister_kind(kind: str):
    _VERSIONS.pop(kind, None)


def extra_versions(kind: str):
    return list(_VERSIONS.get(kind, ()))


def serves(kind: str, version: str, hub_version: str) -> bool:
    return version == hub_version or version in _VERSIONS.get(kind, ())


def set_versions(kind: str,
                 versions: Dict[str, Tuple[Optional[WireFn],
                                           Optional[WireFn]]]):
    """Atomically replace kind's served extra versions (one dict
    assignment) — re-registering a CRD must not open a window where a
    concurrent list/watch at an extra version finds the kind unserved."""
    if versions:
        _VERSIONS[kind] = dict(versions)
    else:
        _VERSIONS.pop(kind, None)


def to_hub(kind: str, data: Dict[str, Any], version: str,
           hub_version: str) -> Dict[str, Any]:
    """Request body at `version` -> hub wire form (converter.go Convert:
    the hub is the pivot; there are no version-to-version edges)."""
    if version == hub_version:
        return data
    fns = _VERSIONS.get(kind, {}).get(version)
    if fns is None:
        raise KeyError(f"{kind} is not served at {version}")
    data = copy.deepcopy(data)
    data["apiVersion"] = hub_version
    return fns[0](data) if fns[0] else data


def from_hub(kind: str, data: Dict[str, Any], version: str,
             hub_version: str, owned: bool = False) -> Dict[str, Any]:
    """Hub wire form -> response body at `version`. owned=True promises
    the caller built `data` fresh (encode_object does) so the converter
    may mutate it in place — skipping a deepcopy per object on the
    list/watch hot path."""
    if version == hub_version:
        return data
    fns = _VERSIONS.get(kind, {}).get(version)
    if fns is None:
        raise KeyError(f"{kind} is not served at {version}")
    if not owned:
        data = copy.deepcopy(data)
    data["apiVersion"] = version
    return fns[1](data) if fns[1] else data


# -- apps/v1beta1 Deployment ---------------------------------------------------

ROLLBACK_ANNOTATION = "deprecated.deployment.rollback.to"


def _deployment_v1beta1_to_hub(data):
    # v1beta1 defaulting: nil selector defaults from template labels
    # (pkg/apis/apps/v1beta1/defaults.go SetDefaults_DeploymentSpec —
    # shared with the other legacy workload kinds)
    data = _selector_default_to_hub(data)
    spec = data.get("spec") or {}
    # spec.rollbackTo exists only in v1beta1; the hub schema has no
    # field for it, so it survives as the deprecated annotation
    rb = spec.pop("rollbackTo", None)
    if rb is not None:
        meta = data.setdefault("metadata", {})
        ann = meta.setdefault("annotations", {})
        ann[ROLLBACK_ANNOTATION] = str(rb.get("revision", 0))
    data["spec"] = spec
    return data


def _deployment_v1beta1_from_hub(data):
    ann = ((data.get("metadata") or {}).get("annotations") or {})
    # pop, not get: the annotation IS the v1beta1 field in hub form —
    # leaving it behind would resurrect a rollbackTo the client deleted
    # on the next round trip
    rev = ann.pop(ROLLBACK_ANNOTATION, None)
    if rev is not None:
        spec = data.setdefault("spec", {})
        try:
            spec["rollbackTo"] = {"revision": int(rev)}
        except ValueError:
            pass
    return data


# -- autoscaling/v2beta1 HorizontalPodAutoscaler -------------------------------


METRICS_ANNOTATION = "autoscaling.alpha.kubernetes.io/metrics"


def _is_cpu_util(m):
    res = m.get("resource") or {}
    return (m.get("type") == "Resource" and res.get("name") == "cpu"
            and res.get("targetAverageUtilization") is not None)


def _hpa_v2beta1_to_hub(data):
    import json as _json

    spec = data.get("spec") or {}
    metrics = spec.pop("metrics", None) or []
    rest = []
    for m in metrics:
        if _is_cpu_util(m) and "targetCpuUtilizationPercentage" not in spec:
            spec["targetCpuUtilizationPercentage"] = \
                m["resource"]["targetAverageUtilization"]
        else:
            rest.append(m)
    if rest:
        # metrics the v1 hub can't express survive as the reference's
        # alpha annotation (pkg/apis/autoscaling/v1/conversion.go:37)
        ann = data.setdefault("metadata", {}).setdefault("annotations", {})
        ann[METRICS_ANNOTATION] = _json.dumps(rest)
    data["spec"] = spec
    # v2beta1 status.currentMetrics cpu utilization -> v1 status field
    status = data.get("status")
    if status:
        for m in status.pop("currentMetrics", None) or []:
            res = m.get("resource") or {}
            if m.get("type") == "Resource" and res.get("name") == "cpu" \
                    and res.get("currentAverageUtilization") is not None:
                status["currentCpuUtilizationPercentage"] = \
                    res["currentAverageUtilization"]
    return data


def _hpa_v2beta1_from_hub(data):
    import json as _json

    spec = data.get("spec") or {}
    metrics = []
    cpu = spec.pop("targetCpuUtilizationPercentage", None)
    if cpu is not None:
        metrics.append({
            "type": "Resource",
            "resource": {"name": "cpu", "targetAverageUtilization": cpu}})
    ann = ((data.get("metadata") or {}).get("annotations") or {})
    preserved = ann.pop(METRICS_ANNOTATION, None)
    if preserved:
        try:
            metrics.extend(_json.loads(preserved))
        except ValueError:
            pass
    if metrics:
        spec["metrics"] = metrics
    data["spec"] = spec
    status = data.get("status")
    if status:
        ccpu = status.pop("currentCpuUtilizationPercentage", None)
        if ccpu is not None:
            status["currentMetrics"] = [{
                "type": "Resource",
                "resource": {"name": "cpu",
                             "currentAverageUtilization": ccpu}}]
    return data


def _selector_default_to_hub(data):
    """Shared legacy-workload defaulting: a nil selector defaults from
    the template labels (pkg/apis/extensions/v1beta1/defaults.go
    SetDefaults_ReplicaSet / SetDefaults_DaemonSet — removed in
    apps/v1beta2+, where selector is required and immutable)."""
    spec = data.get("spec") or {}
    # nil-only defaulting: an EXPLICIT empty selector ({}) is a valid
    # match-everything selector in the legacy versions and must survive
    # the round-trip (the reference defaults only `Selector == nil`)
    if spec.get("selector") is None:
        tlabels = (((spec.get("template") or {}).get("metadata") or {})
                   .get("labels") or {})
        if tlabels:
            spec["selector"] = {"matchLabels": dict(tlabels)}
            data["spec"] = spec
    return data


def install_defaults():
    """Register the built-in multi-version pairs. The 1.11 reference
    serves the workload kinds at apps/v1 (hub here), apps/v1beta1,
    apps/v1beta2, and extensions/v1beta1 simultaneously
    (pkg/master/master.go InstallAPIs; pkg/apis/extensions)."""
    register_version("Deployment", "apps/v1beta1",
                     _deployment_v1beta1_to_hub, _deployment_v1beta1_from_hub)
    # extensions/v1beta1 Deployment carries the same legacy fields as
    # apps/v1beta1 (nil-selector defaulting + spec.rollbackTo)
    register_version("Deployment", "extensions/v1beta1",
                     _deployment_v1beta1_to_hub, _deployment_v1beta1_from_hub)
    # apps/v1beta2 dropped the legacy defaulting — wire shape == hub
    register_version("Deployment", "apps/v1beta2")
    register_version("ReplicaSet", "extensions/v1beta1",
                     _selector_default_to_hub)
    register_version("ReplicaSet", "apps/v1beta2")
    register_version("DaemonSet", "extensions/v1beta1",
                     _selector_default_to_hub)
    register_version("DaemonSet", "apps/v1beta2")
    register_version("StatefulSet", "apps/v1beta1",
                     _selector_default_to_hub)
    register_version("StatefulSet", "apps/v1beta2")
    register_version("HorizontalPodAutoscaler", "autoscaling/v2beta1",
                     _hpa_v2beta1_to_hub, _hpa_v2beta1_from_hub)
    register_version("CronJob", "batch/v2alpha1")
