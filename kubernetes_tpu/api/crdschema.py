"""OpenAPI v3 schema validation for custom resources.

The enforcement half of CustomResourceValidation (reference:
apiextensions-apiserver pkg/apiserver/validation/validation.go, which
delegates to go-openapi's SpecValidator). This is a self-contained
structural validator covering the keywords CRD authors actually use:
type, properties, required, items, enum, pattern, minimum/maximum,
minLength/maxLength, minItems/maxItems, additionalProperties, nullable.
Errors come back field-addressed, feeding the same 422 machinery as
built-in kinds.
"""

from __future__ import annotations

import re
from typing import Any, List, Tuple

_TYPE_CHECKS = {
    "object": lambda v: isinstance(v, dict),
    "array": lambda v: isinstance(v, list),
    "string": lambda v: isinstance(v, str),
    # bool is an int subclass in Python: exclude it from numerics
    "integer": lambda v: isinstance(v, int) and not isinstance(v, bool),
    "number": lambda v: isinstance(v, (int, float))
    and not isinstance(v, bool),
    "boolean": lambda v: isinstance(v, bool),
}


def validate_schema(value: Any, schema: dict,
                    path: str = "") -> List[Tuple[str, str]]:
    """value vs schema -> [(field_path, message)]; empty = valid."""
    errs: List[Tuple[str, str]] = []
    _walk(value, schema or {}, path or "<root>", errs)
    return errs


def schema_errors(schema: dict,
                  path: str = "openAPIV3Schema") -> List[Tuple[str, str]]:
    """Structural validation of the SCHEMA itself, run at CRD
    registration (apiextensions validation.go ValidateCustomResource
    Definition): a broken pattern or unknown type is the schema
    author's 422, not a fate inflicted on every future resource
    author."""
    errs: List[Tuple[str, str]] = []
    if not isinstance(schema, dict):
        errs.append((path, "schema must be an object"))
        return errs
    t = schema.get("type")
    if t is not None and t not in _TYPE_CHECKS:
        errs.append((f"{path}.type", f"unknown schema type {t!r}"))
    pat = schema.get("pattern")
    if pat is not None:
        try:
            re.compile(pat)
        except re.error as e:
            errs.append((f"{path}.pattern",
                         f"invalid regular expression {pat!r}: {e}"))
    for name, sub in (schema.get("properties") or {}).items():
        errs.extend(schema_errors(sub, f"{path}.properties[{name}]"))
    items = schema.get("items")
    if isinstance(items, dict):
        errs.extend(schema_errors(items, f"{path}.items"))
    addl = schema.get("additionalProperties")
    if isinstance(addl, dict):
        errs.extend(schema_errors(addl, f"{path}.additionalProperties"))
    return errs


def _walk(value, schema, path, errs):
    if value is None:
        if schema.get("nullable"):
            return
        # absent vs null is the caller's concern (required handles
        # absence); an explicit null against a typed schema fails
        if "type" in schema:
            errs.append((path, "must not be null"))
        return
    t = schema.get("type")
    if t is not None:
        check = _TYPE_CHECKS.get(t)
        if check is None:
            errs.append((path, f"unknown schema type {t!r}"))
            return
        if not check(value):
            errs.append((path, f"must be of type {t}"))
            return
    if "enum" in schema and value not in schema["enum"]:
        errs.append((path, f"must be one of {schema['enum']!r}"))
    if isinstance(value, str):
        pat = schema.get("pattern")
        if pat is not None:
            try:
                matched = re.search(pat, value) is not None
            except re.error:
                # a broken pattern in the CRD is a schema-author error,
                # reported as a field error rather than a 500 on every
                # write (the reference rejects it at CRD create)
                errs.append((path, f"schema pattern {pat!r} is not a "
                                   f"valid regular expression"))
                matched = True
            if not matched:
                errs.append((path, f"must match pattern {pat!r}"))
        if "minLength" in schema and len(value) < schema["minLength"]:
            errs.append((path,
                         f"length must be >= {schema['minLength']}"))
        if "maxLength" in schema and len(value) > schema["maxLength"]:
            errs.append((path,
                         f"length must be <= {schema['maxLength']}"))
    if isinstance(value, (int, float)) and not isinstance(value, bool):
        if "minimum" in schema and value < schema["minimum"]:
            errs.append((path, f"must be >= {schema['minimum']}"))
        if "maximum" in schema and value > schema["maximum"]:
            errs.append((path, f"must be <= {schema['maximum']}"))
    if isinstance(value, list):
        if "minItems" in schema and len(value) < schema["minItems"]:
            errs.append((path, f"must have >= {schema['minItems']} items"))
        if "maxItems" in schema and len(value) > schema["maxItems"]:
            errs.append((path, f"must have <= {schema['maxItems']} items"))
        items = schema.get("items")
        if isinstance(items, dict):
            for i, v in enumerate(value):
                _walk(v, items, f"{path}[{i}]", errs)
    if isinstance(value, dict):
        props = schema.get("properties", {})
        for req in schema.get("required", []):
            if req not in value:
                errs.append((f"{path}.{req}", "required value missing"))
        addl = schema.get("additionalProperties")
        for k, v in value.items():
            sub = props.get(k)
            if sub is not None:
                _walk(v, sub, f"{path}.{k}", errs)
            elif addl is False:
                errs.append((f"{path}.{k}",
                             "additional properties are not allowed"))
            elif isinstance(addl, dict):
                _walk(v, addl, f"{path}.{k}", errs)
