"""Label selector semantics.

Host-side golden implementation of apimachinery's labels.Selector
(reference: staging/src/k8s.io/apimachinery/pkg/labels/selector.go).
This is the behavioral contract the tensor kernels in ops/selectors.py
must reproduce; parity tests compare the two on identical fixtures.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional, Sequence

# Operators (reference: apimachinery/pkg/selection/operator.go and
# api/core/v1 NodeSelectorOperator values).
IN = "In"
NOT_IN = "NotIn"
EXISTS = "Exists"
DOES_NOT_EXIST = "DoesNotExist"
GT = "Gt"
LT = "Lt"

OPS = (IN, NOT_IN, EXISTS, DOES_NOT_EXIST, GT, LT)


def _as_int(s: str) -> Optional[int]:
    try:
        return int(s)
    except (ValueError, TypeError):
        return None


@dataclass(frozen=True)
class Requirement:
    """One (key op values) clause.

    Matching rules (reference: apimachinery/pkg/labels/selector.go:159
    `Requirement.Matches`):
      In       -> key exists and value in set
      NotIn    -> key missing OR value not in set
      Exists   -> key exists
      DoesNotExist -> key missing
      Gt/Lt    -> key exists, both label value and operand parse as int,
                  and labelValue > / < operand
    """

    key: str
    op: str
    values: tuple = ()

    def matches(self, labels: Mapping[str, str]) -> bool:
        has = self.key in labels
        if self.op == IN:
            return has and labels[self.key] in self.values
        if self.op == NOT_IN:
            return (not has) or labels[self.key] not in self.values
        if self.op == EXISTS:
            return has
        if self.op == DOES_NOT_EXIST:
            return not has
        if self.op in (GT, LT):
            if not has or len(self.values) != 1:
                return False
            lv = _as_int(labels[self.key])
            rv = _as_int(self.values[0])
            if lv is None or rv is None:
                return False
            return lv > rv if self.op == GT else lv < rv
        raise ValueError(f"unknown operator {self.op!r}")


@dataclass(frozen=True)
class Selector:
    """AND of requirements; empty selector matches everything
    (reference: labels.SelectorFromSet / internalSelector)."""

    requirements: tuple = ()

    def matches(self, labels: Mapping[str, str]) -> bool:
        return all(r.matches(labels) for r in self.requirements)

    @staticmethod
    def from_set(label_set: Mapping[str, str]) -> "Selector":
        """Equality selector from a map (reference: labels.SelectorFromSet)."""
        return Selector(
            tuple(Requirement(k, IN, (v,)) for k, v in sorted(label_set.items()))
        )

    @staticmethod
    def from_requirements(reqs: Sequence[Requirement]) -> "Selector":
        return Selector(tuple(reqs))

    @staticmethod
    def parse(text: str) -> "Selector":
        """Parse the set-based selector STRING syntax
        (apimachinery/pkg/labels/selector.go Parse):
        comma-separated requirements of the forms
        `k=v` / `k==v` / `k!=v` / `k in (v1,v2)` / `k notin (v1,v2)` /
        `k` (exists) / `!k` (does not exist). Raises ValueError on
        malformed input."""
        import re

        reqs: List[Requirement] = []
        rest = text.strip()
        while rest:
            # `\s+` before in/notin is load-bearing: without it the
            # greedy key backtracks so "admin (a,b)" parses as
            # key="adm" op=in — a requirement on a key the user never
            # wrote (the reference lexer tokenizes on whitespace)
            m = re.match(
                r"\s*(!?)([A-Za-z0-9._/-]+)"
                r"(?:\s*(==|=|!=)\s*([A-Za-z0-9._-]*)"
                r"|\s+(in|notin)\s*\(([^)]*)\))?\s*(?:,|$)", rest)
            if not m or not m.group(0).strip():
                raise ValueError(f"unparseable selector {text!r}")
            neg, key, eqop, eqval, setop, setvals = m.groups()
            if eqop:
                if neg:
                    raise ValueError(f"unparseable selector {text!r}")
                reqs.append(Requirement(
                    key, NOT_IN if eqop == "!=" else IN, (eqval,)))
            elif setop:
                if neg:
                    raise ValueError(f"unparseable selector {text!r}")
                vals = tuple(v.strip() for v in setvals.split(",")
                             if v.strip())
                if not vals:
                    # an empty set would make NotIn match EVERYTHING
                    # (and In nothing) — the reference parser rejects it
                    raise ValueError(
                        f"empty value set in selector {text!r}")
                reqs.append(Requirement(
                    key, IN if setop == "in" else NOT_IN, vals))
            else:
                reqs.append(Requirement(
                    key, DOES_NOT_EXIST if neg else EXISTS))
            rest = rest[m.end():]
        return Selector(tuple(reqs))


@dataclass(frozen=True)
class LabelSelector:
    """The versioned meta/v1.LabelSelector (matchLabels + matchExpressions),
    as used by services/replicasets/pod-affinity terms
    (reference: apimachinery/pkg/apis/meta/v1/types.go LabelSelector).

    None ~ nil selector: matches nothing when used for pod affinity;
    an empty LabelSelector matches everything.
    """

    match_labels: Mapping[str, str] = field(default_factory=dict)
    match_expressions: tuple = ()  # tuple[Requirement]

    def to_selector(self) -> Selector:
        reqs: List[Requirement] = [
            Requirement(k, IN, (v,)) for k, v in sorted(self.match_labels.items())
        ]
        reqs.extend(self.match_expressions)
        return Selector(tuple(reqs))

    def matches(self, labels: Mapping[str, str]) -> bool:
        return self.to_selector().matches(labels)
