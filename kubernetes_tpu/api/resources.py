"""Resource quantities.

A deliberately small replacement for apimachinery's resource.Quantity
(reference: staging/src/k8s.io/apimachinery/pkg/api/resource): quantities
are canonicalized at parse time to int64 scalars — milli-units for CPU,
bytes for memory/storage, raw counts for everything else — which is the
form the scheduler's NodeInfo already uses internally (reference:
pkg/scheduler/schedulercache/node_info.go:131-140 `Resource`).
"""

from __future__ import annotations

import re

# Canonical resource names (reference: staging/src/k8s.io/api/core/v1/types.go).
CPU = "cpu"
MEMORY = "memory"
EPHEMERAL_STORAGE = "ephemeral-storage"
STORAGE = "storage"  # PV/PVC capacity key
PODS = "pods"

_BINARY_SUFFIX = {
    "Ki": 1024,
    "Mi": 1024**2,
    "Gi": 1024**3,
    "Ti": 1024**4,
    "Pi": 1024**5,
    "Ei": 1024**6,
}
_DECIMAL_SUFFIX = {
    "n": 1e-9,
    "u": 1e-6,
    "m": 1e-3,
    "": 1.0,
    "k": 1e3,
    "M": 1e6,
    "G": 1e9,
    "T": 1e12,
    "P": 1e15,
    "E": 1e18,
}

_QTY_RE = re.compile(r"^([+-]?[0-9.]+(?:[eE][+-]?[0-9]+)?)(Ki|Mi|Gi|Ti|Pi|Ei|[numkMGTPE]?)$")


def parse_quantity(value) -> float:
    """Parse a Kubernetes quantity string ("100m", "1Gi", "2") to a float
    of base units (cores, bytes, counts)."""
    if isinstance(value, (int, float)):
        return float(value)
    m = _QTY_RE.match(value.strip())
    if not m:
        raise ValueError(f"invalid quantity: {value!r}")
    num, suffix = m.groups()
    base = float(num)
    if suffix in _BINARY_SUFFIX:
        return base * _BINARY_SUFFIX[suffix]
    return base * _DECIMAL_SUFFIX[suffix]


def milli(value) -> int:
    """Quantity -> integer milli-units (reference Quantity.MilliValue)."""
    import math

    return int(math.ceil(parse_quantity(value) * 1000 - 1e-9))


def value(value_) -> int:
    """Quantity -> integer base units, rounded up (reference Quantity.Value)."""
    import math

    return int(math.ceil(parse_quantity(value_) - 1e-9))


def is_extended(name: str) -> bool:
    """Whether a resource name is an extended (non-core) resource.

    Reference: pkg/apis/core/v1/helper/helpers.go IsExtendedResourceName —
    anything not in the default (kubernetes.io) namespace and not
    hugepages/attachable prefixed counts as extended.
    """
    if name in (CPU, MEMORY, EPHEMERAL_STORAGE, PODS):
        return False
    return "/" in name and not name.startswith("kubernetes.io/")
