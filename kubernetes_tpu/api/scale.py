"""The polymorphic scale mapping: one place that knows how a kind's
replica count is read and written.

Shared by the apiserver's /scale subresource and the HPA controller —
the reference routes both through the scale client
(staging/src/k8s.io/client-go/scale/client.go; HPA usage in
pkg/controller/podautoscaler/horizontal.go scaleForResourceMappings).
Built-in workload kinds map to spec/status.replicas; custom kinds map
through the dotted paths their CRD declares in subresources.scale.
"""

from __future__ import annotations

from typing import Optional, Tuple

from . import scheme
from . import types as api

# kinds with a native Scale mapping (the reference's registry wires
# autoscaling/v1 Scale REST for exactly these:
# registry/apps/*/storage/storage.go ScaleREST + core RC)
BUILTIN_SCALE_KINDS = {
    "Deployment": "deployments",
    "ReplicaSet": "replicasets",
    "ReplicationController": "replicationcontrollers",
    "StatefulSet": "statefulsets",
}
BUILTIN_SCALE_PLURALS = frozenset(BUILTIN_SCALE_KINDS.values())


def crd_for_kind(store, kind: str):
    for crd in store.list("customresourcedefinitions"):
        if crd.spec.names.kind == kind:
            return crd
    return None


def dotted_get(wire: dict, path: str, default=None):
    cur = wire
    for part in [p for p in path.split(".") if p]:
        if not isinstance(cur, dict) or part not in cur:
            return default
        cur = cur[part]
    return cur


def dotted_set(wire: dict, path: str, value):
    parts = [p for p in path.split(".") if p]
    cur = wire
    for part in parts[:-1]:
        cur = cur.setdefault(part, {})
    cur[parts[-1]] = value


def mapping_for(store, plural: str,
                obj) -> Optional[Tuple[str, str, str]]:
    """-> (spec_replicas_path, status_replicas_path, selector string) or
    None when the kind serves no scale."""
    if plural in BUILTIN_SCALE_PLURALS:
        sel = ""
        s = getattr(obj.spec, "selector", None)
        if s is not None and getattr(s, "match_labels", None):
            sel = ",".join(f"{k}={v}"
                           for k, v in sorted(s.match_labels.items()))
        elif isinstance(s, dict) and s:
            # ReplicationController carries a bare map selector
            sel = ",".join(f"{k}={v}" for k, v in sorted(s.items()))
        return ".spec.replicas", ".status.replicas", sel
    if isinstance(obj, api.CustomObject):
        crd = crd_for_kind(store, obj.kind)
        if crd is not None and crd.spec.subresources is not None and \
                crd.spec.subresources.scale is not None:
            sc = crd.spec.subresources.scale
            sel = ""
            if sc.label_selector_path:
                wire = scheme.encode_object(obj)
                got = dotted_get(wire, sc.label_selector_path, "")
                # the Scale selector is a STRING field; a map-shaped
                # value at the path degrades to no selector rather than
                # crashing every consumer (HPA retry-loops otherwise)
                sel = got if isinstance(got, str) else ""
            return sc.spec_replicas_path, sc.status_replicas_path, sel
    return None


def get_spec_replicas(obj, spec_path: str) -> int:
    if isinstance(obj, api.CustomObject):
        got = dotted_get({"spec": obj.spec, "status": obj.status},
                         spec_path, 0)
        return got if isinstance(got, int) else 0
    return obj.spec.replicas


def set_spec_replicas(obj, spec_path: str, value: int):
    if isinstance(obj, api.CustomObject):
        dotted_set({"spec": obj.spec, "status": obj.status},
                   spec_path, value)
    else:
        obj.spec.replicas = value
