"""Scheme + codecs: the api-machinery serialization layer.

Analog of the reference's runtime.Scheme and serializer stack
(staging/src/k8s.io/apimachinery/pkg/runtime/scheme.go and
runtime/serializer/json/): a registry mapping kind names <-> Python
types <-> storage plurals, plus a generic JSON codec over the dataclass
object model in api/types.py. Wire format follows the reference's JSON
conventions — camelCase field names, top-level ``kind``/``apiVersion``
tags — so objects round-trip through the HTTP apiserver, kubectl, and
YAML manifests.

The dataclasses are the HUB schema — simultaneously the internal types
and the storage wire schema (resource quantities stay canonical int64s —
milli-CPU, bytes — as in schedulercache's Resource, node_info.go:131).
Additional served versions convert to/from the hub at the wire level
(api/conversion.py, the converter.go:40 analog): encode_object(obj,
version=...) emits any served version, decode_request() accepts any.
"""

from __future__ import annotations

import dataclasses
import json
from typing import (Any, Dict, List, Mapping, Optional, Tuple, Union,
                    get_args, get_origin, get_type_hints)

from . import conversion
from . import labels as lbl
from . import types as api

# -- kind registry (runtime.Scheme analog) ------------------------------------

# kind -> (plural, type, apiVersion, namespaced)
_REGISTRY: Dict[str, Tuple[str, type, str, bool]] = {}
_BY_PLURAL: Dict[str, str] = {}
_BY_TYPE: Dict[type, str] = {}


def register(kind: str, plural: str, typ: type, api_version: str = "v1",
             namespaced: bool = True):
    old = _REGISTRY.get(kind)
    if old is not None and old[0] != plural:
        # re-registration under a new plural (CRD rename): the retired
        # plural must stop resolving or it would route to a registry
        # entry that may later disappear (KeyError -> 500)
        _BY_PLURAL.pop(old[0], None)
    _REGISTRY[kind] = (plural, typ, api_version, namespaced)
    _BY_PLURAL[plural] = kind
    # every CRD-defined kind shares api.CustomObject, which tags itself:
    # the type->kind map keeps only the first (static) binding
    if typ not in _BY_TYPE:
        _BY_TYPE[typ] = kind


def crd_conflict(crd: "api.CustomResourceDefinition",
                 replacing: Optional[str] = None) -> Optional[str]:
    """Why this CRD may NOT be registered: its names must not collide
    with a built-in kind or another CRD — a CRD named 'Pod' would
    otherwise hijack (and, on deletion, unregister) the built-in
    server-wide. `replacing` names the kind an update supersedes, so a
    CRD renaming its own plural doesn't conflict with itself."""
    names = crd.spec.names
    existing = _REGISTRY.get(names.kind)
    if existing is not None:
        if existing[1] is not api.CustomObject:
            return f"kind {names.kind!r} is a built-in type"
        if names.kind != replacing and existing[0] != names.plural:
            return f"kind {names.kind!r} already defined by another CRD"
    served_by = _BY_PLURAL.get(names.plural)
    if served_by is not None and served_by not in (names.kind, replacing):
        return f"plural {names.plural!r} already served by {served_by!r}"
    return None


def register_dynamic(crd: "api.CustomResourceDefinition",
                     replacing: Optional[str] = None):
    """Serve a CRD's kind (apiextensions customresource_handler.go:
    instances decode to api.CustomObject). Raises ValueError on a name
    collision (see crd_conflict)."""
    msg = crd_conflict(crd, replacing)
    if msg is not None:
        raise ValueError(msg)
    names = crd.spec.names
    register(names.kind, names.plural, api.CustomObject,
             f"{crd.spec.group}/{crd.spec.version}",
             namespaced=crd.spec.scope == "Namespaced")
    # multi-version serving (apiextensions 1.11 spec.versions): every
    # listed version is served; non-storage versions convert by tag
    # rewrite only (CRDs have no conversion webhooks in 1.11 — all
    # versions share the schema, customresource_handler.go). Replaced
    # as one atomic swap so a concurrent list/watch at an extra version
    # never observes the kind momentarily unserved.
    conversion.set_versions(names.kind, {
        f"{crd.spec.group}/{v}": (None, None)
        for v in (crd.spec.versions or ()) if v != crd.spec.version})


def unregister(kind: str):
    """Remove a dynamically-registered kind (CRD deletion). Built-in
    kinds are never unregistered."""
    entry = _REGISTRY.get(kind)
    if entry is None or entry[1] is not api.CustomObject:
        return
    del _REGISTRY[kind]
    _BY_PLURAL.pop(entry[0], None)
    conversion.unregister_kind(kind)
    if _BY_TYPE.get(entry[1]) == kind:
        _BY_TYPE.pop(entry[1], None)


register("Pod", "pods", api.Pod)
register("CSIDriver", "csidrivers", api.CSIDriver,
         "storage.k8s.io/v1beta1", namespaced=False)
register("PodPreset", "podpresets", api.PodPreset,
         "settings.k8s.io/v1alpha1")
register("StorageClass", "storageclasses", api.StorageClass,
         "storage.k8s.io/v1", namespaced=False)
register("Node", "nodes", api.Node, namespaced=False)
register("Service", "services", api.Service)
register("ReplicationController", "replicationcontrollers", api.ReplicationController)
register("ReplicaSet", "replicasets", api.ReplicaSet, "apps/v1")
register("StatefulSet", "statefulsets", api.StatefulSet, "apps/v1")
register("Deployment", "deployments", api.Deployment, "apps/v1")
register("DaemonSet", "daemonsets", api.DaemonSet, "apps/v1")
register("ControllerRevision", "controllerrevisions", api.ControllerRevision,
         "apps/v1")
register("Job", "jobs", api.Job, "batch/v1")
register("CronJob", "cronjobs", api.CronJob, "batch/v1beta1")
register("PodDisruptionBudget", "poddisruptionbudgets", api.PodDisruptionBudget,
         "policy/v1beta1")
register("PodGroup", "podgroups", api.PodGroup,
         "scheduling.sigs.k8s.io/v1alpha1")
# scheduler weight profiles (shadow-scoring observatory, sched/weights.py)
register("WeightProfile", "weightprofiles", api.WeightProfile,
         "scheduling.sigs.k8s.io/v1alpha1")
register("PersistentVolume", "persistentvolumes", api.PersistentVolume,
         namespaced=False)
register("PersistentVolumeClaim", "persistentvolumeclaims", api.PersistentVolumeClaim)
register("Namespace", "namespaces", api.Namespace, namespaced=False)
register("Endpoints", "endpoints", api.Endpoints)
register("Event", "events", api.EventObject)
register("ResourceQuota", "resourcequotas", api.ResourceQuota)
register("ServiceAccount", "serviceaccounts", api.ServiceAccount)
register("Secret", "secrets", api.Secret)
register("ConfigMap", "configmaps", api.ConfigMap)
register("PriorityClass", "priorityclasses", api.PriorityClass,
         "scheduling.k8s.io/v1beta1", namespaced=False)
register("Lease", "leases", api.LeaseRecord, "coordination.k8s.io/v1",
         namespaced=False)
register("HorizontalPodAutoscaler", "horizontalpodautoscalers",
         api.HorizontalPodAutoscaler, "autoscaling/v1")
register("PodMetrics", "podmetrics", api.PodMetrics, "metrics.k8s.io/v1beta1")
register("APIService", "apiservices", api.APIService,
         "apiregistration.k8s.io/v1", namespaced=False)
register("PodSecurityPolicy", "podsecuritypolicies", api.PodSecurityPolicy,
         "policy/v1beta1", namespaced=False)
register("MutatingWebhookConfiguration", "mutatingwebhookconfigurations",
         api.MutatingWebhookConfiguration,
         "admissionregistration.k8s.io/v1beta1", namespaced=False)
register("ValidatingWebhookConfiguration", "validatingwebhookconfigurations",
         api.ValidatingWebhookConfiguration,
         "admissionregistration.k8s.io/v1beta1", namespaced=False)
register("LimitRange", "limitranges", api.LimitRange)
register("CertificateSigningRequest", "certificatesigningrequests",
         api.CertificateSigningRequest, "certificates.k8s.io/v1beta1",
         namespaced=False)
register("SelfSubjectAccessReview", "selfsubjectaccessreviews",
         api.SelfSubjectAccessReview, "authorization.k8s.io/v1",
         namespaced=False)
register("Role", "roles", api.Role, "rbac.authorization.k8s.io/v1")
register("ClusterRole", "clusterroles", api.ClusterRole,
         "rbac.authorization.k8s.io/v1", namespaced=False)
register("RoleBinding", "rolebindings", api.RoleBinding,
         "rbac.authorization.k8s.io/v1")
register("ClusterRoleBinding", "clusterrolebindings", api.ClusterRoleBinding,
         "rbac.authorization.k8s.io/v1", namespaced=False)
register("CustomResourceDefinition", "customresourcedefinitions",
         api.CustomResourceDefinition, "apiextensions.k8s.io/v1beta1",
         namespaced=False)
conversion.install_defaults()


def kind_for_plural(plural: str) -> Optional[str]:
    return _BY_PLURAL.get(plural)


def is_registered(kind: str) -> bool:
    return kind in _REGISTRY


def plural_for_kind(kind: str) -> str:
    return _REGISTRY[kind][0]


def type_for_kind(kind: str) -> type:
    return _REGISTRY[kind][1]


def kind_of(obj) -> Optional[str]:
    return _BY_TYPE.get(type(obj))


def api_version_for(kind: str) -> str:
    return _REGISTRY[kind][2]


def is_namespaced(kind: str) -> bool:
    return _REGISTRY[kind][3]


def all_kinds() -> List[str]:
    return list(_REGISTRY)


# -- multi-version serving -----------------------------------------------------


def served_versions(kind: str) -> List[str]:
    """Every apiVersion this kind is served at, hub (storage) first."""
    return [api_version_for(kind)] + conversion.extra_versions(kind)


def serves(kind: str, gv: str) -> bool:
    return conversion.serves(kind, gv, api_version_for(kind))


def convert_wire(kind: str, data: Dict[str, Any], to_version: str
                 ) -> Dict[str, Any]:
    """Hub wire dict -> `to_version` wire dict."""
    return conversion.from_hub(kind, data, to_version, api_version_for(kind))


def decode_request(kind: str, data: Mapping):
    """Wire dict at ANY served version -> hub object. The body's
    apiVersion tag picks the conversion; absent or hub-tagged bodies
    decode directly."""
    ver = data.get("apiVersion")
    hub = api_version_for(kind)
    if ver and ver != hub:
        data = conversion.to_hub(kind, dict(data), ver, hub)
    return decode(kind, data)


# -- field-name conversion -----------------------------------------------------


# wire names that break the mechanical snake->camel rule (initialisms
# the reference capitalizes wholesale)
_CAMEL_OVERRIDES = {
    "open_api_v3_schema": "openAPIV3Schema",
    "pod_ip": "podIP",
    "host_ip": "hostIP",
    "cluster_ip": "clusterIP",
    "pod_cidr": "podCIDR",
}


def _camel(name: str) -> str:
    special = _CAMEL_OVERRIDES.get(name)
    if special is not None:
        return special
    parts = name.split("_")
    return parts[0] + "".join(p.title() for p in parts[1:])


# Element types for fields whose annotation is a bare ``tuple`` (frozen
# selector dataclasses in api/labels.py).
_TUPLE_ELEM: Dict[Tuple[str, str], Any] = {
    ("Requirement", "values"): str,
    ("Selector", "requirements"): lbl.Requirement,
    ("LabelSelector", "match_expressions"): lbl.Requirement,
}

_HINT_CACHE: Dict[type, Dict[str, Any]] = {}


def _hints(cls: type) -> Dict[str, Any]:
    h = _HINT_CACHE.get(cls)
    if h is None:
        h = get_type_hints(cls)
        _HINT_CACHE[cls] = h
    return h


# -- encode --------------------------------------------------------------------


def encode(value) -> Any:
    """Object -> plain JSON-able structure (camelCase keys)."""
    if dataclasses.is_dataclass(value) and not isinstance(value, type):
        out = {}
        for f in dataclasses.fields(value):
            v = getattr(value, f.name)
            # drop empty/default-ish values for compact wire objects
            if v is None or v == {} or v == [] or v == ():
                continue
            out[_camel(f.name)] = encode(v)
        return out
    if isinstance(value, Mapping):
        return {k: encode(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [encode(v) for v in value]
    return value


def stable_hash(value, length: int = 40) -> str:
    """sha1 of the canonical sorted-JSON wire form (util/hash
    ComputeHash analog) — THE one content-hash idiom; template hashing,
    ControllerRevision identity, and generation fingerprints all go
    through here so canonicalization fixes land everywhere at once."""
    import hashlib
    enc = value if isinstance(value, (dict, list)) else encode(value)
    return hashlib.sha1(
        json.dumps(enc, sort_keys=True, default=str).encode()
    ).hexdigest()[:length]


def encode_object(obj, version: Optional[str] = None) -> Dict[str, Any]:
    """Top-level object -> dict with kind/apiVersion tags. Custom
    objects carry their own tags (all CRD kinds share one Python type).
    version requests a specific served version; the hub wire form is
    converted through api/conversion.py."""
    kind = getattr(obj, "kind", None) or kind_of(obj)
    if kind and kind in _REGISTRY:
        hub = api_version_for(kind)
    else:
        hub = getattr(obj, "api_version", None) or "v1"
    out = {"kind": kind, "apiVersion": hub}
    out.update(encode(obj))
    if version is not None and version != hub and kind:
        # owned=True: `out` was built fresh above, the converter may
        # mutate it instead of deep-copying every list/watch item
        out = conversion.from_hub(kind, out, version, hub, owned=True)
    return out


def to_json(obj) -> str:
    return json.dumps(encode_object(obj))


# -- decode --------------------------------------------------------------------


def _decode(value, hint, owner: str = "", fname: str = ""):
    if value is None:
        return None
    origin = get_origin(hint)
    if origin is Union:  # Optional[T]
        args = [a for a in get_args(hint) if a is not type(None)]
        return _decode(value, args[0], owner, fname)
    if dataclasses.is_dataclass(hint):
        return _decode_dataclass(value, hint)
    if origin in (dict, Mapping) or hint in (dict, Mapping):
        args = get_args(hint)
        vt = args[1] if len(args) == 2 else None
        return {k: (_decode(v, vt) if vt else v) for k, v in value.items()}
    if origin is list:
        (et,) = get_args(hint) or (None,)
        return [_decode(v, et) if et else v for v in value]
    if origin is tuple:
        args = get_args(hint)
        if len(args) == 2 and args[1] is Ellipsis:
            return tuple(_decode(v, args[0]) for v in value)
        return tuple(_decode(v, t) for v, t in zip(value, args))
    if hint is tuple:
        et = _TUPLE_ELEM.get((owner, fname))
        return tuple(_decode(v, et) if et and et is not str else v for v in value)
    return value


# resource-map fields whose values may arrive as quantity strings from
# YAML/JSON manifests ("100m", "1Gi") and must canonicalize to the int64
# convention (cpu -> milli, everything else -> base units) — the
# reference parses resource.Quantity at decode time
_RESOURCE_MAP_FIELDS = frozenset({
    "requests", "limits", "capacity", "allocatable", "hard", "used",
    "usage", "max", "min", "default", "default_request",
})


def _canon_resources(d: Dict[str, Any]) -> Dict[str, Any]:
    from . import resources as res

    out = {}
    for k, v in d.items():
        if isinstance(v, str):
            # quota keys spell cpu as "requests.cpu"/"limits.cpu" — all
            # CPU accounting is in milli-units
            is_cpu = k == res.CPU or k.endswith("." + res.CPU)
            out[k] = res.milli(v) if is_cpu else res.value(v)
        else:
            out[k] = v
    return out


def _decode_dataclass(data: Mapping, cls: type):
    hints = _hints(cls)
    kwargs = {}
    for f in dataclasses.fields(cls):
        wire = _camel(f.name)
        if wire not in data:
            continue
        v = _decode(data[wire], hints[f.name], cls.__name__, f.name)
        if f.name in _RESOURCE_MAP_FIELDS and isinstance(v, dict):
            v = _canon_resources(v)
        kwargs[f.name] = v
    return cls(**kwargs)


def decode(kind_or_type, data: Mapping):
    """kind name (or type) + wire dict -> object."""
    cls = kind_or_type if isinstance(kind_or_type, type) else type_for_kind(kind_or_type)
    return _decode_dataclass(data, cls)


def decode_object(data: Mapping):
    """Wire dict with a ``kind`` tag -> object."""
    kind = data.get("kind")
    if not kind or kind not in _REGISTRY:
        raise ValueError(f"unknown kind {kind!r}")
    return decode(kind, data)


def from_json(text: str):
    return decode_object(json.loads(text))
