"""Core object model.

A slim, scheduler-complete analog of the reference's API types
(reference: staging/src/k8s.io/api/core/v1/types.go — Pod, Node, Taint,
Toleration, Affinity; apps/v1 ReplicaSet/StatefulSet; policy/v1beta1
PodDisruptionBudget). Resource quantities are canonicalized at
construction: CPU in milli-units, memory/ephemeral-storage in bytes,
extended resources in raw counts — matching the int64 `Resource` struct
the reference scheduler uses (pkg/scheduler/schedulercache/node_info.go:131).
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field, replace
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

from . import resources as res
from .labels import LabelSelector, Requirement, Selector

# --- metadata ---------------------------------------------------------------

_uid_counter = itertools.count(1)


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    uid: str = ""
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    resource_version: int = 0
    # spec-change counter (apimachinery ObjectMeta.Generation): bumped
    # by the store when a workload kind's spec fingerprint changes;
    # controllers echo it into status.observedGeneration
    generation: int = 0
    deletion_timestamp: Optional[float] = None
    # seconds the kubelet has to stop containers once deletionTimestamp
    # is set (apimachinery ObjectMeta.DeletionGracePeriodSeconds)
    deletion_grace_period_seconds: Optional[int] = None
    # deletion gates (apimachinery ObjectMeta.Finalizers): a DELETE with
    # finalizers present only marks deletion_timestamp; the object goes
    # away when the last finalizer is removed (apiserver delete/update
    # paths + the protection controllers)
    finalizers: List[str] = field(default_factory=list)
    owner_references: List["OwnerReference"] = field(default_factory=list)

    def __post_init__(self):
        if not self.uid:
            self.uid = f"uid-{next(_uid_counter)}"


@dataclass
class OwnerReference:
    kind: str = ""
    name: str = ""
    uid: str = ""
    controller: bool = False
    # foreground-deletion blocker; setting it requires update permission
    # on the owner's finalizers (admission gc plugin)
    block_owner_deletion: bool = False


# --- resources --------------------------------------------------------------


def resource_list(
    cpu=None, memory=None, ephemeral_storage=None, pods=None, **extended
) -> Dict[str, int]:
    """Build a canonical resource map: cpu -> milli, memory/eph -> bytes,
    pods/extended -> counts. Accepts quantity strings or numbers."""
    out: Dict[str, int] = {}
    if cpu is not None:
        out[res.CPU] = res.milli(cpu)
    if memory is not None:
        out[res.MEMORY] = res.value(memory)
    if ephemeral_storage is not None:
        out[res.EPHEMERAL_STORAGE] = res.value(ephemeral_storage)
    if pods is not None:
        out[res.PODS] = res.value(pods)
    for name, q in extended.items():
        out[name.replace("__", "/")] = res.value(q)
    return out


@dataclass
class ResourceRequirements:
    """Canonical requests/limits maps (see resource_list)."""

    requests: Dict[str, int] = field(default_factory=dict)
    limits: Dict[str, int] = field(default_factory=dict)


@dataclass
class ContainerPort:
    container_port: int = 0
    host_port: int = 0
    protocol: str = "TCP"
    host_ip: str = ""


@dataclass
class Probe:
    """Liveness/readiness probe config (reference: core/v1 Probe +
    pkg/probe handlers). Handler precedence: exec command (runs through
    the runtime's interpreter, rc==0 healthy), then tcpSocket (checks a
    listener on the pod port), else the runtime's health bit — the seam
    tests/kubemark flip directly."""

    initial_delay_seconds: float = 0.0
    period_seconds: float = 10.0
    failure_threshold: int = 3
    success_threshold: int = 1
    exec_command: List[str] = field(default_factory=list)
    tcp_port: int = 0


@dataclass
class LifecycleHandler:
    """core/v1 Handler collapsed to its exec form — the runtime's
    interpreter executes the command against container state."""

    command: List[str] = field(default_factory=list)


@dataclass
class Lifecycle:
    """core/v1 Lifecycle: postStart runs right after the container
    starts (failure kills it — FailedPostStartHook); preStop runs
    before the kubelet stops it."""

    post_start: Optional[LifecycleHandler] = None
    pre_stop: Optional[LifecycleHandler] = None


@dataclass
class Container:
    name: str = "c"
    image: str = ""
    resources: ResourceRequirements = field(default_factory=ResourceRequirements)
    ports: List[ContainerPort] = field(default_factory=list)
    liveness_probe: Optional[Probe] = None
    readiness_probe: Optional[Probe] = None
    lifecycle: Optional[Lifecycle] = None
    image_pull_policy: str = ""  # "" -> defaulted; Always|IfNotPresent|Never
    privileged: bool = False  # securityContext.privileged, flattened
    # EnvVar list collapsed to a name->value map (no valueFrom sources)
    env: Dict[str, str] = field(default_factory=dict)
    # v1 Container.Command (entrypoint); init containers run it to
    # completion through the fake runtime's exec interpreter
    command: List[str] = field(default_factory=list)


# --- taints & tolerations ---------------------------------------------------

NO_SCHEDULE = "NoSchedule"
PREFER_NO_SCHEDULE = "PreferNoSchedule"
NO_EXECUTE = "NoExecute"

TOLERATION_OP_EQUAL = "Equal"
TOLERATION_OP_EXISTS = "Exists"


@dataclass(frozen=True)
class Taint:
    key: str
    value: str = ""
    effect: str = NO_SCHEDULE


@dataclass(frozen=True)
class Toleration:
    key: str = ""
    operator: str = TOLERATION_OP_EQUAL
    value: str = ""
    effect: str = ""  # empty matches all effects
    toleration_seconds: Optional[int] = None

    def tolerates(self, taint: Taint) -> bool:
        """Reference: staging/src/k8s.io/api/core/v1/toleration.go:37
        Toleration.ToleratesTaint."""
        if self.effect and self.effect != taint.effect:
            return False
        if self.key and self.key != taint.key:
            return False
        if self.operator in ("", TOLERATION_OP_EQUAL):
            return self.value == taint.value
        return self.operator == TOLERATION_OP_EXISTS


def tolerations_tolerate_taint(tolerations: Sequence[Toleration], taint: Taint) -> bool:
    """Reference: pkg/apis/core/v1/helper/helpers.go:350."""
    return any(t.tolerates(taint) for t in tolerations)


# --- affinity ---------------------------------------------------------------


@dataclass
class NodeSelectorTerm:
    """AND of expressions; an empty term matches nothing once it is part of
    a required selector (reference: predicates.go nodeMatchesNodeSelectorTerms
    via NodeSelectorRequirementsAsSelector)."""

    match_expressions: List[Requirement] = field(default_factory=list)
    match_fields: List[Requirement] = field(default_factory=list)


@dataclass
class NodeSelector:
    """OR of terms; an empty term list matches nothing
    (reference: predicates.go:753 nodeMatchesNodeSelectorTerms comment)."""

    node_selector_terms: List[NodeSelectorTerm] = field(default_factory=list)


@dataclass
class PreferredSchedulingTerm:
    weight: int = 1
    preference: NodeSelectorTerm = field(default_factory=NodeSelectorTerm)


@dataclass
class NodeAffinity:
    required: Optional[NodeSelector] = None
    preferred: List[PreferredSchedulingTerm] = field(default_factory=list)


@dataclass
class PodAffinityTerm:
    label_selector: Optional[LabelSelector] = None
    namespaces: List[str] = field(default_factory=list)  # empty -> pod's own ns
    topology_key: str = ""


@dataclass
class WeightedPodAffinityTerm:
    weight: int = 1
    pod_affinity_term: PodAffinityTerm = field(default_factory=PodAffinityTerm)


@dataclass
class PodAffinity:
    required: List[PodAffinityTerm] = field(default_factory=list)
    preferred: List[WeightedPodAffinityTerm] = field(default_factory=list)


@dataclass
class PodAntiAffinity:
    required: List[PodAffinityTerm] = field(default_factory=list)
    preferred: List[WeightedPodAffinityTerm] = field(default_factory=list)


@dataclass
class Affinity:
    node_affinity: Optional[NodeAffinity] = None
    pod_affinity: Optional[PodAffinity] = None
    pod_anti_affinity: Optional[PodAntiAffinity] = None


# whenUnsatisfiable values (api/core/v1 UnsatisfiableConstraintAction).
DO_NOT_SCHEDULE = "DoNotSchedule"
SCHEDULE_ANYWAY = "ScheduleAnyway"


@dataclass
class TopologySpreadConstraint:
    """Forward-ported from modern core/v1 (no 1.11 analog): bound the
    skew of matching pods across the domains of topology_key.
    DoNotSchedule constraints are hard filters; ScheduleAnyway only
    steers the TopologySpread score plane (ops/topology.py)."""

    max_skew: int = 1
    topology_key: str = ""
    when_unsatisfiable: str = DO_NOT_SCHEDULE
    label_selector: Optional[LabelSelector] = None


# --- pod --------------------------------------------------------------------


@dataclass
class Volume:
    name: str = ""
    # Non-empty source kind marks volumes that participate in NoDiskConflict
    # (reference: predicates.go:279 NoDiskConflict — GCEPD/AWSEBS/RBD/ISCSI).
    source_kind: str = ""  # "GCEPersistentDisk" | "AWSElasticBlockStore" | "RBD" | "ISCSI" | ""
    source_id: str = ""  # pd name / volume id / image spec
    read_only: bool = False
    pvc_name: str = ""  # non-empty for persistentVolumeClaim volumes
    # local ephemeral / API-backed sources (core/v1 VolumeSource fields;
    # consumed by the volume plugin layer, kubernetes_tpu/volume/)
    empty_dir: bool = False
    host_path: str = ""
    config_map: str = ""  # ConfigMap name
    secret: str = ""  # Secret name
    downward_api: Dict[str, str] = field(default_factory=dict)  # path -> fieldRef
    nfs_server: str = ""
    nfs_path: str = ""
    projected: List["Volume"] = field(default_factory=list)  # sub-sources


@dataclass
class PodSpec:
    node_name: str = ""
    node_selector: Dict[str, str] = field(default_factory=dict)
    affinity: Optional[Affinity] = None
    topology_spread_constraints: List[TopologySpreadConstraint] = \
        field(default_factory=list)
    tolerations: List[Toleration] = field(default_factory=list)
    containers: List[Container] = field(default_factory=list)
    init_containers: List[Container] = field(default_factory=list)
    volumes: List[Volume] = field(default_factory=list)
    priority: Optional[int] = None
    priority_class_name: str = ""
    scheduler_name: str = "default-scheduler"
    restart_policy: str = "Always"
    # graceful-termination budget (core/v1 default 30s); used when a
    # DELETE asks for the spec default (gracePeriodSeconds=-1)
    termination_grace_period_seconds: int = 30
    service_account_name: str = ""
    host_network: bool = False  # host-namespace flag (exec-deny, PSP)
    # pod-level wall-clock bound enforced by the kubelet
    # (kubelet/active_deadline.go): None = unbounded
    active_deadline_seconds: Optional[int] = None


@dataclass
class PodStatus:
    phase: str = "Pending"
    nominated_node_name: str = ""
    conditions: List[Tuple[str, str]] = field(default_factory=list)
    start_time: Optional[float] = None
    # CNI-assigned address (kubelet network plugin, kubelet/network.py)
    pod_ip: str = ""
    # stamped by the kubelet from pod_qos_class (reference: qos.go via
    # kubelet status manager; PodStatus.QOSClass)
    qos_class: str = ""


@dataclass
class Pod:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)

    @property
    def name(self):
        return self.metadata.name

    @property
    def namespace(self):
        return self.metadata.namespace

    @property
    def uid(self):
        return self.metadata.uid

    def full_name(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"


# --- node -------------------------------------------------------------------

# Well-known labels (reference: pkg/kubelet/apis/well_known_labels.go).
LABEL_HOSTNAME = "kubernetes.io/hostname"
LABEL_ZONE = "failure-domain.beta.kubernetes.io/zone"
LABEL_REGION = "failure-domain.beta.kubernetes.io/region"

# Interconnect-topology + heterogeneity labels (no 1.11 analog; the
# forward-ported topology subsystem, ops/topology.py). Racks nest inside
# superpods — get_rack_key/get_superpod_key encode the hierarchy so a
# rack id's string key is prefixed by its superpod's, and link distance
# is derivable from interned-id prefixes.
LABEL_RACK = "topology.kubernetes.io/rack"
LABEL_SUPERPOD = "topology.kubernetes.io/superpod"
LABEL_ACCEL_GEN = "accelerator.kubernetes.io/generation"

# Node condition types (reference: api/core/v1/types.go NodeConditionType).
NODE_READY = "Ready"
NODE_OUT_OF_DISK = "OutOfDisk"
NODE_MEMORY_PRESSURE = "MemoryPressure"
NODE_DISK_PRESSURE = "DiskPressure"
NODE_PID_PRESSURE = "PIDPressure"
NODE_NETWORK_UNAVAILABLE = "NetworkUnavailable"

COND_TRUE = "True"
COND_FALSE = "False"
COND_UNKNOWN = "Unknown"


@dataclass
class NodeCondition:
    type: str
    status: str = COND_TRUE
    reason: str = ""


@dataclass
class ContainerImage:
    names: List[str] = field(default_factory=list)
    size_bytes: int = 0


@dataclass
class NodeSpec:
    unschedulable: bool = False
    taints: List[Taint] = field(default_factory=list)
    pod_cidr: str = ""  # allocated by the nodeipam controller
    provider_id: str = ""  # cloud instance identity (<provider>://<id>)


@dataclass
class NodeAddress:
    type: str = ""  # InternalIP | ExternalIP | Hostname
    address: str = ""


@dataclass
class NodeStatus:
    capacity: Dict[str, int] = field(default_factory=dict)
    allocatable: Dict[str, int] = field(default_factory=dict)
    conditions: List[NodeCondition] = field(default_factory=list)
    images: List[ContainerImage] = field(default_factory=list)
    addresses: List[NodeAddress] = field(default_factory=list)
    # attach/detach controller state (core/v1 NodeStatus.VolumesAttached /
    # VolumesInUse; maintained by controllers/attachdetach.py)
    volumes_attached: List[str] = field(default_factory=list)
    volumes_in_use: List[str] = field(default_factory=list)
    # NodeDaemonEndpoints.KubeletEndpoint.Port (core/v1 types.go): where
    # this node's kubelet serves logs/exec; 0 = no server
    kubelet_port: int = 0


@dataclass
class Node:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: NodeSpec = field(default_factory=NodeSpec)
    status: NodeStatus = field(default_factory=NodeStatus)

    @property
    def name(self):
        return self.metadata.name


def get_zone_key(node: Node) -> str:
    """Reference: pkg/util/node/node.go GetZoneKey — region + zone labels
    joined with a NUL separator; empty when neither label is present."""
    labels = node.metadata.labels or {}
    region = labels.get(LABEL_REGION, "")
    zone = labels.get(LABEL_ZONE, "")
    if not region and not zone:
        return ""
    return region + ":\x00:" + zone


def get_superpod_key(node: Node) -> str:
    """Hierarchical superpod key ("sp:<v>"); empty when unlabeled."""
    v = (node.metadata.labels or {}).get(LABEL_SUPERPOD, "")
    return f"sp:{v}" if v else ""


def get_rack_key(node: Node) -> str:
    """Hierarchical rack key ("sp:<v>/rk:<r>"): prefixed by the node's
    superpod key so two racks in the same superpod share a string (and
    therefore an interned-id) prefix; empty when no rack label."""
    labels = node.metadata.labels or {}
    rack = labels.get(LABEL_RACK, "")
    if not rack:
        return ""
    return f"{get_superpod_key(node) or 'sp:'}/rk:{rack}"


def get_accel_gen(node: Node) -> int:
    """Accelerator generation rank from LABEL_ACCEL_GEN (0 = unlabeled
    or unparseable; negative ranks clamp to 0 so the dense i32 column's
    zero stays the "no information" value)."""
    raw = (node.metadata.labels or {}).get(LABEL_ACCEL_GEN, "")
    try:
        return max(0, int(raw))
    except (TypeError, ValueError):
        return 0


# --- persistent volumes ------------------------------------------------------


@dataclass
class PersistentVolumeSpec:
    # Volume source (same convention as Volume.source_kind/source_id):
    # "AWSElasticBlockStore" | "GCEPersistentDisk" | "AzureDisk" | ...
    source_kind: str = ""
    source_id: str = ""  # for CSI: the driver's volume handle
    csi_driver: str = ""  # CSI only: which registered driver owns it
    capacity: Dict[str, int] = field(default_factory=dict)
    storage_class_name: str = ""
    # Volume topology constraint (reference: 1.11-era PVs carry zone/region
    # labels consumed by VolumeZone, predicates.go:582; node affinity on PVs
    # is the VolumeScheduling-gated successor checked by VolumeBinding).
    node_affinity: Optional[NodeSelector] = None


@dataclass
class PersistentVolume:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PersistentVolumeSpec = field(default_factory=PersistentVolumeSpec)

    @property
    def name(self):
        return self.metadata.name


@dataclass
class PersistentVolumeClaimSpec:
    storage_class_name: str = ""
    volume_name: str = ""  # non-empty once bound to a PV
    requests: Dict[str, int] = field(default_factory=dict)
    # StorageClass volumeBindingMode, flattened onto the claim (no
    # StorageClass object in this model): "Immediate" claims are bound by
    # PersistentVolumeController as soon as a PV matches;
    # "WaitForFirstConsumer" claims are bound by the scheduler's
    # VolumeBinder at pod commit, when the node is known — exactly one
    # writer owns each claim, so the two can never race on volume_name
    volume_binding_mode: str = "Immediate"


@dataclass
class PersistentVolumeClaimStatus:
    phase: str = ""  # Pending | Bound | Lost
    # the GRANTED size, which trails spec.requests during an expansion
    capacity: Dict[str, int] = field(default_factory=dict)
    # (type, status) pairs; expansion uses Resizing /
    # FileSystemResizePending (core/v1 PersistentVolumeClaimCondition)
    conditions: List[Tuple[str, str]] = field(default_factory=list)


@dataclass
class PersistentVolumeClaim:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PersistentVolumeClaimSpec = field(default_factory=PersistentVolumeClaimSpec)
    status: PersistentVolumeClaimStatus = field(
        default_factory=PersistentVolumeClaimStatus)

    @property
    def name(self):
        return self.metadata.name


# --- workload owners (for spreading) & PDBs ---------------------------------


@dataclass
class ServicePort:
    name: str = ""
    port: int = 0
    target_port: int = 0
    protocol: str = "TCP"
    node_port: int = 0


@dataclass
class ServiceSpec:
    selector: Dict[str, str] = field(default_factory=dict)
    ports: List[ServicePort] = field(default_factory=list)
    cluster_ip: str = ""
    type: str = "ClusterIP"  # ClusterIP | NodePort | LoadBalancer | ExternalName
    session_affinity: str = "None"  # None | ClientIP
    session_affinity_timeout: int = 10800  # sessionAffinityConfig.clientIP
    external_ips: List[str] = field(default_factory=list)
    load_balancer_ip: str = ""
    external_traffic_policy: str = "Cluster"  # Cluster | Local
    health_check_node_port: int = 0
    external_name: str = ""


@dataclass
class LoadBalancerIngress:
    ip: str = ""
    hostname: str = ""


@dataclass
class LoadBalancerStatus:
    ingress: List[LoadBalancerIngress] = field(default_factory=list)


@dataclass
class ServiceStatus:
    load_balancer: LoadBalancerStatus = field(default_factory=LoadBalancerStatus)


@dataclass
class Service:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ServiceSpec = field(default_factory=ServiceSpec)
    status: ServiceStatus = field(default_factory=ServiceStatus)

    def __init__(self, metadata=None, spec=None, selector=None, status=None):
        # `selector=` kwarg kept for scheduler-side call sites that treat a
        # Service as just its label selector (selector_spreading.go view)
        self.metadata = metadata or ObjectMeta()
        self.spec = spec or ServiceSpec()
        self.status = status or ServiceStatus()
        if selector is not None:
            self.spec.selector = selector

    @property
    def selector(self) -> Dict[str, str]:
        return self.spec.selector


@dataclass
class PodTemplateSpec:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)


@dataclass
class ReplicationControllerSpec:
    replicas: int = 1
    selector: Dict[str, str] = field(default_factory=dict)
    template: Optional[PodTemplateSpec] = None


@dataclass
class ReplicationControllerStatus:
    replicas: int = 0
    ready_replicas: int = 0
    observed_generation: int = 0


@dataclass
class ReplicationController:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ReplicationControllerSpec = field(default_factory=ReplicationControllerSpec)
    status: ReplicationControllerStatus = field(default_factory=ReplicationControllerStatus)

    def __init__(self, metadata=None, spec=None, status=None, selector=None):
        self.metadata = metadata or ObjectMeta()
        self.spec = spec or ReplicationControllerSpec()
        self.status = status or ReplicationControllerStatus()
        if selector is not None:
            self.spec.selector = selector

    @property
    def selector(self) -> Dict[str, str]:
        return self.spec.selector


@dataclass
class ReplicaSetSpec:
    replicas: int = 1
    selector: Optional[LabelSelector] = None
    template: Optional[PodTemplateSpec] = None
    min_ready_seconds: int = 0


@dataclass
class ReplicaSetStatus:
    replicas: int = 0
    ready_replicas: int = 0
    available_replicas: int = 0
    fully_labeled_replicas: int = 0
    observed_generation: int = 0


@dataclass
class ReplicaSet:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ReplicaSetSpec = field(default_factory=ReplicaSetSpec)
    status: ReplicaSetStatus = field(default_factory=ReplicaSetStatus)

    def __init__(self, metadata=None, spec=None, status=None, selector=None):
        self.metadata = metadata or ObjectMeta()
        self.spec = spec or ReplicaSetSpec()
        self.status = status or ReplicaSetStatus()
        if selector is not None:
            self.spec.selector = selector

    @property
    def selector(self) -> Optional[LabelSelector]:
        return self.spec.selector


@dataclass
class StatefulSetUpdateStrategy:
    """apps/v1 StatefulSetUpdateStrategy: RollingUpdate replaces stale
    pods in reverse ordinal order down to (but not including)
    `partition`; OnDelete waits for manual deletion."""

    type: str = "RollingUpdate"  # RollingUpdate | OnDelete
    partition: int = 0


@dataclass
class StatefulSetSpec:
    replicas: int = 1
    selector: Optional[LabelSelector] = None
    template: Optional[PodTemplateSpec] = None
    service_name: str = ""
    pod_management_policy: str = "OrderedReady"
    update_strategy: StatefulSetUpdateStrategy = field(
        default_factory=StatefulSetUpdateStrategy)
    revision_history_limit: int = 10
    # per-ordinal PVCs minted as <template>-<set>-<ordinal>; retained on
    # scale-down (apps/v1 StatefulSetSpec.VolumeClaimTemplates)
    volume_claim_templates: List[PersistentVolumeClaim] = field(
        default_factory=list)


@dataclass
class StatefulSetStatus:
    replicas: int = 0
    ready_replicas: int = 0
    current_replicas: int = 0
    updated_replicas: int = 0
    # names of the ControllerRevisions serving current/target identity
    # (apps/v1 StatefulSetStatus.CurrentRevision/UpdateRevision)
    current_revision: str = ""
    update_revision: str = ""
    observed_generation: int = 0


@dataclass
class StatefulSet:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: StatefulSetSpec = field(default_factory=StatefulSetSpec)
    status: StatefulSetStatus = field(default_factory=StatefulSetStatus)

    def __init__(self, metadata=None, spec=None, status=None, selector=None):
        self.metadata = metadata or ObjectMeta()
        self.spec = spec or StatefulSetSpec()
        self.status = status or StatefulSetStatus()
        if selector is not None:
            self.spec.selector = selector

    @property
    def selector(self) -> Optional[LabelSelector]:
        return self.spec.selector


@dataclass
class DeploymentStrategy:
    type: str = "RollingUpdate"  # or "Recreate"
    max_unavailable: int = 1
    max_surge: int = 1


@dataclass
class DeploymentSpec:
    replicas: int = 1
    selector: Optional[LabelSelector] = None
    template: Optional[PodTemplateSpec] = None
    strategy: DeploymentStrategy = field(default_factory=DeploymentStrategy)
    revision_history_limit: int = 10
    paused: bool = False


@dataclass
class DeploymentStatus:
    replicas: int = 0
    updated_replicas: int = 0
    ready_replicas: int = 0
    available_replicas: int = 0
    unavailable_replicas: int = 0
    observed_generation: int = 0


@dataclass
class Deployment:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: DeploymentSpec = field(default_factory=DeploymentSpec)
    status: DeploymentStatus = field(default_factory=DeploymentStatus)


@dataclass
class DaemonSetUpdateStrategy:
    """apps/v1 DaemonSetUpdateStrategy: RollingUpdate replaces stale
    pods bounded by maxUnavailable; OnDelete waits for manual
    deletion."""

    type: str = "RollingUpdate"  # RollingUpdate | OnDelete
    max_unavailable: int = 1


@dataclass
class DaemonSetSpec:
    selector: Optional[LabelSelector] = None
    template: Optional[PodTemplateSpec] = None
    update_strategy: DaemonSetUpdateStrategy = field(
        default_factory=DaemonSetUpdateStrategy)
    revision_history_limit: int = 10


@dataclass
class DaemonSetStatus:
    current_number_scheduled: int = 0
    desired_number_scheduled: int = 0
    number_ready: int = 0
    number_misscheduled: int = 0
    updated_number_scheduled: int = 0
    observed_generation: int = 0


@dataclass
class DaemonSet:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: DaemonSetSpec = field(default_factory=DaemonSetSpec)
    status: DaemonSetStatus = field(default_factory=DaemonSetStatus)


@dataclass
class ControllerRevision:
    """apps/v1 ControllerRevision: an immutable, numbered snapshot of a
    workload's pod template, owned by its DaemonSet/StatefulSet and used
    for rollout history/undo. Reference: pkg/apis/apps/v1/types.go
    (ControllerRevision), managed through
    pkg/controller/history/controller_history.go."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    # wire-form snapshot (the encoded pod template under {"spec":
    # {"template": ...}}, matching the reference's raw patch payload)
    data: Dict = field(default_factory=dict)
    revision: int = 0


@dataclass
class JobSpec:
    parallelism: int = 1
    completions: int = 1
    backoff_limit: int = 6
    # job-level wall-clock bound (job_controller.go pastActiveDeadline):
    # None = unbounded
    active_deadline_seconds: Optional[int] = None
    selector: Optional[LabelSelector] = None
    template: Optional[PodTemplateSpec] = None


@dataclass
class JobStatus:
    active: int = 0
    succeeded: int = 0
    failed: int = 0
    start_time: Optional[float] = None
    completion_time: Optional[float] = None
    conditions: List[Tuple[str, str]] = field(default_factory=list)


@dataclass
class Job:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: JobSpec = field(default_factory=JobSpec)
    status: JobStatus = field(default_factory=JobStatus)


@dataclass
class CronJobSpec:
    schedule: str = "* * * * *"
    suspend: bool = False
    concurrency_policy: str = "Allow"  # Allow | Forbid | Replace
    job_template: Optional[JobSpec] = None
    job_template_meta: ObjectMeta = field(default_factory=ObjectMeta)


@dataclass
class CronJobStatus:
    last_schedule_time: Optional[float] = None
    active: List[str] = field(default_factory=list)  # job names


@dataclass
class CronJob:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: CronJobSpec = field(default_factory=CronJobSpec)
    status: CronJobStatus = field(default_factory=CronJobStatus)


@dataclass
class LimitRangeItem:
    """core/v1 LimitRangeItem (consumed by the LimitRanger admission
    plugin, plugin/pkg/admission/limitranger)."""

    type: str = "Container"  # "Container" | "Pod"
    max: Dict[str, int] = field(default_factory=dict)
    min: Dict[str, int] = field(default_factory=dict)
    default: Dict[str, int] = field(default_factory=dict)
    default_request: Dict[str, int] = field(default_factory=dict)


@dataclass
class LimitRangeSpec:
    limits: List[LimitRangeItem] = field(default_factory=list)


@dataclass
class LimitRange:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: LimitRangeSpec = field(default_factory=LimitRangeSpec)


@dataclass
class CustomResourceNames:
    """apiextensions CustomResourceDefinitionNames (reference:
    staging/src/k8s.io/apiextensions-apiserver/pkg/apis/apiextensions/
    types.go)."""

    kind: str = ""
    plural: str = ""
    singular: str = ""


@dataclass
class CustomResourceValidation:
    """apiextensions CustomResourceValidation: an OpenAPI v3 schema the
    apiserver enforces on every create/update of the custom kind
    (apiextensions-apiserver pkg/apiserver/validation/validation.go)."""

    open_api_v3_schema: Dict = field(default_factory=dict)


@dataclass
class CustomResourceSubresourceScale:
    """Dotted JSON paths mapping the custom kind onto the Scale shape
    (apiextensions CustomResourceSubresourceScale)."""

    spec_replicas_path: str = ".spec.replicas"
    status_replicas_path: str = ".status.replicas"
    label_selector_path: str = ""


@dataclass
class CustomResourceSubresources:
    """apiextensions CustomResourceSubresources (1.11): opting a custom
    kind into /status (spec-status write isolation) and /scale."""

    status: bool = False
    scale: Optional[CustomResourceSubresourceScale] = None


@dataclass
class CustomResourceDefinitionSpec:
    group: str = ""
    version: str = "v1"  # the storage version
    # additional served versions (apiextensions v1beta1 spec.versions,
    # added in the 1.11 cycle); all share one schema, tag-only conversion
    versions: List[str] = field(default_factory=list)
    scope: str = "Namespaced"  # or "Cluster"
    names: CustomResourceNames = field(default_factory=CustomResourceNames)
    validation: Optional[CustomResourceValidation] = None
    subresources: Optional[CustomResourceSubresources] = None


@dataclass
class CustomResourceDefinition:
    """Dynamic resource registration: creating one of these makes the
    apiserver serve CRUD+watch for the named kind (reference:
    apiextensions-apiserver customresource_handler.go)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: CustomResourceDefinitionSpec = field(
        default_factory=CustomResourceDefinitionSpec)


@dataclass
class CustomObject:
    """An instance of a CRD-defined kind: schema-free spec/status plus
    standard object metadata (the reference's unstructured.Unstructured).
    Carries its own kind/apiVersion tags because every custom kind shares
    this Python type."""

    kind: str = ""
    api_version: str = ""
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: Dict[str, object] = field(default_factory=dict)
    status: Dict[str, object] = field(default_factory=dict)

    @property
    def name(self):
        return self.metadata.name

    @property
    def namespace(self):
        return self.metadata.namespace


@dataclass
class CrossVersionObjectReference:
    """autoscaling/v1 CrossVersionObjectReference — the HPA's scale
    target (Deployment/ReplicaSet/ReplicationController/StatefulSet)."""

    kind: str = "Deployment"
    name: str = ""
    api_version: str = "apps/v1"


@dataclass
class HorizontalPodAutoscalerSpec:
    """autoscaling/v1 (reference: pkg/apis/autoscaling/types.go;
    controller pkg/controller/podautoscaler/horizontal.go:80)."""

    scale_target_ref: CrossVersionObjectReference = field(
        default_factory=CrossVersionObjectReference)
    min_replicas: int = 1
    max_replicas: int = 10
    target_cpu_utilization_percentage: int = 80


@dataclass
class HorizontalPodAutoscalerStatus:
    current_replicas: int = 0
    desired_replicas: int = 0
    current_cpu_utilization_percentage: Optional[int] = None
    last_scale_time: Optional[float] = None
    observed_generation: int = 0


@dataclass
class HorizontalPodAutoscaler:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: HorizontalPodAutoscalerSpec = field(
        default_factory=HorizontalPodAutoscalerSpec)
    status: HorizontalPodAutoscalerStatus = field(
        default_factory=HorizontalPodAutoscalerStatus)


@dataclass
class PodMetrics:
    """metrics.k8s.io PodMetrics analog (what metrics-server publishes
    and the HPA's metrics client reads — reference
    pkg/controller/podautoscaler/metrics/). metadata.name matches the
    pod; usage holds aggregate container usage (cpu in millicores)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    usage: Dict[str, int] = field(default_factory=dict)


# --- gang scheduling (coscheduling PodGroup) ---------------------------------
# Forward-port: the 1.11 reference has no gang scheduling; the API shape
# follows the coscheduling ecosystem (kube-batch / the scheduler-plugins
# PodGroup CRD) — plain pods opt in via the pod-group annotations, and a
# PodGroup object may carry the authoritative minMember.

POD_GROUP_NAME_ANNOTATION = "pod-group.scheduling.k8s.io/name"
POD_GROUP_MIN_AVAILABLE_ANNOTATION = "pod-group.scheduling.k8s.io/min-available"


@dataclass
class PodGroupSpec:
    # minimum number of member pods that must be placeable SIMULTANEOUSLY
    # before any member is bound (all-or-nothing admission)
    min_member: int = 1


@dataclass
class PodGroupStatus:
    phase: str = "Pending"  # Pending | Running | Unschedulable
    scheduled: int = 0


@dataclass
class PodGroup:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodGroupSpec = field(default_factory=PodGroupSpec)
    status: PodGroupStatus = field(default_factory=PodGroupStatus)

    @property
    def name(self):
        return self.metadata.name


def pod_group_name(pod: "Pod") -> Optional[str]:
    """The pod's gang name, or None for ordinary pods. ONE dict lookup —
    this sits on the queue-admission hot path for every pod."""
    ann = pod.metadata.annotations
    if not ann:
        return None
    return ann.get(POD_GROUP_NAME_ANNOTATION) or None


def pod_group_min_available(pod: "Pod") -> Optional[int]:
    """minMember from the pod's own annotation (used when no PodGroup
    object exists); None when absent or unparseable."""
    ann = pod.metadata.annotations
    if not ann:
        return None
    raw = ann.get(POD_GROUP_MIN_AVAILABLE_ANNOTATION)
    if raw is None:
        return None
    try:
        return int(raw)
    except (TypeError, ValueError):
        return None


# -- scheduler weight profiles (kind "weightprofiles") ------------------------
#
# ConfigMap-style objects carrying a scoring weight table for the
# shadow-scoring observatory (sched/weights.py): candidates are
# re-scored counterfactually against live traffic, the live one
# hot-swaps the production weight vector between rounds. No reference
# analog — the reference's priority weights are process-lifetime
# Policy/provider config.

WEIGHT_PROFILE_ROLE_CANDIDATE = "candidate"
WEIGHT_PROFILE_ROLE_LIVE = "live"


@dataclass
class WeightProfileSpec:
    # SCORE_STACK-keyed raw weights (ops/scores.py), e.g.
    # {"LeastRequested": 1.0, "MostRequested": 2.5}; unnamed rows
    # default to 0, HostExtra is pinned to 1 (rows arrive pre-weighted)
    weights: Dict[str, float] = field(default_factory=dict)
    # "candidate": shadow-scored only, zero effect on placements;
    # "live": this profile's vector IS the production weight vector
    role: str = WEIGHT_PROFILE_ROLE_CANDIDATE


@dataclass
class WeightProfile:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: WeightProfileSpec = field(default_factory=WeightProfileSpec)

    @property
    def name(self):
        return self.metadata.name


@dataclass
class PodDisruptionBudgetSpec:
    selector: Optional[LabelSelector] = None
    min_available: Optional[int] = None
    max_unavailable: Optional[int] = None


@dataclass
class PodDisruptionBudgetStatus:
    disruptions_allowed: int = 0
    current_healthy: int = 0
    desired_healthy: int = 0
    expected_pods: int = 0
    observed_generation: int = 0


@dataclass
class PodDisruptionBudget:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodDisruptionBudgetSpec = field(default_factory=PodDisruptionBudgetSpec)
    status: PodDisruptionBudgetStatus = field(default_factory=PodDisruptionBudgetStatus)

    def __init__(self, metadata=None, spec=None, status=None,
                 selector=None, disruptions_allowed=None):
        self.metadata = metadata or ObjectMeta()
        self.spec = spec or PodDisruptionBudgetSpec()
        self.status = status or PodDisruptionBudgetStatus()
        if selector is not None:
            self.spec.selector = selector
        if disruptions_allowed is not None:
            self.status.disruptions_allowed = disruptions_allowed

    @property
    def selector(self) -> Optional[LabelSelector]:
        return self.spec.selector

    @property
    def disruptions_allowed(self) -> int:
        return self.status.disruptions_allowed


# --- namespaces, endpoints, events, quotas, leases ---------------------------


@dataclass
class NamespaceSpec:
    finalizers: List[str] = field(default_factory=lambda: ["kubernetes"])


@dataclass
class NamespaceStatus:
    phase: str = "Active"  # Active | Terminating


@dataclass
class Namespace:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: NamespaceSpec = field(default_factory=NamespaceSpec)
    status: NamespaceStatus = field(default_factory=NamespaceStatus)

    def __post_init__(self):
        self.metadata.namespace = ""  # cluster-scoped


@dataclass
class EndpointAddress:
    ip: str = ""
    node_name: str = ""
    target_pod: str = ""  # namespace/name of backing pod


@dataclass
class EndpointPort:
    name: str = ""
    port: int = 0
    protocol: str = "TCP"


@dataclass
class EndpointSubset:
    addresses: List[EndpointAddress] = field(default_factory=list)
    not_ready_addresses: List[EndpointAddress] = field(default_factory=list)
    ports: List[EndpointPort] = field(default_factory=list)


@dataclass
class Endpoints:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    subsets: List[EndpointSubset] = field(default_factory=list)


@dataclass
class EventObject:
    """An Event API object (reference: core/v1 Event; recorded via
    client-go/tools/record/event.go:56 EventRecorder)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    involved_kind: str = ""
    involved_name: str = ""
    involved_namespace: str = ""
    reason: str = ""
    message: str = ""
    type: str = "Normal"  # Normal | Warning
    count: int = 1
    source_component: str = ""
    first_timestamp: float = 0.0
    last_timestamp: float = 0.0


@dataclass
class ResourceQuotaSpec:
    hard: Dict[str, int] = field(default_factory=dict)
    # quota scopes (pkg/quota scopes.go): the quota only counts objects
    # the scope set matches — BestEffort/NotBestEffort (pod QoS),
    # Terminating/NotTerminating (pod activeDeadlineSeconds set/unset)
    scopes: List[str] = field(default_factory=list)


@dataclass
class ResourceQuotaStatus:
    hard: Dict[str, int] = field(default_factory=dict)
    used: Dict[str, int] = field(default_factory=dict)


@dataclass
class ResourceQuota:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ResourceQuotaSpec = field(default_factory=ResourceQuotaSpec)
    status: ResourceQuotaStatus = field(default_factory=ResourceQuotaStatus)


@dataclass
class ServiceAccount:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    secrets: List[str] = field(default_factory=list)
    # v1 ServiceAccount.AutomountServiceAccountToken: None = mount
    automount_service_account_token: Optional[bool] = None


@dataclass
class ResourceAttributes:
    """authorization/v1 ResourceAttributes (reference:
    pkg/apis/authorization/types.go). `resource` may carry a
    subresource as 'pods/exec', matching the authorizer's attribute
    form."""

    verb: str = ""
    resource: str = ""
    namespace: Optional[str] = None
    name: Optional[str] = None


@dataclass
class SelfSubjectAccessReviewSpec:
    resource_attributes: ResourceAttributes = field(
        default_factory=ResourceAttributes)


@dataclass
class SubjectAccessReviewStatus:
    allowed: bool = False
    reason: str = ""


@dataclass
class SelfSubjectAccessReview:
    """Virtual (non-stored) review resource: POSTing one asks the server
    'can I, the requesting identity, do this?' (reference:
    pkg/registry/authorization/selfsubjectaccessreview/rest.go:48 —
    evaluated against the live authorizer, never persisted). Drives
    `kubectl auth can-i`."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: SelfSubjectAccessReviewSpec = field(
        default_factory=SelfSubjectAccessReviewSpec)
    status: SubjectAccessReviewStatus = field(
        default_factory=SubjectAccessReviewStatus)


@dataclass
class CertificateSigningRequestSpec:
    """certificates/v1beta1 (reference: pkg/apis/certificates/types.go;
    controllers pkg/controller/certificates/)."""

    request: str = ""  # CSR payload (PEM in the reference; opaque here)
    username: str = ""
    groups: List[str] = field(default_factory=list)
    usages: List[str] = field(default_factory=list)


@dataclass
class CertificateSigningRequestStatus:
    # conditions: list of (type, reason) — "Approved"/"Denied"
    conditions: List[Tuple[str, str]] = field(default_factory=list)
    certificate: str = ""  # issued by the signer once approved


@dataclass
class CertificateSigningRequest:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: CertificateSigningRequestSpec = field(
        default_factory=CertificateSigningRequestSpec)
    status: CertificateSigningRequestStatus = field(
        default_factory=CertificateSigningRequestStatus)

    @property
    def approved(self) -> bool:
        return any(t == "Approved" for t, _ in self.status.conditions)

    @property
    def denied(self) -> bool:
        return any(t == "Denied" for t, _ in self.status.conditions)


@dataclass
class Secret:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    type: str = "Opaque"
    data: Dict[str, str] = field(default_factory=dict)


@dataclass
class ConfigMap:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    data: Dict[str, str] = field(default_factory=dict)


@dataclass
class PodSecurityPolicySpec:
    """policy/v1beta1 PodSecurityPolicySpec over this model's flattened
    security surface (reference pkg/apis/policy/types.go:150)."""

    privileged: bool = False  # allow privileged containers
    # volume source kinds a pod may use; ["*"] allows all. Names follow
    # the Volume fields: emptyDir, hostPath, configMap, secret,
    # downwardAPI, nfs, persistentVolumeClaim, projected + PD kinds
    volumes: List[str] = field(default_factory=lambda: ["*"])
    allowed_host_paths: List[str] = field(default_factory=list)  # prefixes
    host_ports: List[Tuple[int, int]] = field(default_factory=list)  # ranges


@dataclass
class PodSecurityPolicy:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSecurityPolicySpec = field(default_factory=PodSecurityPolicySpec)


@dataclass
class WebhookRule:
    """admissionregistration/v1beta1 RuleWithOperations (types.go:52)."""

    operations: List[str] = field(default_factory=lambda: ["*"])
    resources: List[str] = field(default_factory=lambda: ["*"])


@dataclass
class Webhook:
    """One webhook in a configuration (types.go:133 Webhook). The
    reference addresses service refs or URLs; this model uses URLs (a
    service ref resolves through the same endpoints the aggregator
    uses)."""

    name: str = ""
    url: str = ""
    rules: List[WebhookRule] = field(default_factory=list)
    failure_policy: str = "Ignore"  # Ignore | Fail (default per 1.11)
    timeout_seconds: int = 10


@dataclass
class WebhookConfiguration:
    """Base for the two webhook configuration kinds. They must be
    DISTINCT types: the scheme maps python type -> kind, and sharing one
    class would serve every configuration as the first-registered kind."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    webhooks: List[Webhook] = field(default_factory=list)


@dataclass
class MutatingWebhookConfiguration(WebhookConfiguration):
    pass


@dataclass
class ValidatingWebhookConfiguration(WebhookConfiguration):
    pass


@dataclass
class APIServiceSpec:
    """kube-aggregator apiregistration/v1 APIServiceSpec
    (staging/src/k8s.io/kube-aggregator/pkg/apis/apiregistration/
    types.go:28): which Service serves this API group/version. Empty
    service_name = Local (served by this apiserver)."""

    group: str = ""
    version: str = ""
    service_name: str = ""
    service_namespace: str = "default"
    service_port: int = 443
    group_priority_minimum: int = 0
    version_priority: int = 0


@dataclass
class APIServiceCondition:
    type: str = "Available"
    status: str = COND_FALSE
    reason: str = ""


@dataclass
class APIServiceStatus:
    conditions: List[APIServiceCondition] = field(default_factory=list)


@dataclass
class APIService:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: APIServiceSpec = field(default_factory=APIServiceSpec)
    status: APIServiceStatus = field(default_factory=APIServiceStatus)


@dataclass
class PriorityClass:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    value: int = 0
    global_default: bool = False
    description: str = ""

    def __post_init__(self):
        self.metadata.namespace = ""  # cluster-scoped


# --- RBAC (rbac.authorization.k8s.io/v1) -------------------------------------
# Reference: staging/src/k8s.io/api/rbac/v1/types.go; evaluated per
# request by plugin/pkg/auth/authorizer/rbac/rbac.go:74.


@dataclass
class RBACPolicyRule:
    """rbac/v1 PolicyRule: verbs x apiGroups x resources, optionally
    narrowed to resourceNames; OR nonResourceURLs for path requests."""

    verbs: List[str] = field(default_factory=list)
    api_groups: List[str] = field(default_factory=list)
    resources: List[str] = field(default_factory=list)
    resource_names: List[str] = field(default_factory=list)
    non_resource_urls: List[str] = field(default_factory=list)


@dataclass
class RBACSubject:
    kind: str = "User"  # User | Group | ServiceAccount
    name: str = ""
    namespace: str = ""  # ServiceAccount subjects only


@dataclass
class RoleRef:
    kind: str = "ClusterRole"  # Role | ClusterRole
    name: str = ""


@dataclass
class Role:
    """Namespaced rules (rbac/v1 Role)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    rules: List[RBACPolicyRule] = field(default_factory=list)


@dataclass
class ClusterRole:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    rules: List[RBACPolicyRule] = field(default_factory=list)
    # aggregationRule.clusterRoleSelectors: this role's rules are the
    # UNION of rules from ClusterRoles matching any selector, maintained
    # by the clusterroleaggregation controller
    aggregation_selectors: List[LabelSelector] = field(default_factory=list)

    def __post_init__(self):
        self.metadata.namespace = ""  # cluster-scoped


@dataclass
class RoleBinding:
    """Grants a Role (or ClusterRole) within the binding's namespace."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    subjects: List[RBACSubject] = field(default_factory=list)
    role_ref: RoleRef = field(default_factory=RoleRef)


@dataclass
class ClusterRoleBinding:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    subjects: List[RBACSubject] = field(default_factory=list)
    role_ref: RoleRef = field(default_factory=RoleRef)

    def __post_init__(self):
        self.metadata.namespace = ""  # cluster-scoped


@dataclass
class PodPreset:
    """settings.k8s.io/v1alpha1 PodPreset: env/volumes injected into
    selector-matching pods at admission (plugin/pkg/admission/podpreset)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    selector: Optional[LabelSelector] = None  # None -> every pod in ns
    env: Dict[str, str] = field(default_factory=dict)
    volumes: List[Volume] = field(default_factory=list)


@dataclass
class StorageClass:
    """storage.k8s.io/v1 StorageClass (flattened: the
    is-default-class annotation becomes a field)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    provisioner: str = ""
    is_default: bool = False
    volume_binding_mode: str = "Immediate"
    # gates PVC growth (StorageClass.AllowVolumeExpansion, 1.11's
    # ExpandPersistentVolumes feature + PersistentVolumeClaimResize
    # admission)
    allow_volume_expansion: bool = False


@dataclass
class CSIDriver:
    """Out-of-process CSI driver registration (the CSIDriver object of
    later Kubernetes + the kubelet plugin-socket watcher, collapsed:
    name = driver name, endpoint = the driver's protocol URL;
    volume/csi.py)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    endpoint: str = ""


@dataclass
class LeaseRecord:
    """Leader-election lock record (reference: client-go/tools/leaderelection/
    resourcelock — LeaderElectionRecord stored in an Endpoints annotation)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    holder_identity: str = ""
    lease_duration_seconds: float = 15.0
    acquire_time: float = 0.0
    renew_time: float = 0.0
    leader_transitions: int = 0


# --- derived pod semantics ---------------------------------------------------


def get_resource_request(pod: Pod) -> Dict[str, int]:
    """Effective pod request: sum over containers, max against each init
    container (reference: predicates.go:667 GetResourceRequest)."""
    out: Dict[str, int] = {}
    for c in pod.spec.containers:
        for name, q in c.resources.requests.items():
            out[name] = out.get(name, 0) + q
    for c in pod.spec.init_containers:
        for name, q in c.resources.requests.items():
            if q > out.get(name, 0):
                out[name] = q
    return out


DEFAULT_MILLI_CPU_REQUEST = 100  # 0.1 core
DEFAULT_MEMORY_REQUEST = 200 * 1024 * 1024  # 200 MB


def get_nonzero_requests(pod: Pod) -> Tuple[int, int]:
    """(milliCPU, memory) with per-container defaulting of *unset* values
    (reference: algorithm/priorities/util/non_zero.go:38 and
    resource_allocation.go:115 getNonZeroRequests)."""
    cpu = mem = 0
    for c in pod.spec.containers:
        reqs = c.resources.requests
        cpu += reqs[res.CPU] if res.CPU in reqs else DEFAULT_MILLI_CPU_REQUEST
        mem += reqs[res.MEMORY] if res.MEMORY in reqs else DEFAULT_MEMORY_REQUEST
    return cpu, mem


def is_pod_active(pod: Pod) -> bool:
    """Not Succeeded/Failed and not being deleted — the liveness rule
    shared by controllers and quota (controller_utils.go IsPodActive,
    quota core evaluator)."""
    return (pod.status.phase not in ("Succeeded", "Failed")
            and pod.metadata.deletion_timestamp is None)


QOS_GUARANTEED = "Guaranteed"
QOS_BURSTABLE = "Burstable"
QOS_BEST_EFFORT = "BestEffort"


def pod_qos_class(pod: Pod) -> str:
    """The pod's QoS class (pkg/apis/core/v1/helper/qos/qos.go
    GetPodQOS): Guaranteed iff every container sets cpu+memory limits
    with requests either absent or equal to the limits (absent requests
    default to limits); BestEffort iff nothing sets any request or
    limit; Burstable otherwise. Drives eviction ranking and the
    kubelet's cgroup-tier analog."""
    requests: Dict[str, int] = {}
    limits: Dict[str, int] = {}
    guaranteed = True
    # qos.go iterates init and regular containers alike
    for c in list(pod.spec.containers) + list(pod.spec.init_containers):
        for k, v in c.resources.requests.items():
            if k in (res.CPU, res.MEMORY) and v:
                requests[k] = requests.get(k, 0) + v
        lim_set = set()
        for k, v in c.resources.limits.items():
            if k in (res.CPU, res.MEMORY) and v:
                limits[k] = limits.get(k, 0) + v
                lim_set.add(k)
        if lim_set != {res.CPU, res.MEMORY}:
            guaranteed = False
        for k in (res.CPU, res.MEMORY):
            req = c.resources.requests.get(k)
            if req and c.resources.limits.get(k) != req:
                guaranteed = False
    if not requests and not limits:
        return QOS_BEST_EFFORT
    return QOS_GUARANTEED if guaranteed else QOS_BURSTABLE


def is_best_effort(pod: Pod) -> bool:
    """QoS == BestEffort: no container has any requests or limits
    (reference: pkg/apis/core/v1/helper/qos/qos.go GetPodQOS)."""
    return pod_qos_class(pod) == QOS_BEST_EFFORT


def get_container_ports(*pods: Pod) -> List[ContainerPort]:
    """Host ports requested by the pods' containers, host_port != 0
    (reference: pkg/scheduler/util/utils.go GetContainerPorts)."""
    out = []
    for pod in pods:
        for c in pod.spec.containers:
            out.extend(p for p in c.ports if p.host_port != 0)
    return out


def pod_priority(pod: Pod) -> int:
    """Reference: pkg/apis/scheduling has DefaultPriorityWhenNoDefaultClassExists=0;
    pod.Spec.Priority nil -> 0 (util.GetPodPriority, pkg/scheduler/util/utils.go:57)."""
    return pod.spec.priority if pod.spec.priority is not None else 0


# --- node selector / affinity matching (golden host-side) --------------------

# matchFields supports only metadata.name
# (reference: pkg/scheduler/algorithm/scheduler_interface.go NodeFieldSelectorKeys).
NODE_FIELD_NAME = "metadata.name"


def _term_matches_node(term: NodeSelectorTerm, node: Node) -> bool:
    """Reference: predicates.go:753 nodeMatchesNodeSelectorTerms. Terms with
    neither expressions nor fields match nothing (requirement conversion of
    an empty list yields a nothing-selector in the required path)."""
    if not term.match_expressions and not term.match_fields:
        return False
    if term.match_expressions:
        sel = Selector(tuple(term.match_expressions))
        if not sel.matches(node.metadata.labels):
            return False
    if term.match_fields:
        fields = {NODE_FIELD_NAME: node.metadata.name}
        sel = Selector(tuple(term.match_fields))
        if not sel.matches(fields):
            return False
    return True


def pod_matches_node_selector(pod: Pod, node: Node) -> bool:
    """Golden semantics of the MatchNodeSelector predicate
    (reference: predicates.go:813 PodMatchNodeSelector ->
    :771 podMatchesNodeSelectorAndAffinityTerms):
      - spec.nodeSelector: all pairs must match node labels
      - requiredDuringScheduling node affinity: OR over terms; nil matches
    """
    if pod.spec.node_selector:
        if not Selector.from_set(pod.spec.node_selector).matches(node.metadata.labels):
            return False
    aff = pod.spec.affinity
    if aff and aff.node_affinity and aff.node_affinity.required is not None:
        terms = aff.node_affinity.required.node_selector_terms
        return any(_term_matches_node(t, node) for t in terms)
    return True


def clone_pod(pod: Pod, **meta_overrides) -> Pod:
    import copy

    p = copy.deepcopy(pod)
    if meta_overrides:
        p.metadata = replace(p.metadata, **meta_overrides)
    return p


def with_node_name(pod: Pod, node_name: str) -> Pod:
    """Cheap bound-pod copy for the scheduling hot path: spec/status are
    shallow-replaced (sub-objects like containers are shared and treated
    as immutable during scheduling), avoiding a deepcopy per bind."""
    return Pod(
        metadata=pod.metadata,
        spec=replace(pod.spec, node_name=node_name),
        status=replace(pod.status),
    )
