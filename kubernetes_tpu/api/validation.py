"""API object validation.

Reference: pkg/apis/core/validation/validation.go (~6k LoC of per-kind
rules over apimachinery's field.Path / field.ErrorList). The same
shape is kept — path-addressed errors aggregated into a list so a bad
object reports every problem at once — over this model's flattened
types. The apiserver runs validation after admission mutators, exactly
where the reference's registry strategies call Validate
(registry/core/pod/strategy.go:79), and surfaces failures as 422.
"""

from __future__ import annotations

import functools
import re
from typing import List, Optional

from . import types as api

# apimachinery/pkg/util/validation/validation.go:32 IsDNS1123Subdomain
_DNS1123 = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?"
                      r"(\.[a-z0-9]([-a-z0-9]*[a-z0-9])?)*$")
_LABEL_VALUE = re.compile(r"^(([A-Za-z0-9][-A-Za-z0-9_.]*)?[A-Za-z0-9])?$")
_QUALIFIED_NAME = re.compile(
    r"^([a-z0-9]([-a-z0-9]*[a-z0-9])?(\.[a-z0-9]([-a-z0-9]*[a-z0-9])?)*/)?"
    r"[A-Za-z0-9]([-A-Za-z0-9_.]{0,61}[A-Za-z0-9])?$")


class ValidationError:
    """One field.Error (apimachinery field/errors.go)."""

    def __init__(self, field: str, value, detail: str):
        self.field = field
        self.value = value
        self.detail = detail

    def __repr__(self):
        return f"{self.field}: {self.detail} (got {self.value!r})"


class ErrorList(list):
    def add(self, field: str, value, detail: str):
        self.append(ValidationError(field, value, detail))

    def message(self) -> str:
        return "; ".join(repr(e) for e in self)


def validate_object_meta(meta: api.ObjectMeta, path: str = "metadata",
                         errs: Optional[ErrorList] = None) -> ErrorList:
    errs = errs if errs is not None else ErrorList()
    if not meta.name:
        errs.add(f"{path}.name", meta.name, "name is required")
    elif len(meta.name) > 253 or not _DNS1123.match(meta.name):
        errs.add(f"{path}.name", meta.name,
                 "must be a DNS-1123 subdomain")
    if meta.namespace and not _DNS1123.match(meta.namespace):
        errs.add(f"{path}.namespace", meta.namespace,
                 "must be a DNS-1123 subdomain")
    for k, v in (meta.labels or {}).items():
        if not _QUALIFIED_NAME.match(k):
            errs.add(f"{path}.labels", k, "invalid label key")
        if not _LABEL_VALUE.match(v) or len(v) > 63:
            errs.add(f"{path}.labels[{k}]", v, "invalid label value")
    return errs


def validate_pod(pod: api.Pod) -> ErrorList:
    """validation.go:2990 ValidatePod (spec subset this model carries)."""
    errs = validate_object_meta(pod.metadata)
    spec, path = pod.spec, "spec"
    if not spec.containers:
        errs.add(f"{path}.containers", [], "at least one container required")
    seen = set()
    for i, c in enumerate(spec.containers):
        cpath = f"{path}.containers[{i}]"
        if not c.name:
            errs.add(f"{cpath}.name", c.name, "name is required")
        elif c.name in seen:
            errs.add(f"{cpath}.name", c.name, "duplicate container name")
        seen.add(c.name)
        if c.image_pull_policy not in ("", "Always", "IfNotPresent", "Never"):
            errs.add(f"{cpath}.imagePullPolicy", c.image_pull_policy,
                     "must be Always, IfNotPresent or Never")
        req, lim = c.resources.requests, c.resources.limits
        for res, rv in (req or {}).items():
            if rv < 0:
                errs.add(f"{cpath}.resources.requests[{res}]", rv,
                         "must be non-negative")
            if lim and res in lim and rv > lim[res]:
                errs.add(f"{cpath}.resources.requests[{res}]", rv,
                         "must be <= limit")
        for res, rv in (lim or {}).items():
            if rv < 0:
                errs.add(f"{cpath}.resources.limits[{res}]", rv,
                         "must be non-negative")
    if spec.restart_policy not in ("Always", "OnFailure", "Never"):
        errs.add(f"{path}.restartPolicy", spec.restart_policy,
                 "must be Always, OnFailure or Never")
    vseen = set()
    for i, v in enumerate(spec.volumes):
        vpath = f"{path}.volumes[{i}]"
        if not v.name:
            errs.add(f"{vpath}.name", v.name, "name is required")
        elif v.name in vseen:
            errs.add(f"{vpath}.name", v.name, "duplicate volume name")
        vseen.add(v.name)
        sources = sum(bool(x) for x in (
            v.empty_dir, v.host_path, v.config_map, v.secret,
            v.downward_api, v.nfs_server, v.pvc_name, v.source_kind,
            v.projected))
        if sources > 1:
            errs.add(vpath, v.name, "may not specify more than one source")
    for i, t in enumerate(spec.tolerations):
        if t.operator not in (api.TOLERATION_OP_EQUAL,
                              api.TOLERATION_OP_EXISTS):
            errs.add(f"{path}.tolerations[{i}].operator", t.operator,
                     "must be Equal or Exists")
        if t.operator == api.TOLERATION_OP_EXISTS and t.value:
            errs.add(f"{path}.tolerations[{i}].value", t.value,
                     "must be empty with operator Exists")
    if spec.priority is not None and spec.priority > 2_000_000_000 \
            and not spec.priority_class_name.startswith("system-"):
        errs.add(f"{path}.priority", spec.priority,
                 "only system priority classes may exceed 2000000000")
    return errs


def validate_pod_update(new: api.Pod, old: api.Pod) -> ErrorList:
    """validation.go:3305 ValidatePodUpdate: spec is immutable except
    image, activeDeadline, tolerations additions; nodeName only via
    binding (transition from empty)."""
    errs = ErrorList()
    if old.spec.node_name and new.spec.node_name != old.spec.node_name:
        errs.add("spec.nodeName", new.spec.node_name,
                 "may not be changed once set")
    if len(new.spec.containers) != len(old.spec.containers):
        errs.add("spec.containers", len(new.spec.containers),
                 "may not add or remove containers")
    return errs


def validate_node(node: api.Node) -> ErrorList:
    errs = validate_object_meta(node.metadata)
    for res, v in (node.status.allocatable or {}).items():
        if v < 0:
            errs.add(f"status.allocatable[{res}]", v, "must be non-negative")
    for i, t in enumerate(node.spec.taints):
        if t.effect not in (api.NO_SCHEDULE, api.PREFER_NO_SCHEDULE,
                            api.NO_EXECUTE):
            errs.add(f"spec.taints[{i}].effect", t.effect,
                     "invalid taint effect")
        if not t.key:
            errs.add(f"spec.taints[{i}].key", t.key, "key is required")
    return errs


def validate_service(svc: api.Service) -> ErrorList:
    errs = validate_object_meta(svc.metadata)
    spec = svc.spec
    if spec.type not in ("ClusterIP", "NodePort", "LoadBalancer",
                         "ExternalName"):
        errs.add("spec.type", spec.type, "invalid service type")
    if spec.session_affinity not in ("None", "ClientIP"):
        errs.add("spec.sessionAffinity", spec.session_affinity,
                 "must be None or ClientIP")
    names = set()
    for i, p in enumerate(spec.ports):
        ppath = f"spec.ports[{i}]"
        if not (0 < p.port <= 65535):
            errs.add(f"{ppath}.port", p.port, "must be 1-65535")
        if p.node_port and not (0 < p.node_port <= 65535):
            errs.add(f"{ppath}.nodePort", p.node_port, "must be 1-65535")
        if p.protocol not in ("TCP", "UDP", "SCTP"):
            errs.add(f"{ppath}.protocol", p.protocol, "invalid protocol")
        if len(spec.ports) > 1 and not p.name:
            errs.add(f"{ppath}.name", p.name,
                     "required when multiple ports are present")
        if p.name and p.name in names:
            errs.add(f"{ppath}.name", p.name, "duplicate port name")
        names.add(p.name)
    if spec.type == "ExternalName" and not spec.external_name:
        errs.add("spec.externalName", spec.external_name,
                 "required for ExternalName services")
    return errs


def validate_pvc(pvc) -> ErrorList:
    errs = validate_object_meta(pvc.metadata)
    for res, v in (pvc.spec.requests or {}).items():
        if v < 0:
            errs.add(f"spec.resources.requests[{res}]", v,
                     "must be non-negative")
    return errs


def _validate_workload(obj, selector_required: bool = True) -> ErrorList:
    """Shared apps-workload shape: replicas >= 0; apps/v1 selector
    required and matching the template labels
    (pkg/apis/apps/validation ValidateDeployment/ReplicaSet...)."""
    errs = validate_object_meta(obj.metadata)
    replicas = getattr(obj.spec, "replicas", None)
    if replicas is not None and replicas < 0:
        errs.add("spec.replicas", replicas, "must be non-negative")
    sel = getattr(obj, "selector", None) or getattr(obj.spec, "selector",
                                                    None)
    template = getattr(obj.spec, "template", None) or \
        getattr(obj, "template", None)
    if selector_required and sel is None:
        errs.add("spec.selector", None, "selector is required")
    if sel is not None and template is not None:
        tlabels = getattr(getattr(template, "metadata", None), "labels",
                          None) or {}
        s = sel.to_selector() if hasattr(sel, "to_selector") else None
        if s is not None and s.requirements and not s.matches(tlabels):
            errs.add("spec.template.metadata.labels", tlabels,
                     "must match spec.selector")
    strategy = getattr(obj.spec, "update_strategy", None)
    if strategy is not None:
        # apps/validation ValidateDaemonSetUpdateStrategy: the type is
        # an enum and a RollingUpdate budget of 0 could never progress
        if strategy.type not in ("RollingUpdate", "OnDelete"):
            errs.add("spec.updateStrategy.type", strategy.type,
                     'must be "RollingUpdate" or "OnDelete"')
        elif strategy.type == "RollingUpdate" \
                and getattr(strategy, "max_unavailable", 1) < 1:
            errs.add("spec.updateStrategy.rollingUpdate.maxUnavailable",
                     strategy.max_unavailable, "must be at least 1")
        # StatefulSet strategies carry a partition instead of a budget
        # (apps/validation ValidateStatefulSetUpdateStrategy)
        if getattr(strategy, "partition", 0) < 0:
            errs.add("spec.updateStrategy.rollingUpdate.partition",
                     strategy.partition, "must be non-negative")
    return errs


def validate_namespace(ns) -> ErrorList:
    errs = ErrorList()
    # namespace names are DNS-1123 LABELS (no dots)
    if not ns.metadata.name or "." in ns.metadata.name \
            or not _DNS1123.match(ns.metadata.name):
        errs.add("metadata.name", ns.metadata.name,
                 "must be a DNS-1123 label")
    return errs


def validate_pv(pv) -> ErrorList:
    errs = validate_object_meta(pv.metadata)
    for res_, v in (pv.spec.capacity or {}).items():
        if v < 0:
            errs.add(f"spec.capacity[{res_}]", v, "must be non-negative")
    if pv.spec.source_kind == "CSI" and not pv.spec.csi_driver:
        errs.add("spec.csi.driver", "", "driver name is required")
    return errs


def validate_pv_update(new, old) -> ErrorList:
    """PV source is immutable (validation.go ValidatePersistentVolumeUpdate)."""
    errs = ErrorList()
    if (new.spec.source_kind, new.spec.source_id) != \
            (old.spec.source_kind, old.spec.source_id):
        errs.add("spec.source", new.spec.source_kind,
                 "volume source is immutable")
    return errs


def validate_pvc_update(new, old) -> ErrorList:
    """Claim spec immutable after bind except the binder's own
    volumeName transition (ValidatePersistentVolumeClaimUpdate)."""
    errs = ErrorList()
    if old.spec.volume_name and \
            new.spec.volume_name != old.spec.volume_name:
        errs.add("spec.volumeName", new.spec.volume_name,
                 "is immutable after binding")
    if old.spec.volume_name and \
            new.spec.storage_class_name != old.spec.storage_class_name:
        errs.add("spec.storageClassName", new.spec.storage_class_name,
                 "is immutable after binding")
    return errs


def validate_service_update(new, old) -> ErrorList:
    """clusterIP immutable once allocated (ValidateServiceUpdate)."""
    errs = ErrorList()
    if old.spec.cluster_ip and new.spec.cluster_ip != old.spec.cluster_ip:
        errs.add("spec.clusterIP", new.spec.cluster_ip,
                 "may not change once set")
    return errs


def validate_rbac_role(role) -> ErrorList:
    """ValidatePolicyRule (rbac/validation): every rule names verbs and
    either resources WITH apiGroups, or nonResourceURLs. Requiring
    apiGroups here is what makes the authorizer's strict
    empty-matches-nothing semantics (server/auth.py) unsurprising."""
    errs = validate_object_meta(role.metadata)
    for i, r in enumerate(role.rules or []):
        rp = f"rules[{i}]"
        if not r.verbs:
            errs.add(f"{rp}.verbs", [], "at least one verb is required")
        has_res = bool(r.resources)
        has_nonres = bool(r.non_resource_urls)
        if not has_res and not has_nonres:
            errs.add(f"{rp}.resources", [],
                     "resources or nonResourceURLs is required")
        if has_res and not r.api_groups:
            errs.add(f"{rp}.apiGroups", [],
                     "apiGroups is required for resource rules "
                     '([""] selects the core group)')
    return errs


def validate_rbac_binding(b) -> ErrorList:
    errs = validate_object_meta(b.metadata)
    if not getattr(b.role_ref, "name", ""):
        errs.add("roleRef.name", "", "roleRef is required")
    for i, s in enumerate(b.subjects or []):
        if s.kind not in ("User", "Group", "ServiceAccount"):
            errs.add(f"subjects[{i}].kind", s.kind, "invalid subject kind")
        if not s.name:
            errs.add(f"subjects[{i}].name", s.name, "name is required")
    return errs


def validate_rbac_binding_update(new, old) -> ErrorList:
    """roleRef is immutable (rbac/validation ValidateRoleBindingUpdate)."""
    errs = ErrorList()
    if (new.role_ref.kind, new.role_ref.name) != \
            (old.role_ref.kind, old.role_ref.name):
        errs.add("roleRef", new.role_ref.name, "roleRef is immutable")
    return errs


def validate_hpa(hpa) -> ErrorList:
    errs = validate_object_meta(hpa.metadata)
    if hpa.spec.max_replicas <= 0:
        errs.add("spec.maxReplicas", hpa.spec.max_replicas,
                 "must be greater than 0")
    if hpa.spec.min_replicas is not None and \
            hpa.spec.min_replicas > hpa.spec.max_replicas:
        errs.add("spec.minReplicas", hpa.spec.min_replicas,
                 "must not exceed maxReplicas")
    return errs


def validate_pdb(pdb) -> ErrorList:
    errs = validate_object_meta(pdb.metadata)
    if pdb.spec.min_available is not None and \
            getattr(pdb.spec, "max_unavailable", None) is not None:
        errs.add("spec", None,
                 "minAvailable and maxUnavailable are mutually exclusive")
    return errs


QUOTA_SCOPES = ("BestEffort", "NotBestEffort", "Terminating",
                "NotTerminating")


def validate_resource_quota(q) -> ErrorList:
    errs = validate_object_meta(q.metadata)
    for k, v in (q.spec.hard or {}).items():
        if v < 0:
            errs.add(f"spec.hard[{k}]", v, "must be non-negative")
    for s in getattr(q.spec, "scopes", None) or []:
        # unknown scopes must be 422s (ValidateResourceQuotaSpec): a
        # typo'd scope silently matching everything would turn a scoped
        # quota into an unscoped one
        if s not in QUOTA_SCOPES:
            errs.add("spec.scopes", s,
                     f"must be one of {', '.join(QUOTA_SCOPES)}")
    return errs


def validate_configmap(cm) -> ErrorList:
    errs = validate_object_meta(cm.metadata)
    for k in (cm.data or {}):
        if not re.match(r"^[-._a-zA-Z0-9]+$", k):
            errs.add(f"data[{k}]", k, "invalid key name")
    return errs


def validate_cronjob(cj) -> ErrorList:
    errs = validate_object_meta(cj.metadata)
    schedule = getattr(cj.spec, "schedule", "")
    if not schedule or len(schedule.split()) != 5:
        errs.add("spec.schedule", schedule,
                 "must be a 5-field cron expression")
    return errs


def validate_priority_class(pc) -> ErrorList:
    errs = validate_object_meta(pc.metadata)
    if pc.value > 1_000_000_000 and not pc.metadata.name.startswith(
            "system-"):
        errs.add("value", pc.value,
                 "only system classes may exceed 1000000000")
    return errs


def validate_podgroup(pg) -> ErrorList:
    """Gang admission gates on minMember; a non-positive value would
    either release gangs instantly (0) or wedge them forever (<0)."""
    errs = validate_object_meta(pg.metadata)
    if pg.spec.min_member < 1:
        errs.add("spec.minMember", pg.spec.min_member, "must be at least 1")
    return errs


def validate_job(job) -> ErrorList:
    errs = validate_object_meta(job.metadata)
    for fname in ("completions", "parallelism", "backoff_limit"):
        v = getattr(job.spec, fname, None)
        if v is not None and v < 0:
            errs.add(f"spec.{fname}", v, "must be non-negative")
    return errs


# kind plural -> validator; update validators get (new, old).
# Kinds without a specific entry still get validate_object_meta via
# validate() — every served built-in kind reports field-addressed 422s.
VALIDATORS = {
    "pods": validate_pod,
    "nodes": validate_node,
    "services": validate_service,
    "persistentvolumeclaims": validate_pvc,
    "persistentvolumes": validate_pv,
    "namespaces": validate_namespace,
    "deployments": _validate_workload,
    "replicasets": _validate_workload,
    "statefulsets": _validate_workload,
    "daemonsets": _validate_workload,
    "replicationcontrollers": functools.partial(
        _validate_workload, selector_required=False),
    "jobs": validate_job,
    "cronjobs": validate_cronjob,
    "configmaps": validate_configmap,
    "secrets": validate_configmap,
    "roles": validate_rbac_role,
    "clusterroles": validate_rbac_role,
    "rolebindings": validate_rbac_binding,
    "clusterrolebindings": validate_rbac_binding,
    "horizontalpodautoscalers": validate_hpa,
    "poddisruptionbudgets": validate_pdb,
    "podgroups": validate_podgroup,
    "resourcequotas": validate_resource_quota,
    "priorityclasses": validate_priority_class,
}

UPDATE_VALIDATORS = {
    "pods": validate_pod_update,
    "services": validate_service_update,
    "persistentvolumes": validate_pv_update,
    "persistentvolumeclaims": validate_pvc_update,
    "rolebindings": validate_rbac_binding_update,
    "clusterrolebindings": validate_rbac_binding_update,
}


def validate(kind: str, obj, old=None) -> ErrorList:
    errs = ErrorList()
    v = VALIDATORS.get(kind)
    if v is not None:
        errs.extend(v(obj))
    elif hasattr(obj, "metadata"):
        # no kind-specific rules yet: metadata is still validated for
        # EVERY served kind (name/namespace/labels shape)
        errs.extend(validate_object_meta(obj.metadata))
    if old is not None:
        uv = UPDATE_VALIDATORS.get(kind)
        if uv is not None:
            errs.extend(uv(obj, old))
    return errs
