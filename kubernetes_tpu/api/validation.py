"""API object validation.

Reference: pkg/apis/core/validation/validation.go (~6k LoC of per-kind
rules over apimachinery's field.Path / field.ErrorList). The same
shape is kept — path-addressed errors aggregated into a list so a bad
object reports every problem at once — over this model's flattened
types. The apiserver runs validation after admission mutators, exactly
where the reference's registry strategies call Validate
(registry/core/pod/strategy.go:79), and surfaces failures as 422.
"""

from __future__ import annotations

import re
from typing import List, Optional

from . import types as api

# apimachinery/pkg/util/validation/validation.go:32 IsDNS1123Subdomain
_DNS1123 = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?"
                      r"(\.[a-z0-9]([-a-z0-9]*[a-z0-9])?)*$")
_LABEL_VALUE = re.compile(r"^(([A-Za-z0-9][-A-Za-z0-9_.]*)?[A-Za-z0-9])?$")
_QUALIFIED_NAME = re.compile(
    r"^([a-z0-9]([-a-z0-9]*[a-z0-9])?(\.[a-z0-9]([-a-z0-9]*[a-z0-9])?)*/)?"
    r"[A-Za-z0-9]([-A-Za-z0-9_.]{0,61}[A-Za-z0-9])?$")


class ValidationError:
    """One field.Error (apimachinery field/errors.go)."""

    def __init__(self, field: str, value, detail: str):
        self.field = field
        self.value = value
        self.detail = detail

    def __repr__(self):
        return f"{self.field}: {self.detail} (got {self.value!r})"


class ErrorList(list):
    def add(self, field: str, value, detail: str):
        self.append(ValidationError(field, value, detail))

    def message(self) -> str:
        return "; ".join(repr(e) for e in self)


def validate_object_meta(meta: api.ObjectMeta, path: str = "metadata",
                         errs: Optional[ErrorList] = None) -> ErrorList:
    errs = errs if errs is not None else ErrorList()
    if not meta.name:
        errs.add(f"{path}.name", meta.name, "name is required")
    elif len(meta.name) > 253 or not _DNS1123.match(meta.name):
        errs.add(f"{path}.name", meta.name,
                 "must be a DNS-1123 subdomain")
    if meta.namespace and not _DNS1123.match(meta.namespace):
        errs.add(f"{path}.namespace", meta.namespace,
                 "must be a DNS-1123 subdomain")
    for k, v in (meta.labels or {}).items():
        if not _QUALIFIED_NAME.match(k):
            errs.add(f"{path}.labels", k, "invalid label key")
        if not _LABEL_VALUE.match(v) or len(v) > 63:
            errs.add(f"{path}.labels[{k}]", v, "invalid label value")
    return errs


def validate_pod(pod: api.Pod) -> ErrorList:
    """validation.go:2990 ValidatePod (spec subset this model carries)."""
    errs = validate_object_meta(pod.metadata)
    spec, path = pod.spec, "spec"
    if not spec.containers:
        errs.add(f"{path}.containers", [], "at least one container required")
    seen = set()
    for i, c in enumerate(spec.containers):
        cpath = f"{path}.containers[{i}]"
        if not c.name:
            errs.add(f"{cpath}.name", c.name, "name is required")
        elif c.name in seen:
            errs.add(f"{cpath}.name", c.name, "duplicate container name")
        seen.add(c.name)
        if c.image_pull_policy not in ("", "Always", "IfNotPresent", "Never"):
            errs.add(f"{cpath}.imagePullPolicy", c.image_pull_policy,
                     "must be Always, IfNotPresent or Never")
        req, lim = c.resources.requests, c.resources.limits
        for res, rv in (req or {}).items():
            if rv < 0:
                errs.add(f"{cpath}.resources.requests[{res}]", rv,
                         "must be non-negative")
            if lim and res in lim and rv > lim[res]:
                errs.add(f"{cpath}.resources.requests[{res}]", rv,
                         "must be <= limit")
        for res, rv in (lim or {}).items():
            if rv < 0:
                errs.add(f"{cpath}.resources.limits[{res}]", rv,
                         "must be non-negative")
    if spec.restart_policy not in ("Always", "OnFailure", "Never"):
        errs.add(f"{path}.restartPolicy", spec.restart_policy,
                 "must be Always, OnFailure or Never")
    vseen = set()
    for i, v in enumerate(spec.volumes):
        vpath = f"{path}.volumes[{i}]"
        if not v.name:
            errs.add(f"{vpath}.name", v.name, "name is required")
        elif v.name in vseen:
            errs.add(f"{vpath}.name", v.name, "duplicate volume name")
        vseen.add(v.name)
        sources = sum(bool(x) for x in (
            v.empty_dir, v.host_path, v.config_map, v.secret,
            v.downward_api, v.nfs_server, v.pvc_name, v.source_kind,
            v.projected))
        if sources > 1:
            errs.add(vpath, v.name, "may not specify more than one source")
    for i, t in enumerate(spec.tolerations):
        if t.operator not in (api.TOLERATION_OP_EQUAL,
                              api.TOLERATION_OP_EXISTS):
            errs.add(f"{path}.tolerations[{i}].operator", t.operator,
                     "must be Equal or Exists")
        if t.operator == api.TOLERATION_OP_EXISTS and t.value:
            errs.add(f"{path}.tolerations[{i}].value", t.value,
                     "must be empty with operator Exists")
    if spec.priority is not None and spec.priority > 2_000_000_000 \
            and not spec.priority_class_name.startswith("system-"):
        errs.add(f"{path}.priority", spec.priority,
                 "only system priority classes may exceed 2000000000")
    return errs


def validate_pod_update(new: api.Pod, old: api.Pod) -> ErrorList:
    """validation.go:3305 ValidatePodUpdate: spec is immutable except
    image, activeDeadline, tolerations additions; nodeName only via
    binding (transition from empty)."""
    errs = ErrorList()
    if old.spec.node_name and new.spec.node_name != old.spec.node_name:
        errs.add("spec.nodeName", new.spec.node_name,
                 "may not be changed once set")
    if len(new.spec.containers) != len(old.spec.containers):
        errs.add("spec.containers", len(new.spec.containers),
                 "may not add or remove containers")
    return errs


def validate_node(node: api.Node) -> ErrorList:
    errs = validate_object_meta(node.metadata)
    for res, v in (node.status.allocatable or {}).items():
        if v < 0:
            errs.add(f"status.allocatable[{res}]", v, "must be non-negative")
    for i, t in enumerate(node.spec.taints):
        if t.effect not in (api.NO_SCHEDULE, api.PREFER_NO_SCHEDULE,
                            api.NO_EXECUTE):
            errs.add(f"spec.taints[{i}].effect", t.effect,
                     "invalid taint effect")
        if not t.key:
            errs.add(f"spec.taints[{i}].key", t.key, "key is required")
    return errs


def validate_service(svc: api.Service) -> ErrorList:
    errs = validate_object_meta(svc.metadata)
    spec = svc.spec
    if spec.type not in ("ClusterIP", "NodePort", "LoadBalancer",
                         "ExternalName"):
        errs.add("spec.type", spec.type, "invalid service type")
    if spec.session_affinity not in ("None", "ClientIP"):
        errs.add("spec.sessionAffinity", spec.session_affinity,
                 "must be None or ClientIP")
    names = set()
    for i, p in enumerate(spec.ports):
        ppath = f"spec.ports[{i}]"
        if not (0 < p.port <= 65535):
            errs.add(f"{ppath}.port", p.port, "must be 1-65535")
        if p.node_port and not (0 < p.node_port <= 65535):
            errs.add(f"{ppath}.nodePort", p.node_port, "must be 1-65535")
        if p.protocol not in ("TCP", "UDP", "SCTP"):
            errs.add(f"{ppath}.protocol", p.protocol, "invalid protocol")
        if len(spec.ports) > 1 and not p.name:
            errs.add(f"{ppath}.name", p.name,
                     "required when multiple ports are present")
        if p.name and p.name in names:
            errs.add(f"{ppath}.name", p.name, "duplicate port name")
        names.add(p.name)
    if spec.type == "ExternalName" and not spec.external_name:
        errs.add("spec.externalName", spec.external_name,
                 "required for ExternalName services")
    return errs


def validate_pvc(pvc) -> ErrorList:
    errs = validate_object_meta(pvc.metadata)
    for res, v in (pvc.spec.requests or {}).items():
        if v < 0:
            errs.add(f"spec.resources.requests[{res}]", v,
                     "must be non-negative")
    return errs


# kind plural -> validator; update validators get (new, old)
VALIDATORS = {
    "pods": validate_pod,
    "nodes": validate_node,
    "services": validate_service,
    "persistentvolumeclaims": validate_pvc,
}

UPDATE_VALIDATORS = {
    "pods": validate_pod_update,
}


def validate(kind: str, obj, old=None) -> ErrorList:
    errs = ErrorList()
    v = VALIDATORS.get(kind)
    if v is not None:
        errs.extend(v(obj))
    if old is not None:
        uv = UPDATE_VALIDATORS.get(kind)
        if uv is not None:
            errs.extend(uv(obj, old))
    return errs
