"""Scheduler autopilot: offline weight training + gated auto-promotion.

The closing of the learned-scoring loop the earlier subsystems built
the rails for: the round ledger (utils/tracing.py) is the dataset, the
shadow-scoring observatory (sched/weights.py) is the live-traffic
judge, the storm harness's SLO gates (bench.py) are the promotion CI,
and the live WeightProfile hot swap is the actuator. Pipeline:

  ledger JSONL --dataset--> feature/outcome matrices
               --trainer--> candidate WeightProfile (store watch path)
               --controller--> shadow gate -> replay CI -> promote live
                              -> regression watch (auto-rollback)

Every transition is ledgered (kind "autopilot"), metered
(scheduler_autopilot_promotions_total{outcome}), and served from the
kube-scheduler HealthServer at /debug/autopilot.
"""

# Lazy re-exports (PEP 562): the trainer/controller modules pull the
# ops stack (and with it jax), but bench.py and other CLI entry points
# only need the light replay-gate constants at import time — resolving
# submodules on first attribute access keeps `--help` jax-free.
_EXPORTS = {
    "AutopilotConfig": "controller", "AutopilotController": "controller",
    "OUTCOMES": "controller",
    "LedgerDataset": "dataset", "build_dataset": "dataset",
    "load_dataset": "dataset", "load_records": "dataset",
    "STORM_PRIORITY": "replay", "STORM_SLO_P99": "replay",
    "ReplayReport": "replay", "run_replay": "replay",
    "PolicyGradientTrainer": "trainer", "RidgeTrainer": "trainer",
    "Trainer": "trainer", "emit_candidate": "trainer",
}

__all__ = sorted(_EXPORTS) + ["workload_profiles_path"]


def __getattr__(name):
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(f".{mod}", __name__), name)


def workload_profiles_path() -> str:
    """The checked-in hand-tuned per-workload weight table (density /
    trickle / gang / storm) — a standard --weight-profiles JSON, also
    the autopilot's seed candidate pool."""
    import os

    return os.path.join(os.path.dirname(__file__),
                        "workload_profiles.json")
