"""The promotion pipeline: candidate -> gates -> live -> watched.

State machine over one candidate WeightProfile at a time:

  idle -> shadowing      set_gating pre-compiles the candidate's score
                         planes (so the eventual promotion is a pure
                         traced-value swap, zero recompiles) and
                         snapshots its shadow counters; live traffic
                         then accumulates divergence evidence
       -> (shadow gate)  flip rate and margin-delta over the gating
                         window, bounded by config; candidate deleted
                         mid-window aborts cleanly
       -> (replay CI)    storm trace-replay (replay.py) under the
                         candidate AND under the current production
                         weights; per-class STORM_SLO_P99 gates must
                         pass and the replay objective must not
                         regress against the production baseline
       -> promoted       role=live through the store object when one
                         exists (the informer hot-swap path), else the
                         WeightBook directly; recompile-free by the
                         pre-compile gating above
       -> watching       a FlightRecorder round observer inspects every
                         subsequent traced round; margin collapse or a
                         round-wall SLO breach inside the watch window
                         auto-rolls-back IN MEMORY immediately (the
                         WeightBook demote takes no scheduler lock, so
                         the observer — which may run on the scheduling
                         thread — can never deadlock); the store object
                         is reconciled on the next step()
       -> completed | rolled_back

Every transition is ledgered (tracing.append_record kind "autopilot"),
evented (tracing.event), logged, and the terminal outcome metered as
scheduler_autopilot_promotions_total{outcome}. /debug/autopilot serves
status()/history.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..api import types as api
from ..ops.scores import SCORE_STACK, WEIGHT_FIELDS
from ..utils import faultpoints, tracing
from . import replay as replay_mod

log = logging.getLogger(__name__)

# declared {outcome} label values of
# scheduler_autopilot_promotions_total (utils/metrics.py keeps the
# registered set in lockstep; tests assert it)
OUTCOMES = ("promoted", "rejected_shadow", "rejected_replay",
            "rolled_back", "aborted")

MAX_HISTORY = 64


@dataclass
class AutopilotConfig:
    # shadow gate: evidence floor and bounds over the gating window
    min_shadow_pods: int = 8
    max_flip_rate: float = 0.25
    # mean candidate-margin-minus-production-margin floor (score units;
    # deeply negative = the candidate decides much less decisively)
    margin_delta_floor: float = -1e9
    # promotion CI (replay.py) shape
    replay_nodes: int = 4
    replay_node_cpu: str = "8"
    replay_pod_cpu: str = "100m"
    replay_wave: int = 16
    replay_trace: Optional[List[Dict[str, int]]] = None
    replay_prefill: Optional[Dict[int, int]] = None
    replay_slo_scale: float = 1.0
    # candidate objective may trail the production baseline by at most
    # this much (0 = strict no-regression)
    objective_tolerance: float = 0.02
    # post-promotion regression watch: rounds observed before the
    # promotion is declared good, and the per-round breach bounds
    watch_rounds: int = 8
    watch_margin_floor: float = 0.0   # scores.margin.mean below = breach
    watch_wall_slo_s: float = 30.0    # round wall above = breach

    def as_dict(self) -> Dict[str, Any]:
        return {
            "min_shadow_pods": self.min_shadow_pods,
            "max_flip_rate": self.max_flip_rate,
            "margin_delta_floor": self.margin_delta_floor,
            "objective_tolerance": self.objective_tolerance,
            "watch_rounds": self.watch_rounds,
            "watch_margin_floor": self.watch_margin_floor,
            "watch_wall_slo_s": self.watch_wall_slo_s,
            "replay_slo_scale": self.replay_slo_scale}


class AutopilotController:
    """Drives one candidate at a time through the promotion pipeline.

    Externally paced: start(name) opens the gating window, step()
    advances as far as the evidence allows (and runs the synchronous
    replay CI when the shadow gate passes). The post-promotion watch
    advances itself via a recorder observer; step() only reconciles
    terminal state. Thread-safety: _mu guards controller state;
    WeightBook/ObjectStore calls happen outside scheduler locks except
    the observer's in-memory demote, which is deadlock-free by design
    (WeightBook lock only)."""

    def __init__(self, sched, store=None,
                 config: Optional[AutopilotConfig] = None):
        self.sched = sched
        self.store = store
        self.book = sched.weightbook
        self.metrics = sched.metrics
        self.cfg = config or AutopilotConfig()
        self._mu = threading.Lock()
        self.state = "idle"
        self.candidate: Optional[str] = None
        self.outcome: Optional[str] = None
        self.history: List[Dict[str, Any]] = []
        self.reports: Dict[str, Any] = {}
        self._shadow_start: Optional[Dict[str, float]] = None
        self._watch: Optional[Dict[str, Any]] = None
        self._observer = None
        self._force = False
        # the scheduler serves /debug/autopilot through this backref
        sched.autopilot = self

    # -- bookkeeping ---------------------------------------------------------

    def _transition(self, state: str, **info):
        entry = {"state": state, "profile": self.candidate}
        entry.update({k: v for k, v in info.items() if v is not None})
        self.state = state
        self.history.append(entry)
        del self.history[:-MAX_HISTORY]
        rec = tracing.active()
        if rec is not None:
            rec.append_record("autopilot", state=state,
                              profile=self.candidate,
                              **{k: v for k, v in info.items()
                                 if v is not None})
        tracing.event("autopilot", state=state, profile=self.candidate)
        log.info("autopilot: %s profile=%s %s", state, self.candidate,
                 info or "")

    def _finish(self, outcome: str, **info):
        self.outcome = outcome
        self.metrics.autopilot_promotions.labels(outcome=outcome).inc()
        self._transition(outcome, **info)
        self._detach_observer()
        self._watch = None
        self._shadow_start = None

    def _detach_observer(self):
        rec = tracing.active()
        if rec is not None and self._observer is not None:
            try:
                rec.observers.remove(self._observer)
            except ValueError:
                pass
        self._observer = None

    # -- pipeline ------------------------------------------------------------

    def start(self, name: str, force: bool = False) -> str:
        """Open the gating window for one candidate. force=True skips
        the shadow and replay gates on the next step() — the operator
        override the regression watch exists to backstop."""
        with self._mu:
            if self.state not in ("idle", "completed", "rolled_back",
                                  "rejected_shadow", "rejected_replay",
                                  "aborted"):
                raise RuntimeError(
                    f"autopilot busy: {self.state} on {self.candidate}")
            self.candidate = name
            self.outcome = None
            self._force = force
            if not self.book.has_profile(name):
                self._finish("aborted", reason="unknown profile")
                return self.state
            # pre-compile the candidate's planes NOW: the one gating
            # compile lands here, before any verdict, so promotion
            # later swaps a traced value into an already-built program
            self.book.set_gating(name, True)
            self._shadow_start = self.book.stats_snapshot(name)
            self._transition("shadowing", force=force or None)
            return self.state

    def step(self) -> str:
        """Advance as far as the current evidence allows. Returns the
        (possibly terminal) state."""
        with self._mu:
            if self.state == "shadowing":
                self._step_shadowing()
            elif self.state == "watching" or self.outcome == "rolled_back":
                # rolled_back keeps reconciling: the observer could not
                # touch the store, so the object's role lags the
                # in-memory demote until a step() lands
                self._reconcile_watch()
            return self.state

    def _step_shadowing(self):
        name = self.candidate
        if not self.book.has_profile(name):
            # deleted mid-gating: abort cleanly, nothing was promoted
            self._finish("aborted", reason="candidate deleted "
                                           "during gating")
            return
        if not self._force:
            verdict = self._shadow_verdict(name)
            if verdict is None:
                return  # not enough evidence yet; stay shadowing
            ok, shadow_info = verdict
            self.reports["shadow"] = shadow_info
            if not ok:
                self.book.set_gating(name, False)
                self._finish("rejected_shadow", **shadow_info)
                return
            self._transition("replaying", **shadow_info)
            ok, replay_info = self._replay_verdict(name)
            self.reports["replay"] = replay_info
            if not self.book.has_profile(name):
                self._finish("aborted", reason="candidate deleted "
                                               "during replay CI")
                return
            if not ok:
                self.book.set_gating(name, False)
                self._finish("rejected_replay", **{
                    k: replay_info[k] for k in
                    ("objective", "baseline_objective", "failures")
                    if k in replay_info})
                return
        try:
            self._promote(name)
        except faultpoints.FaultInjected as e:
            self.book.set_gating(name, False)
            self._finish("aborted", reason=str(e))
            return
        self._begin_watch(name)

    def _shadow_verdict(self, name):
        """(ok, info) once the gating window holds enough scored pods;
        None while evidence is still accumulating."""
        s0 = self._shadow_start or {}
        s1 = self.book.stats_snapshot(name)
        pods = s1["pods"] - s0.get("pods", 0)
        if pods < self.cfg.min_shadow_pods:
            return None
        flips = s1["flips"] - s0.get("flips", 0)
        flip_rate = flips / pods
        dn = s1["delta_n"] - s0.get("delta_n", 0)
        dsum = s1["delta_sum"] - s0.get("delta_sum", 0.0)
        delta_mean = dsum / dn if dn else 0.0
        info = {"pods": pods, "flips": flips,
                "flip_rate": round(flip_rate, 4),
                "margin_delta_mean": round(delta_mean, 4)}
        if flip_rate > self.cfg.max_flip_rate:
            info["reason"] = (f"flip rate {flip_rate:.2f} over the "
                              f"{self.cfg.max_flip_rate:.2f} gate")
            return False, info
        if dn and delta_mean < self.cfg.margin_delta_floor:
            info["reason"] = (f"margin delta {delta_mean:.2f} under "
                              f"the {self.cfg.margin_delta_floor:.2f} "
                              f"floor")
            return False, info
        return True, info

    def _current_production_table(self) -> Optional[Dict[str, float]]:
        """The live weight table as a profiles dict (None = static
        defaults, which run_replay applies by construction)."""
        if self.book.live_version() == "static":
            return None
        vec = self.book.live_vector()
        return {name: float(vec[s]) for s, name in enumerate(SCORE_STACK)
                if WEIGHT_FIELDS[name] is not None and vec[s]}

    def _replay_verdict(self, name):
        """Promotion CI: replay under the candidate and under current
        production; SLO gates must pass and the objective must not
        regress."""
        cfg = self.cfg
        rep = self.book.report(name) or {}
        weights = rep.get("weights")
        if not weights:
            return False, {"failures": ["candidate has no weights"]}
        kw = dict(nodes=cfg.replay_nodes, node_cpu=cfg.replay_node_cpu,
                  pod_cpu=cfg.replay_pod_cpu, wave=cfg.replay_wave,
                  trace=cfg.replay_trace, prefill=cfg.replay_prefill,
                  slo_scale=cfg.replay_slo_scale)
        baseline = replay_mod.run_replay(
            self._current_production_table(), name="production", **kw)
        cand = replay_mod.run_replay(dict(weights), name=name, **kw)
        info = {"objective": cand.objective,
                "baseline_objective": baseline.objective,
                "candidate": cand.as_dict(),
                "baseline": baseline.as_dict()}
        if not cand.passed:
            info["failures"] = list(cand.failures)
            return False, info
        if cand.objective < baseline.objective - cfg.objective_tolerance:
            info["failures"] = [
                f"objective {cand.objective:.4f} regresses the "
                f"production baseline {baseline.objective:.4f}"]
            return False, info
        return True, info

    def _promote(self, name: str):
        faultpoints.fire("autopilot.promote", payload=name)
        prev_version = self.book.live_version()
        promoted_via = "weightbook"
        if self.store is not None:
            obj = self.store.get("weightprofiles", "default", name)
            if obj is not None:
                obj.spec.role = api.WEIGHT_PROFILE_ROLE_LIVE
                self.store.update("weightprofiles", obj)
                promoted_via = "store"
        if promoted_via == "weightbook":
            self.book.set_role(name, api.WEIGHT_PROFILE_ROLE_LIVE)
        self.outcome = "promoted"
        self.metrics.autopilot_promotions.labels(
            outcome="promoted").inc()
        self._transition("promoted", previous=prev_version,
                         now=self.book.live_version(), via=promoted_via)

    def _begin_watch(self, name: str):
        w = {"profile": name, "version": self.book.live_version(),
             "rounds_left": self.cfg.watch_rounds, "breach": None}
        self._watch = w
        rec = tracing.active()
        if rec is None:
            # nothing to observe without a recorder: the promotion
            # stands on the gates alone
            self._transition("completed", watched=0)
            self.book.set_gating(name, False)
            self._watch = None
            return

        def observe(record):
            self._observe_round(record)

        self._observer = observe
        rec.observers.append(observe)
        self._transition("watching", rounds=self.cfg.watch_rounds,
                         version=w["version"])

    def _observe_round(self, record: Dict[str, Any]):
        """FlightRecorder observer: runs after every finished traced
        round, possibly ON the scheduling thread — so a breach rolls
        back through the WeightBook only (no scheduler lock, no store
        round-trip; step() reconciles the object afterwards)."""
        w = self._watch
        if w is None or self.state != "watching":
            return
        if record.get("weights_version") != w["version"]:
            return  # replay rounds, other schedulers, stale records
        scores = record.get("scores")
        if not scores:
            return
        breach = None
        margin = (scores.get("margin") or {}).get("mean")
        if margin is not None and margin < self.cfg.watch_margin_floor:
            breach = (f"margin mean {margin:.4f} under the "
                      f"{self.cfg.watch_margin_floor:.4f} floor")
        wall = float(record.get("wall_s", 0.0))
        if breach is None and wall > self.cfg.watch_wall_slo_s:
            breach = (f"round wall {wall:.3f}s over the "
                      f"{self.cfg.watch_wall_slo_s:.3f}s SLO")
        with self._mu:
            if self._watch is not w or self.state != "watching":
                return
            if breach is not None:
                w["breach"] = breach
                # instant in-memory rollback: demote ONLY the promoted
                # candidate, so whatever was live before it (or the
                # static defaults) decides the very next round
                self.book.set_role(w["profile"],
                                   api.WEIGHT_PROFILE_ROLE_CANDIDATE)
                self.book.set_gating(w["profile"], False)
                self._finish("rolled_back", reason=breach,
                             restored=self.book.live_version())
                return
            w["rounds_left"] -= 1
            if w["rounds_left"] <= 0:
                self.book.set_gating(w["profile"], False)
                self._transition("completed",
                                 watched=self.cfg.watch_rounds)
                self._detach_observer()
                self._watch = None

    def _reconcile_watch(self):
        """step() housekeeping while watching / after a rollback: the
        store object's role must eventually match the in-memory truth
        (the observer cannot do a store round-trip — see
        _observe_round), and an externally deleted or demoted live
        profile ends the watch as an operator rollback."""
        name = self.candidate
        if self.outcome == "rolled_back" and self.store is not None:
            obj = self.store.get("weightprofiles", "default", name)
            if obj is not None and obj.spec.role == \
                    api.WEIGHT_PROFILE_ROLE_LIVE:
                obj.spec.role = api.WEIGHT_PROFILE_ROLE_CANDIDATE
                self.store.update("weightprofiles", obj)
            return
        if self.state == "watching" and not self.book.has_profile(name):
            self._finish("rolled_back",
                         reason="candidate deleted during watch",
                         restored=self.book.live_version())

    def rollback(self, reason: str = "operator"):
        """Explicit rollback lever (CLI / debug): demote the promoted
        candidate and finish."""
        with self._mu:
            if self.candidate is None or self.state not in (
                    "watching", "promoted", "completed"):
                return
            self.book.set_role(self.candidate,
                               api.WEIGHT_PROFILE_ROLE_CANDIDATE)
            self.book.set_gating(self.candidate, False)
            self._finish("rolled_back", reason=reason,
                         restored=self.book.live_version())
        self._reconcile_watch()

    # -- reporting (/debug/autopilot) ----------------------------------------

    def status(self) -> Dict[str, Any]:
        with self._mu:
            out: Dict[str, Any] = {
                "state": self.state,
                "candidate": self.candidate,
                "outcome": self.outcome,
                "weights_version": self.book.live_version(),
                "config": self.cfg.as_dict(),
                "history": list(self.history),
            }
            if self._watch is not None:
                out["watch"] = {k: self._watch[k] for k in
                                ("profile", "version", "rounds_left",
                                 "breach")}
            if self.reports:
                out["reports"] = dict(self.reports)
            return out
