"""Round-ledger JSONL -> dense per-round feature/outcome matrices.

The offline substrate the trainer fits on. One ledger record per
scheduling round (utils/tracing.py); this module streams the file —
rotated generation ("<path>.1") first, then the live file — and builds:

  * ``features``   [R, F] round-level covariates (utilization,
    fragmentation, margin, wall seconds, placed/pending depths,
    shadow-flip counts) in FEATURES order;
  * ``contrib``    [R, S] the per-priority share of winning score
    totals (SCORE_STACK-aligned, from ``scores.breakdown``) — the
    regressors a weight table can actually act on;
  * ``quality``    [R] the scalar outcome each round is judged by
    (see round_quality).

Robustness contract (tested): unknown keys are ignored (the documented
ledger contract), records of any schema version are accepted, records
without a ``scores`` aggregate (nothing placed, autopilot transitions,
background noise) are skipped, and undecodable lines are counted in
``skipped`` — a torn final line from a crashed run or a rotation race
must never poison a training job.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..ops.scores import SCORE_STACK

# feature column order of LedgerDataset.features
FEATURES = ("util_cpu", "frag_cpu", "margin_mean", "margin_rel",
            "wall_s", "placed", "pending", "shadow_flips", "preempted")


def load_records(path: str) -> Tuple[List[Dict[str, Any]], int]:
    """Stream ledger records from `path` (and its rotated `<path>.1`
    generation, read first so rows come out oldest-first). Returns
    (records, undecodable_line_count); a missing file contributes
    nothing — a fresh cluster simply has no history yet."""
    records: List[Dict[str, Any]] = []
    skipped = 0
    for p in (path + ".1", path):
        if not os.path.exists(p):
            continue
        with open(p) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    skipped += 1
                    continue
                if isinstance(rec, dict):
                    records.append(rec)
                else:
                    skipped += 1
    return records, skipped


def round_quality(rec: Dict[str, Any]) -> float:
    """The scalar outcome a round is judged by: packed (high
    utilization, low fragmentation), decisive (margin-over-runner-up
    relative to the score scale, so re-weighted ledgers compare), and
    fast (wall seconds, clamped so one straggler round cannot dominate
    the fit). All terms are O(1) by construction."""
    tele = rec.get("telemetry") or {}
    scores = rec.get("scores") or {}
    util = float((tele.get("util") or {}).get("cpu", 0.0))
    frag = float((tele.get("frag") or {}).get("cpu", 0.0))
    margin = float((scores.get("margin") or {}).get("mean", 0.0))
    mean_total = abs(float(scores.get("mean", 0.0)))
    margin_rel = margin / mean_total if mean_total > 0 else 0.0
    wall = min(float(rec.get("wall_s", 0.0)), 10.0)
    return util - frag + 0.1 * min(margin_rel, 1.0) - 0.01 * wall


@dataclass
class LedgerDataset:
    features: np.ndarray  # [R, len(FEATURES)] float64
    contrib: np.ndarray   # [R, len(SCORE_STACK)] per-priority share
    quality: np.ndarray   # [R] float64
    rounds: List[int] = field(default_factory=list)
    versions: List[str] = field(default_factory=list)
    skipped: int = 0      # undecodable lines + recordless rounds

    def __len__(self) -> int:
        return int(self.features.shape[0])

    def active_priorities(self) -> List[str]:
        """SCORE_STACK names with any observed contribution — the only
        rows a trainer has evidence about."""
        return [name for s, name in enumerate(SCORE_STACK)
                if np.any(self.contrib[:, s] != 0.0)]


def _row(rec: Dict[str, Any]) -> Optional[Tuple[List[float], List[float]]]:
    """One ledger record -> (feature row, contrib-share row), or None
    when the record carries no scores aggregate (nothing to learn
    from). Reads only known keys — unknown keys and versions pass
    through untouched, per the ledger contract."""
    scores = rec.get("scores")
    if not isinstance(scores, dict):
        return None
    tele = rec.get("telemetry") or {}
    margin = float((scores.get("margin") or {}).get("mean", 0.0))
    mean_total = abs(float(scores.get("mean", 0.0)))
    flips = 0
    for entry in (rec.get("shadow") or {}).values():
        if isinstance(entry, dict):
            flips += int(entry.get("flips", 0))
    feats = [
        float((tele.get("util") or {}).get("cpu", 0.0)),
        float((tele.get("frag") or {}).get("cpu", 0.0)),
        margin,
        margin / mean_total if mean_total > 0 else 0.0,
        float(rec.get("wall_s", 0.0)),
        float(rec.get("placed", 0) or 0),
        float(rec.get("pending", 0) or 0),
        float(flips),
        float(rec.get("preempted", 0) or 0),
    ]
    breakdown = scores.get("breakdown") or {}
    raw = [abs(float(breakdown.get(name, 0.0))) for name in SCORE_STACK]
    total = sum(raw)
    shares = [v / total for v in raw] if total > 0 else raw
    return feats, shares


def build_dataset(records: List[Dict[str, Any]],
                  skipped: int = 0) -> LedgerDataset:
    rows: List[List[float]] = []
    shares: List[List[float]] = []
    quality: List[float] = []
    rounds: List[int] = []
    versions: List[str] = []
    for rec in records:
        if not isinstance(rec, dict):
            skipped += 1
            continue
        parsed = _row(rec)
        if parsed is None:
            skipped += 1
            continue
        feats, share = parsed
        rows.append(feats)
        shares.append(share)
        quality.append(round_quality(rec))
        rounds.append(int(rec.get("round", 0) or 0))
        versions.append(str(rec.get("weights_version", "")))
    if rows:
        features = np.asarray(rows, np.float64)
        contrib = np.asarray(shares, np.float64)
        q = np.asarray(quality, np.float64)
    else:
        features = np.zeros((0, len(FEATURES)), np.float64)
        contrib = np.zeros((0, len(SCORE_STACK)), np.float64)
        q = np.zeros((0,), np.float64)
    return LedgerDataset(features=features, contrib=contrib, quality=q,
                         rounds=rounds, versions=versions,
                         skipped=skipped)


def load_dataset(path: str) -> LedgerDataset:
    records, skipped = load_records(path)
    return build_dataset(records, skipped=skipped)
