"""Promotion CI: storm trace-replay under a candidate weight vector.

The bench's storm harness (bench.py --trace) proves the overload plane
against synthetic arrival traces and per-class p99 SLO gates; the
autopilot reuses the same discipline as its promotion CI: before a
candidate may go live, a bounded arrival trace is replayed through an
ISOLATED store + scheduler with the candidate as the live vector, and
the per-class `STORM_SLO_P99` gates must pass — plus a scalar replay
objective (packedness, decisiveness, full placement) that must not
regress against the same replay under the current production weights.

This module is a library, not a bench: it returns a ReplayReport and
never exits the process. The gate constants live HERE and bench.py
imports them, so the bench gates and the promotion-CI gates cannot
drift apart.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..api import types as api
from ..utils import tracing

# class -> pod priority (sched/queue.py bands: system >= 2e9,
# high >= 1000, normal > 0, low <= 0)
STORM_PRIORITY = {"system": 2_000_000_000, "high": 10_000,
                  "normal": 10, "low": 0}
# p99 SLO gates in seconds for the PROTECTED classes — the ones above
# the shed threshold, which the overload plane exists to defend (see
# bench.py's storm harness for the full rationale and headroom notes)
STORM_SLO_P99 = {"system": 5.0, "high": 5.0}


def default_trace(wave: int) -> List[Dict[str, int]]:
    """The promotion-CI mini-trace: three ticks at one wave of low
    arrivals with the high/system trickle riding along — enough to
    exercise the priority bands and the score path without turning
    every promotion into a minutes-long storm."""
    return [{"low": wave, "high": 4, "system": 2}] * 3


def _p99(samples: List[float]) -> float:
    if not samples:
        return 0.0
    s = sorted(samples)
    return s[min(int(len(s) * 0.99), len(s) - 1)]


def _node(name: str, cpu: str) -> api.Node:
    alloc = api.resource_list(cpu=cpu, memory="32Gi", pods=110)
    return api.Node(
        metadata=api.ObjectMeta(name=name),
        spec=api.NodeSpec(),
        status=api.NodeStatus(capacity=dict(alloc), allocatable=alloc,
                              conditions=[api.NodeCondition(
                                  api.NODE_READY, api.COND_TRUE)]))


def _pod(name: str, cls: str, cpu: str, node_name: str = "") -> api.Pod:
    reqs = api.resource_list(cpu=cpu)
    p = api.Pod(
        metadata=api.ObjectMeta(name=name),
        spec=api.PodSpec(containers=[api.Container(
            name="c",
            resources=api.ResourceRequirements(requests=reqs))]))
    p.spec.priority = STORM_PRIORITY[cls]
    if node_name:
        p.spec.node_name = node_name
    return p


@dataclass
class ReplayReport:
    """One replay's gate verdict + the scalar objective candidates are
    ranked by. objective = placed_frac - 0.5*frag + 0.5*margin_rel:
    place everything, leave free capacity unfragmented, and decide by
    clear margins (margin relative to the score scale, so differently
    scaled weight tables compare fairly)."""

    name: str
    version: str
    placed: int = 0
    total: int = 0
    p99: Dict[str, float] = field(default_factory=dict)
    util: float = 0.0
    frag: float = 0.0
    margin_rel: float = 0.0
    objective: float = 0.0
    passed: bool = True
    failures: List[str] = field(default_factory=list)
    wall_s: float = 0.0

    def as_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name, "version": self.version,
            "placed": self.placed, "total": self.total,
            "p99": {c: round(v, 4) for c, v in self.p99.items()},
            "util": round(self.util, 4), "frag": round(self.frag, 4),
            "margin_rel": round(self.margin_rel, 4),
            "objective": round(self.objective, 4),
            "passed": self.passed, "failures": list(self.failures),
            "wall_s": round(self.wall_s, 3)}


def run_replay(weights: Optional[Dict[str, float]] = None, *,
               name: str = "candidate", nodes: int = 4,
               node_cpu: str = "8", pod_cpu: str = "100m",
               wave: int = 16,
               trace: Optional[List[Dict[str, int]]] = None,
               prefill: Optional[Dict[int, int]] = None,
               slo: Optional[Dict[str, float]] = None,
               slo_scale: float = 1.0,
               max_drain: int = 500) -> ReplayReport:
    """Replay one arrival trace through an isolated store + scheduler.

    weights: the candidate table loaded as the LIVE vector for the
    whole replay (None = the scheduler's static defaults — the
    baseline the controller compares candidates against). prefill
    pre-binds `cores` one-core pods onto node index `i` per {i: cores}
    entry, so tests can shape the cluster the score planes must
    discriminate over. Gates: per-class p99 <= slo[cls] * slo_scale
    and full eventual placement for EVERY class.

    Uses the process-global flight recorder when one is active (the
    replay's rounds ride the live ledger, visible promotion CI); brings
    up and tears down its own otherwise. Margin extraction filters on
    the replay's own weights_version and round ids, so a concurrently
    traced production scheduler only adds noise-free records.
    """
    from ..runtime.store import ObjectStore
    from ..sched.scheduler import Scheduler

    rec = tracing.active()
    owned = rec is None
    if owned:
        rec = tracing.enable()
    trace = list(trace) if trace is not None else default_trace(wave)
    slo = dict(STORM_SLO_P99 if slo is None else slo)
    store = ObjectStore()
    for i in range(nodes):
        store.create("nodes", _node(f"rp-n{i}", node_cpu))
    for i, cores in (prefill or {}).items():
        for k in range(int(cores)):
            store.create("pods", _pod(f"rp-pre{i}-{k}", "normal", "1",
                                      node_name=f"rp-n{i}"))
    sched = Scheduler(store, wave_size=wave)
    report = ReplayReport(name=name if weights else "baseline",
                          version="static")
    try:
        if weights:
            sched.weightbook.load_entries(
                [{"name": name, "weights": dict(weights),
                  "role": api.WEIGHT_PROFILE_ROLE_LIVE}])
        report.version = sched.weightbook.live_version()
        # warm the kernel cache OUTSIDE the latency clock: the first
        # round under a plane-activating vector pays the XLA compile
        # (seconds), and a p99 gate must judge scheduling, not
        # compilation — one throwaway pod takes the hit here, after the
        # candidate is live so its planes are the ones compiled
        store.create("pods", _pod("rp-warm", "normal", pod_cpu))
        sched.run_once(timeout=60.0)
        t_start = time.monotonic()
        rid_start = rec._next_rid
        created: Dict[str, tuple] = {}  # uid -> (cls, t_enqueue)
        latency: Dict[str, List[float]] = {c: [] for c in STORM_PRIORITY}
        bound: set = set()

        def _scan():
            now = time.monotonic()
            for p in store.list("pods"):
                if p.uid in created and p.uid not in bound \
                        and p.spec.node_name:
                    bound.add(p.uid)
                    cls, t0 = created[p.uid]
                    latency[cls].append(now - t0)

        seq = 0
        for tick in trace:
            for cls, count in tick.items():
                for _ in range(int(count)):
                    p = _pod(f"rp-{cls}-{seq}", cls, pod_cpu)
                    seq += 1
                    obj = store.create("pods", p)
                    created[obj.uid] = (cls, time.monotonic())
            sched.run_once(timeout=5.0)
            _scan()
        # drain: every pod must eventually place (feasibility permitting
        # is the caller's job — the default trace always fits)
        spins = 0
        while len(bound) < len(created) and spins < max_drain:
            n = sched.run_once(timeout=5.0)
            _scan()
            spins = spins + 1 if n == 0 else 0
            if n == 0:
                time.sleep(0.002)
        _scan()
        report.total = len(created)
        report.placed = len(bound)
        report.p99 = {c: _p99(v) for c, v in latency.items() if v}
        # cluster shape after the replay, straight from store truth:
        # cpu utilization and the fragmentation index over free cpu
        free: List[float] = []
        total_alloc = total_req = 0.0
        by_node: Dict[str, float] = {}
        for p in store.list("pods"):
            if p.spec.node_name:
                req = 0.0
                for c in p.spec.containers:
                    # canonical resource maps carry milli-cpu ints
                    # (api.resource_list); units cancel in the ratios
                    req += float((c.resources.requests or {})
                                 .get("cpu", 0))
                by_node[p.spec.node_name] = \
                    by_node.get(p.spec.node_name, 0.0) + req
        for nd in store.list("nodes"):
            alloc = float(nd.status.allocatable.get("cpu", 0))
            used = by_node.get(nd.metadata.name, 0.0)
            total_alloc += alloc
            total_req += used
            free.append(max(alloc - used, 0.0))
        report.util = total_req / total_alloc if total_alloc else 0.0
        total_free = sum(free)
        report.frag = (1.0 - max(free) / total_free) if total_free else 0.0
        # decisiveness: margin-over-runner-up relative to the score
        # scale, from THIS replay's traced rounds only
        margins: List[float] = []
        for row in rec.ledger_rows():
            if row.get("round", 0) < rid_start:
                continue
            if row.get("weights_version") != report.version:
                continue
            sc = row.get("scores")
            if not sc or "margin" not in sc:
                continue
            mean_total = abs(float(sc.get("mean", 0.0)))
            if mean_total > 0:
                margins.append(
                    float(sc["margin"]["mean"]) / mean_total)
        report.margin_rel = (sum(margins) / len(margins)
                             if margins else 0.0)
        placed_frac = report.placed / report.total if report.total else 1.0
        report.objective = (placed_frac - 0.5 * report.frag
                            + 0.5 * min(report.margin_rel, 1.0))
        for cls, bound_s in slo.items():
            p99c = report.p99.get(cls)
            if p99c is not None and p99c > bound_s * slo_scale:
                report.failures.append(
                    f"{cls}-class p99 {p99c*1e3:.0f}ms over its "
                    f"{bound_s*slo_scale*1e3:.0f}ms SLO gate")
        if report.placed < report.total:
            report.failures.append(
                f"{report.total - report.placed} pods never placed")
        report.passed = not report.failures
        report.wall_s = time.monotonic() - t_start
        return report
    finally:
        sched.close()
        if owned:
            tracing.disable()
