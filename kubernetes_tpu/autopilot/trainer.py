"""Offline weight trainers: ledger dataset -> candidate weight table.

Regression first: RidgeTrainer fits per-priority score-contribution
shares against round quality (closed-form ridge, numpy only) and turns
the coefficients into bounded multiplicative nudges of the current
weight table. A policy-gradient trainer is stubbed behind the same
interface — the seam the RL papers plug into (PAPERS.md: "Learning to
Score", RL custom scheduler) without touching the promotion pipeline.

A trainer only re-weights priorities it has EVIDENCE about (nonzero
contribution share somewhere in the dataset); everything else keeps
the base weight. Candidates are emitted as WeightProfile objects
through the store watch path (emit_candidate), so the scheduler's
informer — and the shadow observatory behind it — picks them up
exactly like an operator-applied profile.
"""

from __future__ import annotations

from typing import Dict, Optional, Union

import numpy as np

from ..api import types as api
from ..ops.kernel import Weights
from ..ops.scores import SCORE_STACK, WEIGHT_FIELDS, stack_weights
from ..utils import faultpoints
from .dataset import LedgerDataset

# evidence floor: below this many scored rounds a fit is noise
MIN_ROUNDS = 4


def weights_table(w: Union[Weights, Dict[str, float]]) -> Dict[str, float]:
    """A Weights namedtuple (or an already-plain table) as a
    WeightProfile weights dict: tunable, nonzero rows only."""
    if isinstance(w, dict):
        return {k: float(v) for k, v in w.items()
                if WEIGHT_FIELDS.get(k) is not None and float(v)}
    vec = stack_weights(w)
    return {name: float(vec[s]) for s, name in enumerate(SCORE_STACK)
            if WEIGHT_FIELDS[name] is not None and vec[s]}


class Trainer:
    """The trainer interface: fit a dataset, return a SCORE_STACK-keyed
    candidate weight table (HostExtra never appears — it is pinned)."""

    name = "trainer"

    def __init__(self, base: Union[Weights, Dict[str, float]]):
        self.base = weights_table(base)

    def fit(self, ds: LedgerDataset) -> Dict[str, float]:
        raise NotImplementedError


class RidgeTrainer(Trainer):
    """Closed-form ridge regression of contribution shares vs quality.

    beta = (X'X + lam I)^-1 X'y over centered columns; coefficients are
    normalized to [-1, 1] and applied as bounded multiplicative nudges:
    an active priority moves by at most `step` of its base weight, and
    a priority with base weight 0 (its plane was activated by some live
    profile in the data) is introduced at `anchor * step * beta` only
    when its coefficient is positive — negative evidence about an
    inactive plane keeps it off rather than inventing a weight for it.
    """

    name = "ridge"

    def __init__(self, base: Union[Weights, Dict[str, float]],
                 ridge_lambda: float = 1.0, step: float = 0.5,
                 min_rounds: int = MIN_ROUNDS):
        super().__init__(base)
        self.ridge_lambda = float(ridge_lambda)
        self.step = float(step)
        self.min_rounds = int(min_rounds)

    def fit(self, ds: LedgerDataset) -> Dict[str, float]:
        faultpoints.fire("autopilot.train", payload=ds)
        if len(ds) < self.min_rounds:
            raise ValueError(
                f"ledger dataset has {len(ds)} scored rounds; "
                f"{self.min_rounds} required for a fit")
        names = [n for n in ds.active_priorities()
                 if WEIGHT_FIELDS[n] is not None]
        if not names:
            raise ValueError("no tunable priority has any observed "
                             "contribution in the dataset")
        idx = [SCORE_STACK.index(n) for n in names]
        X = ds.contrib[:, idx]
        X = X - X.mean(axis=0, keepdims=True)
        y = ds.quality - ds.quality.mean()
        A = X.T @ X + self.ridge_lambda * np.eye(len(idx))
        beta = np.linalg.solve(A, X.T @ y)
        bmax = float(np.max(np.abs(beta)))
        if bmax > 0:
            beta = beta / bmax
        # scale anchor for introducing a zero-base priority: the median
        # nonzero base weight keeps the new row on the table's scale
        nonzero = [v for v in self.base.values() if v > 0]
        anchor = float(np.median(nonzero)) if nonzero else 1.0
        out = dict(self.base)
        for k, n in enumerate(names):
            b = float(beta[k])
            basev = self.base.get(n, 0.0)
            if basev > 0:
                w = basev * (1.0 + self.step * b)
            elif b > 0:
                w = anchor * self.step * b
            else:
                continue
            w = max(0.0, round(w, 4))
            if w:
                out[n] = w
            else:
                out.pop(n, None)
        return out


class PolicyGradientTrainer(Trainer):
    """The RL seam: same interface, same emit path, different fit. Not
    implemented — the replay harness (autopilot/replay.py) is the
    episode generator a REINFORCE-style fit would roll out against,
    and the controller consumes its candidates unchanged."""

    name = "policy_gradient"

    def fit(self, ds: LedgerDataset) -> Dict[str, float]:
        faultpoints.fire("autopilot.train", payload=ds)
        raise NotImplementedError(
            "policy-gradient training is a stubbed seam; use "
            "RidgeTrainer (the promotion pipeline is trainer-agnostic)")


def emit_candidate(store, name: str, weights: Dict[str, float],
                   namespace: str = "default"):
    """Emit a trained weight table as a candidate WeightProfile through
    the store — the same watch path an operator-applied profile takes,
    so the scheduler's informer loads it and the shadow observatory
    starts judging it immediately. Updates in place when the profile
    already exists (a retrained candidate supersedes its old table)."""
    existing = store.get("weightprofiles", namespace, name)
    if existing is not None:
        existing.spec.weights = dict(weights)
        existing.spec.role = api.WEIGHT_PROFILE_ROLE_CANDIDATE
        return store.update("weightprofiles", existing)
    wp = api.WeightProfile(
        metadata=api.ObjectMeta(name=name, namespace=namespace),
        spec=api.WeightProfileSpec(
            weights=dict(weights),
            role=api.WEIGHT_PROFILE_ROLE_CANDIDATE))
    return store.create("weightprofiles", wp)
