"""Chaos engineering surface: continuously-checked cluster invariants
(invariants.py) + the seeded fault-schedule campaign runner with
failing-schedule shrinking (campaign.py, `python -m kubernetes_tpu.chaos`).

The reference exercises failure paths by killing whole components
(test/e2e/chaosmonkey); this framework's failure surface is internal —
~25 named fault points (utils/faultpoints.py) across the kernel, bind,
watch, snapshot, mesh, and autopilot planes. The campaign composes
those points into randomized fault *schedules*, replays them against a
kubemark HollowCluster with the invariant checker armed after every
scheduling round, and shrinks any violating schedule to a minimal
`KTPU_FAULTPOINTS` reproducer string.
"""

from .invariants import InvariantChecker, InvariantViolation

__all__ = ["InvariantChecker", "InvariantViolation"]
