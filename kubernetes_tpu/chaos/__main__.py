"""Chaos campaign CLI.

  python -m kubernetes_tpu.chaos --seed 7 --schedules 50
  python -m kubernetes_tpu.chaos --seed 7 --schedules 200 --budget 300
  KTPU_FAULTPOINTS='snapshot.write=corrupt::4' \
      python -m kubernetes_tpu.chaos --repro --seed 7

Exit status 0 = every schedule ran clean; 1 = at least one invariant
violation (each printed with its shrunk KTPU_FAULTPOINTS reproducer).
"""

from __future__ import annotations

import argparse
import os
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m kubernetes_tpu.chaos",
        description="seeded fault-schedule campaign with invariant "
                    "checking and failing-schedule shrinking")
    ap.add_argument("--seed", type=int, default=0,
                    help="campaign seed (workload + schedule sampling)")
    ap.add_argument("--schedules", type=int, default=50,
                    help="fault schedules to sample and replay")
    ap.add_argument("--ticks", type=int, default=8,
                    help="virtual-clock ticks per replay")
    ap.add_argument("--budget", type=float, default=None, metavar="SECONDS",
                    help="wall-clock budget; stop sampling when exceeded")
    ap.add_argument("--repro", action="store_true",
                    help="replay ONE schedule from $KTPU_FAULTPOINTS "
                         "against the --seed scenario (reproducer mode)")
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from .campaign import replay, run_campaign

    if args.repro:
        spec = os.environ.pop("KTPU_FAULTPOINTS", "")
        if not spec:
            print("--repro needs KTPU_FAULTPOINTS set", file=sys.stderr)
            return 2
        out = replay((), args.seed, ticks=args.ticks, env_spec=spec)
        fired = {k: v for k, v in out.injected.items() if v}
        print(f"repro seed={args.seed} spec={spec!r}: "
              f"checks={out.checks} placed={out.placed} fired={fired}")
        if out.violated:
            print(f"VIOLATION {out.violation}: {out.detail}")
            return 1
        print("clean")
        return 0

    res = run_campaign(args.seed, args.schedules, ticks=args.ticks,
                       budget_s=args.budget, log=print)
    print(f"campaign seed={res.seed}: {res.schedules} schedules, "
          f"{res.checks_total} invariant checks, "
          f"{res.injected_total} faults fired, "
          f"{len(res.findings)} violation(s)")
    for f in res.findings:
        print(f"  {f.outcome.violation}: KTPU_FAULTPOINTS='{f.env}' "
              f"--seed {f.seed} (env re-triggers: {f.env_retriggers})")
    return 0 if res.ok else 1


if __name__ == "__main__":
    sys.exit(main())
