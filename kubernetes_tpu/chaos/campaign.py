"""Seeded chaos-campaign runner with failing-schedule shrinking.

The reference's chaos suite (test/e2e/chaosmonkey) kills whole
components and asserts the cluster recovers. This framework's failure
surface is finer-grained — ~25 named fault points (utils/faultpoints)
across the kernel, bind, watch, snapshot, mesh, and poison planes — so
its chaosmonkey analog composes those points into randomized fault
*schedules*:

  FaultSpec(point, mode, arg, times, tick)

A schedule is 2-4 specs (sometimes seeded from NASTY_PAIRS, the
combinations most likely to interact) fired at virtual-clock ticks of
a fixed kubemark scenario: a small HollowCluster, a steady pod
arrival stream, two gangs (one that fits, one that never does), a
node-status heartbeat per tick, and the invariant checker
(chaos/invariants.py) armed after every scheduling round. A correct
scheduler tolerates EVERY such schedule with zero invariant
violations — the faults are all recoverable by construction (breaker,
watchdog, bind reconciler, poison isolation...).

When a schedule DOES violate an invariant, the campaign shrinks it:
greedy removal of whole specs, then tick normalization (fire at t=0),
then times reduction — each step re-replayed, kept only while the
violation still reproduces. The minimal schedule is emitted as a
ready-to-paste `KTPU_FAULTPOINTS` string plus the campaign seed, so
the reproducer re-triggers with zero campaign machinery:

  KTPU_FAULTPOINTS='snapshot.write=corrupt::4' \
      python -m kubernetes_tpu.chaos --repro --seed 7

Determinism: the workload is derived from the seed alone (never from
the schedule), so shrinking never perturbs the scenario; the virtual
clock advances one second per tick; latency args are small and
bounded so wall time stays bounded.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field, replace
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..utils import faultpoints
from .invariants import InvariantChecker, InvariantViolation

# -- the fault-schedule space -----------------------------------------------
#
# point -> modes a correct scheduler must tolerate without invariant
# violations in the campaign scenario. Deliberately narrower than the
# full registry: modes that model UPSTREAM data loss the scheduler
# cannot observe (watch.deliver=drop swallows the pod-add event itself)
# would trip conservation on a healthy build, and points whose
# subsystem is not running in the scenario (autoscaler, autopilot,
# REST informers) would never fire. Everything here is expressible as
# a KTPU_FAULTPOINTS token (no custom fn/exc), so every shrunk
# reproducer is a paste-able env string.
SAMPLABLE: Tuple[Tuple[str, Tuple[str, ...]], ...] = (
    ("kernel.wave", ("raise", "latency")),
    ("kernel.round", ("raise", "latency")),
    ("kernel.gang", ("raise", "latency")),
    ("kernel.hang", ("latency",)),
    ("device.lost", ("raise",)),
    ("queue.shed", ("drop",)),
    ("bind.post", ("raise", "latency", "drop")),
    ("watch.deliver", ("latency",)),
    ("snapshot.write", ("corrupt", "latency")),
    ("heartbeat.deliver", ("drop", "latency")),
    ("featurize.poison", ("raise",)),
    ("wave.poison", ("raise",)),
    ("queue.quarantine", ("drop",)),
    ("lease.renew", ("raise", "drop")),
    # control-plane outage: severs bind POSTs and truth GETs together;
    # sampled times (1-3) stay below the spool threshold (3 failed
    # attempts trip the breaker, the 4th fire darkens the truth GET),
    # so a healthy build conserves without ever spooling — duration
    # outages are driven explicitly by the outage tests/bench
    ("store.outage", ("raise",)),
    # capacity chaos: a raise here models HBM RESOURCE_EXHAUSTED at the
    # dispatch seam — the capacity-fault ladder must compact and retry
    # without a device conviction, a mesh reform, or a pod conviction
    ("device.oom", ("raise",)),
    # compaction chaos: a crash or stall at the compaction entry must
    # leave the live snapshot untouched (the scratch rebuild only swaps
    # in after it fully succeeds)
    ("snapshot.compact", ("raise", "latency")),
)

# point-pairs with a history of interacting badly (ISSUE 17): a device
# loss racing a poison conviction, a wedged dispatch while heartbeats
# stop, a failing bind POST while leadership is in doubt. The sampler
# seeds roughly a third of its schedules from one of these.
NASTY_PAIRS: Tuple[Tuple[Tuple[str, str], Tuple[str, str]], ...] = (
    (("device.lost", "raise"), ("wave.poison", "raise")),
    (("kernel.hang", "latency"), ("heartbeat.deliver", "drop")),
    (("bind.post", "raise"), ("lease.renew", "raise")),
    # a capacity fault whose recovery compaction itself crashes: the
    # ladder must salvage through the host twin (guarded compact),
    # never wedge the round or trip the breaker via a false conviction
    (("device.oom", "raise"), ("snapshot.compact", "raise")),
)

_LATENCY_ARGS = (0.005, 0.01, 0.02)


@dataclass(frozen=True)
class FaultSpec:
    """One armed fault point inside a schedule. `tick` is the virtual-
    clock tick (0-based) the point is activated at; `times` bounds how
    many fires apply (faultpoints semantics, never None here so
    reproducer strings stay bounded)."""

    point: str
    mode: str
    arg: float = 0.0
    times: int = 1
    tick: int = 0

    def token(self) -> str:
        """The KTPU_FAULTPOINTS token for this spec (tick elided: env
        activation arms at process start)."""
        if self.mode == "latency":
            return f"{self.point}={self.mode}:{self.arg}:{self.times}"
        return f"{self.point}={self.mode}::{self.times}"


def env_string(specs: Sequence[FaultSpec]) -> str:
    """The ready-to-paste KTPU_FAULTPOINTS string for a schedule."""
    return ",".join(s.token() for s in specs)


@dataclass
class ReplayOutcome:
    violation: Optional[str] = None  # invariant name, or None = clean
    detail: str = ""
    digest: dict = field(default_factory=dict)
    injected: Dict[str, int] = field(default_factory=dict)
    placed: int = 0
    checks: int = 0

    @property
    def violated(self) -> bool:
        return self.violation is not None


@dataclass
class Finding:
    """One violating schedule, shrunk."""

    seed: int
    schedule: List[FaultSpec]
    minimal: List[FaultSpec]
    outcome: ReplayOutcome
    env: str  # KTPU_FAULTPOINTS string of the minimal schedule
    env_retriggers: bool  # replaying the env form alone still violates


@dataclass
class CampaignResult:
    seed: int
    schedules: int = 0
    injected_total: int = 0
    checks_total: int = 0
    findings: List[Finding] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.findings


# -- schedule sampling ------------------------------------------------------

def sample_schedule(rng: random.Random) -> List[FaultSpec]:
    """2-4 fault specs at ticks 0..5; ~1/3 of schedules start from a
    NASTY_PAIRS combination, the rest draw independently from
    SAMPLABLE. Points are distinct within one schedule (faultpoints
    keeps one active fault per point)."""
    specs: List[FaultSpec] = []
    taken = set()

    def add(point: str, mode: str):
        if point in taken:
            return
        taken.add(point)
        arg = rng.choice(_LATENCY_ARGS) if mode == "latency" else 0.0
        times = rng.randint(2, 4) if mode == "corrupt" else rng.randint(1, 3)
        specs.append(FaultSpec(point=point, mode=mode, arg=arg,
                               times=times, tick=rng.randrange(6)))

    if rng.random() < 0.34:
        for point, mode in rng.choice(NASTY_PAIRS):
            add(point, mode)
    want = rng.randint(2, 4)
    while len(specs) < want:
        point, modes = rng.choice(SAMPLABLE)
        add(point, rng.choice(modes))
    specs.sort(key=lambda s: (s.tick, s.point))
    return specs


# -- the replay scenario ----------------------------------------------------

def _mk_pod(name: str, cpu: int, priority: int = 0,
            gang: Optional[str] = None, min_member: int = 0):
    from ..api import types as api

    p = api.Pod(
        metadata=api.ObjectMeta(name=name, namespace="default"),
        spec=api.PodSpec(
            priority=priority,
            containers=[api.Container(
                name="c",
                resources=api.ResourceRequirements(
                    requests={"cpu": cpu, "memory": 64 << 20}))]))
    if gang:
        p.metadata.annotations = {
            "pod-group.scheduling.k8s.io/name": gang,
            "pod-group.scheduling.k8s.io/min-available": str(min_member)}
    return p


def _workload(seed: int, ticks: int) -> Dict[int, list]:
    """tick -> pods arriving at that tick. Derived from the seed ALONE
    (never the fault schedule), so shrinking a schedule replays the
    identical scenario. The mix: a trickle of small plain pods (some
    below the shed priority threshold), a 2-member gang that fits, and
    a 3x9000m gang that can never fit 2 hollow nodes — it retries
    every round, keeping the joint-assignment + recheck path hot under
    whatever faults are armed."""
    rng = random.Random(seed * 7919 + 17)
    arrivals: Dict[int, list] = {t: [] for t in range(ticks)}
    n = 0
    for t in range(ticks):
        for _ in range(rng.randint(0, 2)):
            arrivals[t].append(_mk_pod(
                f"load-{seed}-{n}", cpu=rng.choice((100, 250)),
                priority=rng.choice((0, 1500))))
            n += 1
    fit_tick = rng.randrange(max(1, ticks // 2))
    arrivals[fit_tick].extend(
        _mk_pod(f"gfit-{seed}-{i}", cpu=4000, gang=f"gfit-{seed}",
                min_member=2) for i in range(2))
    big_tick = rng.randrange(max(1, ticks // 2))
    arrivals[big_tick].extend(
        _mk_pod(f"gbig-{seed}-{i}", cpu=9000, gang=f"gbig-{seed}",
                min_member=3) for i in range(3))
    return arrivals


def replay(specs: Sequence[FaultSpec], seed: int, ticks: int = 8,
           env_spec: Optional[str] = None,
           configure: Optional[Callable] = None,
           journal_path: Optional[str] = None,
           restart_tick: Optional[int] = None) -> ReplayOutcome:
    """Replay one fault schedule against the seeded scenario with the
    invariant checker armed. Returns the outcome; never raises for a
    violation (the campaign decides what to do with it).

    env_spec: instead of tick-scheduled activation, arm this
    KTPU_FAULTPOINTS string before the first tick — the reproducer
    path, verifying a shrunk schedule re-triggers in its env form.
    configure: optional hook(sched) run before the first tick (the
    deliberately-broken-build acceptance test disables the gang
    rollback through it).
    journal_path: durable bind-intent journal for the scenario
    scheduler (control-plane outage coverage).
    restart_tick: kill -9 analog — at this tick the scheduler is
    abandoned mid-flight (no drain, no farewell) and a fresh one is
    constructed over the same store + journal; construction replays
    the journal before its first wave, and the same invariant checker
    keeps watching across the restart."""
    import jax

    jax.config.update("jax_platforms", "cpu")

    from ..kubemark.hollow import HollowCluster
    from ..ops.encoding import Caps
    from ..runtime.store import ObjectStore
    from ..sched.scheduler import Scheduler

    by_tick: Dict[int, List[FaultSpec]] = {}
    for s in specs:
        by_tick.setdefault(s.tick, []).append(s)
    arrivals = _workload(seed, ticks)

    faultpoints.reset()
    store = ObjectStore()
    vclock = [1000.0]

    def _mk_sched() -> "Scheduler":
        s = Scheduler(store, wave_size=8, caps=Caps(M=64, P=16, LV=64),
                      clock=lambda: vclock[0], shed_watermark=8,
                      shed_age_s=1.0,
                      # short, deterministic store-probe window: the
                      # jitter pin makes retry_at = trip + cooldown
                      # exactly, so outage recovery is tick-predictable
                      store_breaker_cooldown=2.0,
                      # housekeeping compaction cadence: gives the
                      # snapshot.compact chaos point a fire path in
                      # schedules that churn rows (the oom ladder's
                      # forced compactions fire it regardless)
                      compact_interval=2.0,
                      bind_journal_path=journal_path)
        s.storehealth.jitter = lambda: 0.5
        return s

    sched = _mk_sched()
    checker = InvariantChecker(metrics=sched.metrics, strict=True)
    sched.invariants = checker
    if configure is not None:
        configure(sched)
    # racks/generations stamped so fault injection also exercises the
    # dense topology columns (rack_id/superpod_id/accel_gen scatter).
    cluster = HollowCluster(store, 2, racks=2, generations=2,
                            clock=lambda: vclock[0])
    out = ReplayOutcome()
    try:
        for node in cluster.nodes:
            node.kubelet.register_node()
        if env_spec is not None:
            faultpoints.activate_spec(env_spec)
        for t in range(ticks + 2):  # +2 drain ticks, faults quiescent
            if restart_tick is not None and t == restart_tick:
                sched = _mk_sched()
                sched.invariants = checker
                if configure is not None:
                    configure(sched)
            for s in by_tick.get(t, ()):
                faultpoints.activate(s.point, s.mode, arg=s.arg,
                                     times=s.times)
            vclock[0] += 1.0
            # the scenario's node-status plane: one heartbeat per tick
            # (also what carries a snapshot.write corruption into the
            # topo upload group — see state/snapshot.py update_node)
            cluster.nodes[t % len(cluster.nodes)].kubelet.heartbeat()
            for pod in arrivals.get(t, ()):
                store.create("pods", pod)
            out.placed += sched.run_once()
            out.placed += sched.run_once()
    except InvariantViolation as v:
        out.violation = v.invariant
        out.detail = v.detail
        out.digest = v.digest
    finally:
        out.checks = checker.checks
        out.injected = {s.point: faultpoints.hits(s.point) for s in specs}
        if env_spec is not None:
            for name, _, _, _ in faultpoints.parse(env_spec):
                out.injected[name] = faultpoints.hits(name)
        faultpoints.reset()
        sched.close()
    return out


# -- shrinking --------------------------------------------------------------

def shrink(specs: Sequence[FaultSpec], seed: int, ticks: int = 8,
           configure: Optional[Callable] = None,
           log: Optional[Callable[[str], None]] = None
           ) -> Tuple[List[FaultSpec], ReplayOutcome]:
    """Greedily minimize a violating schedule: drop whole specs, then
    normalize surviving ticks to 0, then reduce times to 1 — keeping
    each step only if the violation still reproduces. Returns the
    minimal schedule and its replay outcome."""

    def still_violates(cand: Sequence[FaultSpec]) -> Optional[ReplayOutcome]:
        o = replay(cand, seed, ticks=ticks, configure=configure)
        return o if o.violated else None

    cur = list(specs)
    best = still_violates(cur)
    if best is None:  # flaked? caller decides; report the original
        return cur, replay(cur, seed, ticks=ticks, configure=configure)
    # pass 1: drop specs
    changed = True
    while changed and len(cur) > 1:
        changed = False
        for i in range(len(cur)):
            cand = cur[:i] + cur[i + 1:]
            o = still_violates(cand)
            if o is not None:
                if log:
                    log(f"shrink: dropped {cur[i].token()}")
                cur, best, changed = cand, o, True
                break
    # pass 2: fire everything at tick 0 (makes the schedule exactly
    # reproducible as a KTPU_FAULTPOINTS env activation)
    cand = [replace(s, tick=0) for s in cur]
    if any(s.tick for s in cur):
        o = still_violates(cand)
        if o is not None:
            if log:
                log("shrink: normalized ticks to 0")
            cur, best = cand, o
    # pass 3: minimum times budget
    for i, s in enumerate(cur):
        while s.times > 1:
            cand_spec = replace(s, times=s.times - 1)
            cand = cur[:i] + [cand_spec] + cur[i + 1:]
            o = still_violates(cand)
            if o is None:
                break
            if log:
                log(f"shrink: {s.point} times -> {cand_spec.times}")
            s = cand_spec
            cur, best = cand, o
    return cur, best


# -- the campaign -----------------------------------------------------------

def run_campaign(seed: int, schedules: int, ticks: int = 8,
                 budget_s: Optional[float] = None,
                 configure: Optional[Callable] = None,
                 log: Optional[Callable[[str], None]] = None
                 ) -> CampaignResult:
    """Sample and replay `schedules` fault schedules; shrink every
    violation to a minimal reproducer and verify its env-string form
    re-triggers. budget_s (wall seconds, monotonic) stops sampling
    early — the schedules already run still count."""
    import time as _time

    rng = random.Random(seed)
    result = CampaignResult(seed=seed)
    t0 = _time.monotonic()
    for i in range(schedules):
        if budget_s is not None and _time.monotonic() - t0 > budget_s:
            if log:
                log(f"budget exhausted after {i} schedules")
            break
        specs = sample_schedule(rng)
        out = replay(specs, seed, ticks=ticks, configure=configure)
        result.schedules += 1
        result.checks_total += out.checks
        result.injected_total += sum(out.injected.values())
        if log:
            status = out.violation or "ok"
            log(f"[{i + 1}/{schedules}] {env_string(specs)} -> {status}")
        if not out.violated:
            continue
        minimal, mo = shrink(specs, seed, ticks=ticks,
                             configure=configure, log=log)
        env = env_string(minimal)
        env_ok = replay((), seed, ticks=ticks, env_spec=env,
                        configure=configure).violated
        result.findings.append(Finding(
            seed=seed, schedule=list(specs), minimal=minimal,
            outcome=mo, env=env, env_retriggers=env_ok))
        if log:
            log(f"  VIOLATION {mo.violation}: minimal reproducer "
                f"KTPU_FAULTPOINTS='{env}' --seed {seed} "
                f"(env re-triggers: {env_ok})")
    return result
