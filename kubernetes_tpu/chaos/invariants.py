"""Continuously-checked cluster invariants.

Sixteen fault-tolerance planes (breaker, watchdog, mesh reform, poison
isolation, zone eviction, bind reconciler, autopilot rollback) each
protect the same handful of global properties, but until now those
properties were only asserted at the END of individual chaos tests.
The `InvariantChecker` turns them into a post-round observer: armed
(opt-in, `--invariants` / `Scheduler(invariants=True)`), the scheduler
calls `check()` after every scheduling round, and any violated
invariant raises a typed `InvariantViolation` carrying a full state
digest — at the round that broke it, not at drain time with the
evidence long gone. Off, the cost is one attribute None-check per
round (the tracing pattern).

Checked invariants (the `scheduler_invariant_violations_total`
{invariant=...} label set):

  conservation    every live pod this scheduler is responsible for is
                  in EXACTLY one place: bound/assumed, or one queue
                  area (active/backoff/unschedulable/shed/gang-waiting
                  /quarantine). Zero places = a lost pod; two = a
                  double-booked pod (e.g. a gang rollback that forgot
                  to un-assume before parking)
  double_bind     no pod holds capacity on two nodes in the scheduler
                  cache, and a store-bound pod's cache placement
                  agrees with API truth
  capacity        per node, the sum of resident pod requests (from the
                  API store, the truth) never exceeds allocatable
  snapshot_usage  the HBM mirror's per-node requested row equals the
                  sum of its resident pod-matrix rows (the scrubber's
                  cross-check, run continuously), and the usage plane
                  is NaN-free
  gang_atomic     every gang is 0-or-all: placed members (bound or
                  assumed) number 0 or >= minMember
  state_machine   breaker state is a legal DevicePathBreaker state
                  with sane counters, mesh quarantine partitions the
                  device set, watchdog accounting is consistent

The checker runs with the scheduler's `_mu` held (the caller's job —
Scheduler._check_invariants) and takes one atomic queue-area snapshot
(SchedulingQueue.area_uids), so it can never see a pod mid-move
between areas. It must only be called at round boundaries: mid-wave,
popped pods are legitimately in no area.

Eventual consistency: the binder runs on its own thread, so a pod can
legitimately be mid-flight between subsystems at a round boundary (a
failed async bind un-assumes and re-queues in two steps; a gang member
whose bind POST failed is re-placed next round). The cross-subsystem
invariants — conservation and gang_atomic — therefore fire only when
the SAME pod/gang is in violation at two CONSECUTIVE checks: a
transient self-clears within one round, a real leak (the class of bug
these invariants exist for) persists forever.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..api import types as api
from ..sched.storehealth import CONNECTED as STORE_CONNECTED

# capped list lengths inside digests: a 30k-pod run's violation must
# not serialize 30k uids to name three offenders
_DIGEST_CAP = 20

INVARIANTS = ("conservation", "double_bind", "capacity",
              "snapshot_usage", "gang_atomic", "state_machine")


class InvariantViolation(AssertionError):
    """A cluster invariant failed. `invariant` names which (one of
    INVARIANTS), `digest` carries the state evidence captured at the
    violating round."""

    def __init__(self, invariant: str, detail: str, digest: dict):
        super().__init__(f"invariant {invariant!r} violated: {detail}")
        self.invariant = invariant
        self.detail = detail
        self.digest = digest


def _cap(items) -> List[str]:
    out = [str(x) for x in items]
    out.sort()
    return out[:_DIGEST_CAP]


class InvariantChecker:
    """Post-round cluster-invariant observer. `strict=True` (the chaos
    campaign) raises the first violation; `strict=False` (benches,
    long e2e runs) records violations in `self.violations` and keeps
    going so a gate at the end can report all of them. Either way each
    violation increments
    scheduler_invariant_violations_total{invariant=...}."""

    def __init__(self, metrics=None, strict: bool = True):
        self.metrics = metrics
        self.strict = strict
        self.checks = 0
        self.violations: List[InvariantViolation] = []
        # two-consecutive-checks hysteresis for the eventually-
        # consistent invariants: class -> ids suspect at the last check
        self._suspects: Dict[str, frozenset] = {}

    def _persistent(self, cls: str, ids) -> List[str]:
        """Hysteresis filter: of `ids` suspect now, return those that
        were ALSO suspect at the previous check. Async bind transients
        clear within one round; real leaks persist."""
        cur = frozenset(ids)
        prev = self._suspects.get(cls, frozenset())
        self._suspects[cls] = cur
        return sorted(cur & prev)

    # -- entry ----------------------------------------------------------------

    def check(self, sched) -> List[InvariantViolation]:
        """Run every invariant against `sched`. The caller must hold
        sched._mu and be at a round boundary (no popped wave in
        flight)."""
        self.checks += 1
        found: List[Tuple[str, str, dict]] = []
        areas = sched.queue.area_uids()
        pods = [p for p in sched.store.list("pods")
                if p.status.phase not in ("Succeeded", "Failed")]
        assumed = {p.uid for p in sched.cache.assumed_pods()}

        found += self._check_conservation(sched, pods, areas, assumed)
        found += self._check_double_bind(sched, pods)
        found += self._check_capacity(sched, pods)
        found += self._check_snapshot_usage(sched)
        found += self._check_gang_atomic(sched, pods, assumed)
        found += self._check_state_machine(sched)

        out: List[InvariantViolation] = []
        for invariant, detail, evidence in found:
            digest = self._digest(sched, areas, assumed)
            digest.update(evidence)
            v = InvariantViolation(invariant, detail, digest)
            out.append(v)
            self.violations.append(v)
            if self.metrics is not None:
                self.metrics.invariant_violations.labels(
                    invariant=invariant).inc()
        if out and self.strict:
            raise out[0]
        return out

    # -- the invariants -------------------------------------------------------

    def _check_conservation(self, sched, pods, areas, assumed):
        membership: Dict[str, List[str]] = {}
        for area, uids in areas.items():
            for uid in uids:
                membership.setdefault(uid, []).append(area)
        found = []
        lost: List[str] = []
        double: Dict[str, str] = {}
        for p in pods:
            if not sched._responsible(p):
                continue
            placed = bool(p.spec.node_name) or p.uid in assumed
            queued = membership.get(p.uid, [])
            if placed and queued:
                double[p.uid] = f"{p.uid}(placed+{'+'.join(queued)})"
            elif not placed and len(queued) > 1:
                double[p.uid] = f"{p.uid}({'+'.join(queued)})"
            elif not placed and not queued:
                lost.append(p.uid)
        # disconnected-mode spool (control-plane outage survival): a
        # spooled bind intent is the LEGAL assumed-but-unbound state —
        # but only paired with a live assumption (or the bind already
        # landed), and only while the outage lasts. An intent still
        # spooled with the store path CONNECTED at two consecutive
        # checks means the drain/replay machinery is broken: the next
        # housekeeping pass after a reconnect must drain it.
        spool_fn = getattr(sched, "spool_uids", None)
        spool = spool_fn() if callable(spool_fn) else frozenset()
        bound_uids = {p.uid for p in pods if p.spec.node_name}
        unpaired = [uid for uid in spool
                    if uid not in assumed and uid not in bound_uids]
        health = getattr(sched, "storehealth", None)
        stale = self._persistent(
            "spool_stale",
            spool if (health is not None
                      and health.state == STORE_CONNECTED) else ())
        lost = self._persistent("lost", lost)
        double_ids = self._persistent("double", double)
        unpaired = self._persistent("spool_unpaired", unpaired)
        if unpaired:
            found.append((
                "conservation",
                f"{len(unpaired)} spooled bind intent(s) hold no "
                f"assumption and no binding (capacity not reserved), "
                f"e.g. {_cap(unpaired)[:3]}",
                {"spool_unpaired": _cap(unpaired)}))
        if stale:
            found.append((
                "conservation",
                f"{len(stale)} spooled bind intent(s) outlived the "
                f"outage (store CONNECTED across consecutive checks), "
                f"e.g. {_cap(stale)[:3]}",
                {"spool_stale": _cap(stale)}))
        if lost:
            found.append((
                "conservation",
                f"{len(lost)} pod(s) in no queue area and not "
                f"bound/assumed (lost), e.g. {_cap(lost)[:3]}",
                {"lost": _cap(lost)}))
        if double_ids:
            booked = [double[uid] for uid in double_ids]
            found.append((
                "conservation",
                f"{len(booked)} pod(s) in more than one place, "
                f"e.g. {_cap(booked)[:3]}",
                {"double_booked": _cap(booked)}))
        return found

    def _check_double_bind(self, sched, pods):
        cache_node: Dict[str, str] = {}
        dupes = []
        for name, ni in sched.cache.node_infos.items():
            for p in ni.pods:
                prev = cache_node.get(p.uid)
                if prev is not None and prev != name:
                    dupes.append(f"{p.uid}({prev},{name})")
                else:
                    cache_node[p.uid] = name
        disagree = []
        for p in pods:
            if not p.spec.node_name:
                continue
            cached = cache_node.get(p.uid)
            if cached is not None and cached != p.spec.node_name:
                disagree.append(
                    f"{p.uid}(store={p.spec.node_name},cache={cached})")
        found = []
        if dupes:
            found.append((
                "double_bind",
                f"{len(dupes)} pod(s) hold capacity on two nodes, "
                f"e.g. {_cap(dupes)[:3]}",
                {"cache_dupes": _cap(dupes)}))
        if disagree:
            found.append((
                "double_bind",
                f"{len(disagree)} pod(s) cached on a different node "
                f"than API truth, e.g. {_cap(disagree)[:3]}",
                {"cache_divergence": _cap(disagree)}))
        return found

    def _check_capacity(self, sched, pods):
        used: Dict[str, Dict[str, int]] = {}
        count: Dict[str, int] = {}
        for p in pods:
            node = p.spec.node_name
            if not node:
                continue
            count[node] = count.get(node, 0) + 1
            acc = used.setdefault(node, {})
            for r, q in api.get_resource_request(p).items():
                acc[r] = acc.get(r, 0) + q
        over = []
        for node in sched.store.list("nodes"):
            alloc = node.status.allocatable or {}
            acc = used.get(node.name, {})
            for r in ("cpu", "memory"):
                if r in alloc and acc.get(r, 0) > alloc[r]:
                    over.append(f"{node.name}:{r}={acc[r]}>{alloc[r]}")
            if "pods" in alloc and count.get(node.name, 0) > alloc["pods"]:
                over.append(f"{node.name}:pods="
                            f"{count.get(node.name, 0)}>{alloc['pods']}")
        if over:
            return [(
                "capacity",
                f"{len(over)} node resource(s) over allocatable, "
                f"e.g. {_cap(over)[:3]}",
                {"over_allocatable": _cap(over)})]
        return []

    def _check_snapshot_usage(self, sched):
        snap = sched.snapshot
        idxs = sorted(snap.node_index.values())
        if not idxs:
            return []
        mask = snap.ep_valid.astype(bool)
        sums = np.zeros_like(snap.requested)
        counts = np.zeros_like(snap.pod_count)
        if mask.any():
            np.add.at(sums, snap.ep_node[mask], snap.ep_req[mask])
            np.add.at(counts, snap.ep_node[mask], 1)
        found = []
        idx_arr = np.asarray(idxs)
        req = snap.requested[idx_arr]
        if not np.isfinite(req).all():
            bad = [i for i in idxs
                   if not np.isfinite(snap.requested[i]).all()]
            found.append((
                "snapshot_usage",
                f"non-finite values in the snapshot usage plane on "
                f"node row(s) {bad[:3]}",
                {"nonfinite_rows": _cap(bad)}))
            return found  # comparisons below are meaningless on NaN
        # f32 rounding: memory is bytes (above f32's 24-bit exact
        # range), and summation order differs between the aggregate row
        # and the per-pod rows — compare with a relative tolerance
        close = np.isclose(req, sums[idx_arr], rtol=1e-5, atol=1.0)
        if not close.all():
            bad = [idxs[i] for i in np.nonzero(~close.all(axis=1))[0]]
            ex = bad[0]
            found.append((
                "snapshot_usage",
                f"{len(bad)} node row(s) where snapshot requested != "
                f"sum of resident pod rows, e.g. row {ex}: "
                f"{snap.requested[ex].tolist()} vs "
                f"{sums[ex].tolist()}",
                {"diverged_rows": _cap(bad)}))
        pc = snap.pod_count[idx_arr]
        if not (pc == counts[idx_arr]).all():
            bad = [idxs[i]
                   for i in np.nonzero(pc != counts[idx_arr])[0]]
            found.append((
                "snapshot_usage",
                f"{len(bad)} node row(s) where snapshot pod_count != "
                f"resident row count, e.g. row {bad[0]}: "
                f"{int(snap.pod_count[bad[0]])} vs "
                f"{int(counts[bad[0]])}",
                {"count_rows": _cap(bad)}))
        return found

    def _check_gang_atomic(self, sched, pods, assumed):
        members: Dict[str, List] = {}
        for p in pods:
            key = sched.gangs.key(p)
            if key is not None:
                members.setdefault(key, []).append(p)
        partial: Dict[str, str] = {}
        for key, mem in sorted(members.items()):
            placed = sum(1 for p in mem
                         if p.spec.node_name or p.uid in assumed)
            min_member = sched.gangs.min_member(mem[0])
            if 0 < placed < min(min_member, len(mem)):
                partial[key] = f"{key}({placed}/{min_member})"
        split = [partial[k] for k in self._persistent("gang", partial)]
        if split:
            return [(
                "gang_atomic",
                f"{len(split)} gang(s) partially placed "
                f"(0-or-all violated), e.g. {split[:3]}",
                {"partial_gangs": _cap(split)})]
        return []

    def _check_state_machine(self, sched):
        from ..sched.breaker import OPEN, STATE_CODES

        found = []
        br = sched.breaker
        if br.state not in STATE_CODES:
            found.append(("state_machine",
                          f"breaker in unknown state {br.state!r}", {}))
        if br.failures < 0 or br.trips < 0:
            found.append((
                "state_machine",
                f"breaker counters negative (failures={br.failures}, "
                f"trips={br.trips})", {}))
        if br.state == OPEN and br.trips < 1:
            found.append(("state_machine",
                          "breaker OPEN with zero recorded trips", {}))
        mf = sched.meshfaults
        if mf is not None:
            healthy = set(mf.healthy_names())
            quarantined = set(mf.quarantined_names())
            devices = set(mf.devices)
            if healthy & quarantined:
                found.append((
                    "state_machine",
                    f"device(s) both healthy and quarantined: "
                    f"{_cap(healthy & quarantined)[:3]}", {}))
            if (healthy | quarantined) != devices:
                found.append((
                    "state_machine",
                    "mesh healthy+quarantined does not partition the "
                    "device set", {}))
        wd = sched.watchdog
        if wd is not None and wd.outstanding() > wd.abandoned_total:
            found.append((
                "state_machine",
                f"watchdog outstanding ({wd.outstanding()}) exceeds "
                f"abandoned_total ({wd.abandoned_total})", {}))
        sh = getattr(sched, "storehealth", None)
        if sh is not None:
            from ..sched.storehealth import (DISCONNECTED,
                                             STATE_CODES as SH_CODES)
            if sh.state not in SH_CODES:
                found.append((
                    "state_machine",
                    f"store breaker in unknown state {sh.state!r}", {}))
            if sh.failures < 0 or sh.trips < 0:
                found.append((
                    "state_machine",
                    f"store breaker counters negative "
                    f"(failures={sh.failures}, trips={sh.trips})", {}))
            if sh.state == DISCONNECTED and sh.trips < 1:
                found.append((
                    "state_machine",
                    "store breaker DISCONNECTED with zero recorded "
                    "trips", {}))
        return found

    # -- evidence -------------------------------------------------------------

    def _digest(self, sched, areas, assumed) -> dict:
        pods = sched.store.list("pods")
        bound = [p.uid for p in pods if p.spec.node_name]
        d = {
            "check": self.checks,
            "areas": {k: len(v) for k, v in areas.items()},
            "area_uids": {k: _cap(v) for k, v in areas.items() if v},
            "store_pods": len(pods),
            "bound": len(bound),
            "assumed": _cap(assumed),
            "breaker": {"state": sched.breaker.state,
                        "failures": sched.breaker.failures,
                        "trips": sched.breaker.trips},
        }
        sh = getattr(sched, "storehealth", None)
        if sh is not None:
            spool_fn = getattr(sched, "spool_uids", None)
            d["storehealth"] = {"state": sh.state,
                                "failures": sh.failures,
                                "trips": sh.trips,
                                "spool": _cap(spool_fn())
                                if callable(spool_fn) else []}
        if sched.meshfaults is not None:
            d["mesh"] = {
                "devices": len(sched.meshfaults.devices),
                "quarantined": _cap(
                    sched.meshfaults.quarantined_names())}
        if sched.watchdog is not None:
            d["watchdog"] = {
                "abandoned": sched.watchdog.abandoned_total,
                "outstanding": sched.watchdog.outstanding()}
        return d

    def assert_clean(self) -> None:
        """End-of-run gate for strict=False users (benches, e2e): raise
        the first recorded violation if any round ever failed."""
        if self.violations:
            raise self.violations[0]
