"""User-facing CLI — pkg/kubectl analog."""
