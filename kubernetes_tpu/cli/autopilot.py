"""Offline autopilot tooling: train candidates, run promotion CI.

The in-process promotion pipeline (autopilot/controller.py) rides a
live scheduler; this CLI is the batch half of the loop:

    python -m kubernetes_tpu.cli.autopilot train \
        --ledger /var/log/ktpu/rounds.jsonl --out candidates.json

fits the ridge trainer on a round ledger (rotated generation included)
and writes a --weight-profiles-compatible candidates JSON, and

    python -m kubernetes_tpu.cli.autopilot replay \
        --profiles candidates.json [--name density] [--compare-baseline]

runs the storm trace-replay promotion CI over each candidate — the
standalone gate a deployment pipeline can run without touching a live
scheduler. Exit status is the gate verdict (0 = every replay passed),
so this IS the CI job.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional


def _cmd_train(args) -> int:
    from ..autopilot.dataset import load_dataset
    from ..autopilot.trainer import RidgeTrainer
    from ..plugins.registry import default_profile

    ds = load_dataset(args.ledger)
    print(f"# ledger: {len(ds)} scored rounds, {ds.skipped} skipped, "
          f"versions {sorted(set(ds.versions))}", file=sys.stderr)
    trainer = RidgeTrainer(default_profile(None).weights(),
                           ridge_lambda=args.ridge_lambda,
                           step=args.step, min_rounds=args.min_rounds)
    try:
        weights = trainer.fit(ds)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    out = [{"name": args.name, "weights": weights, "role": "candidate"}]
    text = json.dumps(out, indent=2) + "\n"
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
        print(f"# wrote {args.out}", file=sys.stderr)
    else:
        sys.stdout.write(text)
    return 0


def _cmd_replay(args) -> int:
    from ..autopilot.replay import run_replay
    from ..sched.weights import parse_profiles_file

    if args.profiles:
        entries = parse_profiles_file(args.profiles)
    else:
        from ..autopilot import workload_profiles_path

        entries = parse_profiles_file(workload_profiles_path())
    if args.name:
        entries = [e for e in entries if e["name"] == args.name]
        if not entries:
            print(f"error: no profile named {args.name!r}",
                  file=sys.stderr)
            return 1
    kw = dict(nodes=args.nodes, node_cpu=args.node_cpu, wave=args.wave,
              slo_scale=args.slo_scale)
    baseline = None
    if args.compare_baseline:
        baseline = run_replay(None, **kw)
        print(json.dumps(baseline.as_dict()))
    failed = 0
    for e in entries:
        rep = run_replay(dict(e.get("weights") or {}), name=e["name"],
                         **kw)
        verdict = dict(rep.as_dict())
        if baseline is not None:
            regress = rep.objective < baseline.objective - args.tolerance
            verdict["baseline_objective"] = round(baseline.objective, 4)
            if regress:
                verdict["failures"].append(
                    f"objective {rep.objective:.4f} regresses the "
                    f"static baseline {baseline.objective:.4f}")
                verdict["passed"] = False
        print(json.dumps(verdict))
        if not verdict["passed"]:
            failed += 1
    if failed:
        print(f"# {failed}/{len(entries)} candidates FAILED promotion CI",
              file=sys.stderr)
        return 1
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="autopilot",
        description="offline weight training + standalone promotion CI")
    sub = ap.add_subparsers(dest="cmd", required=True)

    tr = sub.add_parser("train", help="fit a candidate weight table "
                                      "from a round ledger")
    tr.add_argument("--ledger", required=True,
                    help="round-ledger JSONL path (the rotated <path>.1 "
                         "generation is read too)")
    tr.add_argument("--out", default=None,
                    help="write candidates JSON here (default stdout)")
    tr.add_argument("--name", default="trained",
                    help="candidate WeightProfile name")
    tr.add_argument("--ridge-lambda", type=float, default=1.0)
    tr.add_argument("--step", type=float, default=0.5,
                    help="max fractional nudge per priority (0.5 = a "
                         "weight moves at most 50%%)")
    tr.add_argument("--min-rounds", type=int, default=4,
                    help="scored-round evidence floor for a fit")

    rp = sub.add_parser("replay", help="storm trace-replay promotion CI "
                                       "over candidate profiles")
    rp.add_argument("--profiles", default=None,
                    help="profiles JSON (default: the checked-in "
                         "per-workload table)")
    rp.add_argument("--name", default=None,
                    help="gate only this profile")
    rp.add_argument("--nodes", type=int, default=4)
    rp.add_argument("--node-cpu", default="8")
    rp.add_argument("--wave", type=int, default=16)
    rp.add_argument("--slo-scale", type=float, default=1.0,
                    help="multiply the per-class p99 gates (headroom "
                         "for slow CI hosts)")
    rp.add_argument("--compare-baseline", action="store_true",
                    help="also replay the static defaults and fail any "
                         "candidate whose objective regresses them")
    rp.add_argument("--tolerance", type=float, default=0.02,
                    help="allowed objective shortfall vs the baseline")

    args = ap.parse_args(argv)
    if args.cmd == "train":
        return _cmd_train(args)
    return _cmd_replay(args)


if __name__ == "__main__":
    sys.exit(main())
