"""hyperkube: every binary in one entry point.

Reference: cmd/hyperkube — one fat binary that dispatches to
kube-apiserver/kube-scheduler/kube-proxy/kubectl/kubelet by its first
argument (or by the name it was invoked as). Here:

    python -m kubernetes_tpu.cli.hyperkube <component> [args...]

with components kubectl, kube-scheduler, kube-proxy, kubeadm,
autopilot (offline weight training + standalone promotion CI), and
csi-mock-driver (the standalone mock CSI driver process).
"""

from __future__ import annotations

import sys
from typing import List, Optional


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)

    def _load(name):
        if name in ("kubectl",):
            from . import kubectl as m
        elif name in ("kube-scheduler", "scheduler"):
            from . import kube_scheduler as m
        elif name in ("kube-proxy", "proxy"):
            from . import kube_proxy as m
        elif name == "kubeadm":
            from . import kubeadm as m
        elif name == "autopilot":
            from . import autopilot as m
        elif name == "csi-mock-driver":
            from ..volume import csi as m
        else:
            return None
        return m

    usage = ("usage: hyperkube <component> [args...]\n"
             "components: kubectl kube-scheduler kube-proxy kubeadm "
             "autopilot csi-mock-driver")
    if argv and argv[0] in ("-h", "--help", "help"):
        print(usage)  # requested help: stdout, success
        return 0
    if not argv:
        print(usage, file=sys.stderr)  # usage error
        return 1
    mod = _load(argv[0])
    if mod is None:
        print(f"error: unknown component {argv[0]!r}", file=sys.stderr)
        return 1
    return mod.main(argv[1:])


if __name__ == "__main__":
    sys.exit(main())
