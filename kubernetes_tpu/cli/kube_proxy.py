"""kube-proxy binary.

Analog of cmd/kube-proxy/app/server.go: connect to the apiserver,
mirror services + endpoints, run the Proxier's event-driven rule-sync
loop, and serve /healthz (last sync stats, healthcheck probes for
externalTrafficPolicy=Local services) + /metrics on the metrics port
(server.go:540 serveHealthz / :552 serveMetrics).

Run: python -m kubernetes_tpu.cli.kube_proxy --server http://... \\
        --hostname-override n1
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..client import RESTClient, RemoteStore
from ..proxy import Proxier


class ProxyHealthServer:
    def __init__(self, proxier: Proxier, host="127.0.0.1", port=0):
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                if self.path == "/healthz":
                    body = json.dumps(outer.proxier.health()).encode()
                    code = 200
                elif self.path.startswith("/healthz/service/"):
                    # cloud-LB probe path for healthCheckNodePorts
                    try:
                        port_q = int(self.path.rsplit("/", 1)[-1])
                    except ValueError:
                        port_q = -1
                    code, payload = outer.proxier.healthcheck.probe(port_q)
                    body = json.dumps(payload).encode()
                elif self.path == "/metrics":
                    h = outer.proxier.health()
                    body = (
                        f"# TYPE kubeproxy_sync_proxy_rules_total counter\n"
                        f"kubeproxy_sync_proxy_rules_total {h['syncs']}\n"
                        f"# TYPE kubeproxy_rules gauge\n"
                        f"kubeproxy_rules {h['rules']}\n").encode()
                    code = 200
                else:
                    code, body = 404, b""
                ctype = ("text/plain; version=0.0.4"  # Prometheus text
                         if self.path == "/metrics" else "application/json")
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.proxier = proxier
        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.httpd.daemon_threads = True
        self.port = self.httpd.server_address[1]

    def start(self):
        threading.Thread(target=self.httpd.serve_forever, daemon=True,
                         name="kube-proxy-health").start()
        return self

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="kube-proxy")
    ap.add_argument("--server", required=True)
    ap.add_argument("--token", default=None)
    ap.add_argument("--ca-cert-data", default=None,
                    help="cluster CA bundle PEM (or @file) for https "
                         "servers")
    ap.add_argument("--client-cert-data", default=None,
                    help="x509 client cert PEM (or @file) for mTLS")
    ap.add_argument("--client-key-data", default=None,
                    help="x509 client key PEM (or @file) for mTLS")
    ap.add_argument("--hostname-override", default="")
    ap.add_argument("--healthz-port", type=int, default=0)
    ap.add_argument("--min-sync-period", type=float, default=0.0)
    ap.add_argument("--sync-loop-period", type=float, default=1.0)
    ap.add_argument("--one-shot", action="store_true",
                    help="sync once and exit (tests/CI)")
    args = ap.parse_args(argv)

    from ..client.rest import pem_arg

    try:
        client = RESTClient(args.server, token=args.token,
                            ca_cert_pem=pem_arg(args.ca_cert_data),
                            client_cert_pem=pem_arg(args.client_cert_data),
                            client_key_pem=pem_arg(args.client_key_data))
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    store = RemoteStore(client)
    store.mirror("services")
    store.mirror("endpoints")
    # the reflector's initial LIST is async; syncing against empty
    # mirrors would install zero rules (and --one-shot would exit 0
    # having programmed nothing)
    if not store.wait_for_sync():
        print("kube-proxy: apiserver mirrors failed to sync",
              file=sys.stderr)
        return 1
    proxier = Proxier(store, node_name=args.hostname_override,
                      min_sync_period=args.min_sync_period)
    health = ProxyHealthServer(proxier, port=args.healthz_port).start()
    print(f"kube-proxy: healthz on :{health.port}", file=sys.stderr)
    if args.one_shot:
        proxier.sync_proxy_rules()
        health.stop()
        return 0
    proxier.run(period=args.sync_loop_period)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    stop.wait()
    proxier.stop()
    health.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
