"""kube-scheduler binary.

Analog of cmd/kube-scheduler/app/server.go: flags + component config ->
build the scheduler against an apiserver, optionally behind leader
election, with healthz + /metrics served on the insecure port
(server.go:225-236) and the scheduling loop as the leader's run function
(server.go:188-203).

Run: python -m kubernetes_tpu.cli.kube_scheduler --server http://...
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ..client import LeaderElector, RESTClient, RemoteStore
from ..plugins.registry import default_profile, default_registry
from ..sched.config import KubeSchedulerConfiguration
from ..sched.scheduler import Scheduler
from ..utils.feature_gates import FeatureGates
from ..utils.metrics import Metrics


class HealthServer:
    """healthz + /metrics on the insecure port (server.go:225)."""

    def __init__(self, scheduler_ref, host="127.0.0.1", port=0):
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                if self.path == "/healthz":
                    body = b"ok"
                    ctype = "text/plain"
                elif self.path == "/metrics":
                    body = outer.metrics_text().encode()
                    ctype = "text/plain"
                elif self.path.startswith("/debug/profile"):
                    # pprof debug=1 analog (server.go:229 EnableProfiling)
                    from ..utils import profiling

                    prof = profiling.active()
                    body = (prof.report() if prof is not None
                            else "profiling disabled (run with "
                                 "--profiling)\n").encode()
                    ctype = "text/plain"
                elif self.path.startswith("/debug/trace"):
                    # flight recorder export: Chrome trace-event JSON
                    # (Perfetto-loadable) by default; ?format=text for
                    # the plain timeline, ?format=ledger for the round
                    # ledger records the JSONL file would hold
                    from ..utils import tracing

                    rec = tracing.active()
                    if rec is None:
                        body = (b"tracing disabled (run with --tracing)\n")
                        ctype = "text/plain"
                    elif "format=text" in self.path:
                        body = rec.text_timeline().encode()
                        ctype = "text/plain"
                    elif "format=ledger" in self.path:
                        body = ("\n".join(json.dumps(r)
                                          for r in rec.ledger_rows())
                                + "\n").encode()
                        ctype = "application/json"
                    else:
                        body = json.dumps(rec.chrome_trace()).encode()
                        ctype = "application/json"
                elif self.path.startswith("/debug/shadow"):
                    # shadow-scoring observatory: counterfactual
                    # divergence per candidate WeightProfile.
                    # ?profile=<name> for one profile's report
                    # (&format=text for flip explanations: "p1: prod
                    # chose node-42, candidate flips to node-7 on
                    # LeastRequested 8→3"); without a profile, an index
                    # of loaded profiles + the active weights_version.
                    from urllib.parse import parse_qs, urlparse

                    sched = outer.scheduler_ref()
                    book = getattr(sched, "weightbook", None)
                    if book is None:
                        body = b"scheduler not running\n"
                        ctype = "text/plain"
                    else:
                        q = parse_qs(urlparse(self.path).query)
                        profile = (q.get("profile") or [None])[0]
                        fmt = (q.get("format") or [""])[0]
                        if profile:
                            if fmt == "text":
                                text = book.report_text(profile)
                            else:
                                entry = book.report(profile)
                                text = (json.dumps(entry)
                                        if entry is not None else None)
                            if text is None:
                                body = (f"no shadow profile "
                                        f"{profile}\n").encode()
                                self.send_response(404)
                                self.send_header("Content-Type",
                                                 "text/plain")
                                self.send_header("Content-Length",
                                                 str(len(body)))
                                self.end_headers()
                                self.wfile.write(body)
                                return
                            body = text.encode()
                            ctype = ("text/plain" if fmt == "text"
                                     else "application/json")
                        else:
                            body = json.dumps(book.index()).encode()
                            ctype = "application/json"
                elif self.path.startswith("/debug/store"):
                    # control-plane outage observatory: store-path
                    # breaker state, bind-spool depth/watermark,
                    # journal stats and per-op store error counters
                    # (sched/scheduler.py store_debug())
                    sched = outer.scheduler_ref()
                    dbg = getattr(sched, "store_debug", None)
                    if dbg is None:
                        body = b"scheduler not running\n"
                        self.send_response(404)
                        self.send_header("Content-Type", "text/plain")
                        self.send_header("Content-Length",
                                         str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                        return
                    body = json.dumps(dbg()).encode()
                    ctype = "application/json"
                elif self.path.startswith("/debug/autopilot"):
                    # autopilot promotion pipeline: current phase,
                    # candidate under evaluation, gate reports and the
                    # bounded transition history
                    # (autopilot/controller.py status())
                    sched = outer.scheduler_ref()
                    ap = getattr(sched, "autopilot", None)
                    if ap is None:
                        body = b"no autopilot controller attached\n"
                        self.send_response(404)
                        self.send_header("Content-Type", "text/plain")
                        self.send_header("Content-Length",
                                         str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                        return
                    body = json.dumps(ap.status()).encode()
                    ctype = "application/json"
                elif self.path.startswith("/debug/score"):
                    # decision observatory: per-pod score decomposition
                    # ("why did node-42 win"). ?uid=<pod uid> for one
                    # pod (&format=text for the one-line explanation);
                    # without a uid, an index of recent decisions.
                    from urllib.parse import parse_qs, urlparse

                    from ..utils import tracing

                    rec = tracing.active()
                    if rec is None:
                        body = (b"tracing disabled (run with --tracing)\n")
                        ctype = "text/plain"
                    else:
                        q = parse_qs(urlparse(self.path).query)
                        uid = (q.get("uid") or [None])[0]
                        fmt = (q.get("format") or [""])[0]
                        if uid:
                            entry = rec.decision(uid)
                            if entry is None:
                                body = (f"no decision recorded for uid "
                                        f"{uid}\n").encode()
                                self.send_response(404)
                                self.send_header("Content-Type",
                                                 "text/plain")
                                self.send_header("Content-Length",
                                                 str(len(body)))
                                self.end_headers()
                                self.wfile.write(body)
                                return
                            if fmt == "text":
                                body = (tracing.format_decision(uid, entry)
                                        + "\n").encode()
                                ctype = "text/plain"
                            else:
                                body = json.dumps(
                                    {"uid": uid, **entry}).encode()
                                ctype = "application/json"
                        else:
                            idx = [{"uid": u, "pod": e.get("pod"),
                                    "node": e.get("node"),
                                    "round": e.get("round"),
                                    "total": e.get("total"),
                                    "margin": e.get("margin")}
                                   for u, e in rec.recent_decisions()]
                            body = json.dumps(idx).encode()
                            ctype = "application/json"
                else:
                    self.send_response(404)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.scheduler_ref = scheduler_ref
        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.httpd.daemon_threads = True
        self.port = self.httpd.server_address[1]
        threading.Thread(target=self.httpd.serve_forever, daemon=True,
                         name="sched-healthz").start()

    def metrics_text(self) -> str:
        sched = self.scheduler_ref()
        if sched is None:
            return ""
        lines = []
        typed = set()
        for series in sched.metrics.all_series().values():
            if hasattr(series, "counts"):  # histogram
                # full Prometheus histogram exposition: CUMULATIVE
                # name_bucket{le="..."} lines ending at +Inf == _count —
                # without the buckets, dashboards cannot compute
                # histogram_quantile() and the old output failed strict
                # text-format parsers
                lines.append(f"# TYPE {series.name} histogram")
                cum = 0
                for bound, c in zip(series.buckets, series.counts):
                    cum += c
                    lines.append(
                        f'{series.name}_bucket{{le="{bound:g}"}} {cum}')
                cum += series.counts[-1]
                lines.append(f'{series.name}_bucket{{le="+Inf"}} {cum}')
                lines.append(f"{series.name}_sum {series.sum}")
                lines.append(f"{series.name}_count {series.total}")
            else:
                # labelled children share one family: the TYPE line must
                # name the bare family (label syntax there fails the
                # Prometheus text parser, discarding the whole scrape)
                family = series.name.partition("{")[0]
                if family not in typed:
                    typed.add(family)
                    kind = getattr(series, "kind", "counter")
                    lines.append(f"# TYPE {family} {kind}")
                lines.append(f"{series.name} {series.value}")
        return "\n".join(lines) + "\n"

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def build_scheduler(cfg: KubeSchedulerConfiguration, store,
                    metrics: Optional[Metrics] = None) -> Scheduler:
    if cfg.policy_config_file:
        profile = default_registry.profile_from_policy(
            open(cfg.policy_config_file).read(), store=store)
    else:
        profile = default_profile(store)
    profile.scheduler_name = cfg.scheduler_name
    profile.disable_preemption = cfg.disable_preemption
    profile.hard_pod_affinity_symmetric_weight = \
        cfg.hard_pod_affinity_symmetric_weight
    features = FeatureGates()
    for k, v in (cfg.feature_gates or {}).items():
        features.set(k, bool(v))
    mesh = None
    if cfg.mesh_devices:
        from ..parallel.mesh import mesh_for_devices

        # clamps counts above the visible device total (with a warning)
        # and resolves <= 1 device to no mesh at all — same semantics as
        # bench.py --mesh
        mesh = mesh_for_devices(cfg.mesh_devices)
    sched = Scheduler(store, profile=profile, wave_size=cfg.wave_size,
                      features=features, mesh=mesh,
                      mesh_min_devices=cfg.mesh_min_devices,
                      scrub_interval=cfg.scrub_interval or None,
                      compact_interval=cfg.compact_interval or None,
                      hbm_budget_bytes=cfg.hbm_budget_bytes,
                      breaker_threshold=cfg.breaker_threshold,
                      breaker_cooldown=cfg.breaker_cooldown,
                      metrics=metrics,
                      bind_max_attempts=cfg.bind_max_attempts,
                      racecheck=cfg.racecheck,
                      shed_watermark=cfg.shed_watermark,
                      shed_priority_threshold=cfg.shed_priority_threshold,
                      shed_age_s=cfg.shed_age_s,
                      wave_deadline_s=cfg.wave_deadline_s,
                      shadow_exact_interval=cfg.shadow_exact_interval,
                      invariants=cfg.invariants,
                      store_breaker_threshold=cfg.store_breaker_threshold,
                      store_breaker_cooldown=cfg.store_breaker_cooldown,
                      bind_journal_path=cfg.bind_journal_path or None,
                      bind_journal_max_bytes=cfg.bind_journal_max_bytes,
                      spool_watermark=cfg.spool_watermark)
    if cfg.weight_profiles_path:
        # file-preloaded profiles feed the weight book directly — the
        # store-watched `weightprofiles` kind is the dynamic path, but
        # a remote apiserver may not carry it
        sched.weightbook.load_file(cfg.weight_profiles_path)
    return sched


def run(cfg: KubeSchedulerConfiguration, server_url: str,
        token: Optional[str] = None, stop: Optional[threading.Event] = None,
        once: bool = False, ca_cert_pem: Optional[str] = None,
        client_cert_pem: Optional[str] = None,
        client_key_pem: Optional[str] = None,
        profiling_enabled: bool = False,
        contention_profiling: bool = False,
        tracing_enabled: bool = False) -> int:
    stop = stop or threading.Event()
    prof_on = profiling_enabled or contention_profiling
    if prof_on:
        from ..utils import profiling

        profiling.enable()
    # a ledger path implies tracing (the recorder is what writes it);
    # only tear down a recorder THIS call created — an embedding caller
    # may have enabled tracing for its own purposes
    trace_on = tracing_enabled or cfg.tracing or bool(cfg.round_ledger_path)
    trace_owned = False
    if trace_on:
        from ..utils import tracing

        trace_owned = tracing.active() is None
        tracing.enable(max_rounds=cfg.trace_rounds,
                       ledger_path=cfg.round_ledger_path or None,
                       ledger_max_bytes=(cfg.round_ledger_max_bytes
                                         if cfg.round_ledger_max_bytes >= 0
                                         else None))
    try:
        return _run_inner(cfg, server_url, token, stop, once, ca_cert_pem,
                          client_cert_pem, client_key_pem,
                          contention_profiling)
    finally:
        # process-global instrumentation: never leak, even on error
        if prof_on:
            from ..utils import profiling

            profiling.disable()
        if trace_owned:
            from ..utils import tracing

            tracing.disable()


def _run_inner(cfg, server_url, token, stop, once, ca_cert_pem,
               client_cert_pem, client_key_pem, contention_profiling):
    client = RESTClient(server_url, token=token, ca_cert_pem=ca_cert_pem,
                        client_cert_pem=client_cert_pem,
                        client_key_pem=client_key_pem)
    # ONE metrics registry shared by the store's reflectors and the
    # scheduler: reflector_relists/watch_stale/stage=reflector errors
    # are served from the same /metrics endpoint as scheduling series
    metrics = Metrics()
    store = RemoteStore(client, metrics=metrics)
    for kind in ("pods", "nodes", "services", "replicationcontrollers",
                 "replicasets", "statefulsets", "poddisruptionbudgets",
                 "persistentvolumes", "persistentvolumeclaims"):
        store.mirror(kind)
    store.wait_for_sync()
    sched_holder = [None]
    health = HealthServer(lambda: sched_holder[0], port=cfg.healthz_port) \
        if cfg.healthz_port >= 0 else None
    # SIGUSR2 -> audit the HBM snapshot against the host cache
    # (factory/cache_comparer.go's trigger). Installed HERE, before any
    # leader election: under --leader-elect the scheduling loop runs in
    # a worker thread where signal.signal() is illegal — installing from
    # there would silently leave SIGUSR2 at its default disposition
    # (terminate) and an operator's audit kill -USR2 would kill the
    # leader. The handler routes through the holder so it survives the
    # scheduler being built later (or never, on a standby).
    if hasattr(signal, "SIGUSR2") and \
            threading.current_thread() is threading.main_thread():
        signal.signal(
            signal.SIGUSR2,
            lambda *_: (sched_holder[0] is not None
                        and sched_holder[0].scrubber.request()))

    def scheduling_loop(elector: Optional[LeaderElector] = None):
        sched = build_scheduler(cfg, store, metrics=metrics)
        if contention_profiling:
            from ..utils import profiling

            profiling.instrument_lock(sched, "_mu", "scheduler._mu")
        sched_holder[0] = sched
        while not stop.is_set():
            if elector is not None and not elector.is_leader:
                # demoted: drain binds once, then idle warm (informers
                # keep the cache current for the recovery pass)
                if not sched.dormant:
                    sched.enter_dormant()
                stop.wait(0.05)
                continue
            if sched.dormant:
                # re-elected: reconcile assumed pods against API truth,
                # rebuild the HBM snapshot, resume waves
                sched.recover_leadership()
            placed = sched.run_once(timeout=0.2)
            if once and sched.queue.active_count() == 0:
                stop.set()
            if placed == 0 and not once:
                stop.wait(0.02)
        sched.close()  # settle in-flight binds + release binder threads

    if cfg.leader_election.leader_elect:
        le = cfg.leader_election
        loop_started = threading.Event()

        def _on_started_leading():
            # the loop thread is started ONCE and then survives
            # leadership churn — warm restart, not process restart. The
            # loop keys dormancy off elector.is_leader itself (caught
            # within one iteration): enter_dormant's bind drain can
            # block for seconds, and the elector thread must get back
            # to candidate mode immediately, not run it
            if not loop_started.is_set():
                loop_started.set()
                threading.Thread(target=scheduling_loop, args=(elector,),
                                 daemon=True).start()

        elector = LeaderElector(
            store, identity=f"{cfg.scheduler_name}-{id(store):x}",
            lock_name=le.lock_name, lease_duration=le.lease_duration,
            renew_deadline=le.renew_deadline, retry_period=le.retry_period,
            on_started_leading=_on_started_leading)
        elector.start()
        stop.wait()
        elector.stop()
    else:
        scheduling_loop()
    if health is not None:
        health.stop()
    store.stop()
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="kube-scheduler")
    ap.add_argument("--server", required=True, help="apiserver URL")
    ap.add_argument("--token", default=None)
    ap.add_argument("--ca-cert-data", default=None,
                    help="cluster CA bundle PEM (or @file) for https "
                         "servers")
    ap.add_argument("--client-cert-data", default=None,
                    help="x509 client cert PEM (or @file) for mTLS")
    ap.add_argument("--client-key-data", default=None,
                    help="x509 client key PEM (or @file) for mTLS")
    ap.add_argument("--config", default=None,
                    help="KubeSchedulerConfiguration file (YAML/JSON)")
    ap.add_argument("--policy-config-file", default=None)
    ap.add_argument("--scheduler-name", default=None)
    ap.add_argument("--leader-elect", action="store_true")
    ap.add_argument("--disable-preemption", action="store_true")
    ap.add_argument("--wave-size", type=int, default=None)
    ap.add_argument("--mesh-devices", type=int, default=None,
                    help="shard the scheduling plane's node axis across "
                         "this many devices (0 = single device, -1 = all "
                         "visible devices); placements stay bit-identical")
    ap.add_argument("--mesh-min-devices", type=int, default=None,
                    help="degradation-ladder floor: a device loss reforms "
                         "the mesh down (8->4->2->1) while at least this "
                         "many devices survive; below it the whole-path "
                         "breaker takes over (host twin)")
    ap.add_argument("--scrub-interval", type=float, default=None,
                    help="seconds between periodic snapshot scrubs "
                         "(0 disables the cadence; SIGUSR2 always works)")
    ap.add_argument("--compact-interval", type=float, default=None,
                    help="seconds between housekeeping snapshot "
                         "compaction sweeps — shrink over-grown row "
                         "buckets and rebuild the shared vocabularies "
                         "from live objects (0 disables the cadence; "
                         "OOM recovery and the HBM governor can still "
                         "force one)")
    ap.add_argument("--hbm-budget-bytes", type=int, default=None,
                    help="projected device-memory budget in bytes: a "
                         "snapshot grow that would exceed it compacts "
                         "first instead of letting the backend throw "
                         "RESOURCE_EXHAUSTED (0 = unbudgeted)")
    ap.add_argument("--healthz-port", type=int, default=None,
                    help="-1 disables; 0 picks a free port")
    ap.add_argument("--feature-gates", default="",
                    help="comma-separated key=bool pairs")
    ap.add_argument("--profiling", action="store_true",
                    help="step profiling served at /debug/profile "
                         "(EnableProfiling analog)")
    ap.add_argument("--contention-profiling", action="store_true",
                    help="also record lock wait times "
                         "(EnableContentionProfiling analog)")
    ap.add_argument("--tracing", action="store_true",
                    help="flight recorder: per-pod span tracing served at "
                         "/debug/trace (Chrome trace-event JSON; "
                         "?format=text for a timeline)")
    ap.add_argument("--trace-rounds", type=int, default=None,
                    help="rounds retained in the flight-recorder ring "
                         "buffer (default 64)")
    ap.add_argument("--round-ledger", default=None,
                    help="append one structured JSONL record per "
                         "scheduling round to this file (requires "
                         "--tracing)")
    ap.add_argument("--round-ledger-max-bytes", type=int, default=None,
                    help="rotate the round ledger to <path>.1 before it "
                         "exceeds this many bytes (one generation kept; "
                         "0 disables rotation, default 64MiB)")
    ap.add_argument("--weight-profiles", default=None,
                    help="JSON file of WeightProfiles ([{name, weights, "
                         "role}]) preloaded into the shadow-scoring "
                         "observatory; role=live hot-swaps the "
                         "production weight vector, candidates are "
                         "shadow-scored on traced rounds "
                         "(/debug/shadow; needs --tracing)")
    ap.add_argument("--shadow-exact-interval", type=int, default=None,
                    help="exact shadow mode: replay the first wave of "
                         "every Nth traced round through the numpy twin "
                         "under each candidate profile (0 disables; the "
                         "default shadow pass is a top-K lower bound)")
    ap.add_argument("--invariants", action="store_true",
                    help="continuously-checked cluster invariants: run "
                         "the chaos invariant checker after every "
                         "scheduling round (conservation, double-bind, "
                         "capacity, snapshot-vs-residents, gang "
                         "atomicity, state-machine sanity); a violation "
                         "raises with a full state digest")
    ap.add_argument("--racecheck", action="store_true",
                    help="instrument the scheduler/queue locks with the "
                         "lock-order watcher (go test -race analog; "
                         "edge names match the ktpu-lint static lock "
                         "graph)")
    ap.add_argument("--shed-watermark", type=int, default=None,
                    help="overload control: pending-depth high watermark "
                         "above which sub-threshold-priority pods park in "
                         "the shed area (0 disables shedding)")
    ap.add_argument("--shed-priority-threshold", type=int, default=None,
                    help="pods below this priority are sheddable past the "
                         "watermark (default 1000: system/high classes "
                         "are never shed)")
    ap.add_argument("--shed-age", type=float, default=None,
                    help="seconds a shed pod waits before aging back into "
                         "the active heap (starvation proof; default 30)")
    ap.add_argument("--wave-deadline", type=float, default=None,
                    help="device-dispatch watchdog budget in seconds: an "
                         "exceeded dispatch is abandoned, trips the "
                         "breaker, and the round completes via the host "
                         "twin (0 disables)")
    ap.add_argument("--bind-journal", default=None,
                    help="durable bind-intent journal path: binds "
                         "spooled during a control-plane outage are "
                         "journaled (fsync'd JSONL) and replayed on "
                         "restart before the first wave (empty "
                         "disables durability)")
    ap.add_argument("--spool-watermark", type=int, default=None,
                    help="disconnected-mode spool depth above which new "
                         "sheddable admissions are held in the shed "
                         "area until the store heals (0 = never hold)")
    ap.add_argument("--store-breaker-threshold", type=int, default=None,
                    help="consecutive store failures (bind/GET/LIST) "
                         "before the store-path breaker declares "
                         "DISCONNECTED (default 3)")
    ap.add_argument("--store-breaker-cooldown", type=float, default=None,
                    help="base seconds between jittered half-open store "
                         "probes while DISCONNECTED (default 30)")
    ap.add_argument("--once", action="store_true",
                    help="exit when the queue drains (batch mode)")
    args = ap.parse_args(argv)

    cfg = (KubeSchedulerConfiguration.load(args.config) if args.config
           else KubeSchedulerConfiguration())
    if args.scheduler_name:
        cfg.scheduler_name = args.scheduler_name
    if args.policy_config_file:
        cfg.policy_config_file = args.policy_config_file
    if args.leader_elect:
        cfg.leader_election.leader_elect = True
    if args.disable_preemption:
        cfg.disable_preemption = True
    if args.wave_size is not None:
        cfg.wave_size = args.wave_size
    if args.mesh_devices is not None:
        cfg.mesh_devices = args.mesh_devices
    if args.mesh_min_devices is not None:
        cfg.mesh_min_devices = args.mesh_min_devices
    if args.scrub_interval is not None:
        cfg.scrub_interval = args.scrub_interval
    if args.compact_interval is not None:
        cfg.compact_interval = args.compact_interval
    if args.hbm_budget_bytes is not None:
        cfg.hbm_budget_bytes = args.hbm_budget_bytes
    if args.healthz_port is not None:
        cfg.healthz_port = args.healthz_port
    if args.tracing:
        cfg.tracing = True
    if args.trace_rounds is not None:
        cfg.trace_rounds = args.trace_rounds
    if args.round_ledger is not None:
        cfg.round_ledger_path = args.round_ledger
    if args.round_ledger_max_bytes is not None:
        cfg.round_ledger_max_bytes = args.round_ledger_max_bytes
    if args.weight_profiles is not None:
        cfg.weight_profiles_path = args.weight_profiles
    if args.shadow_exact_interval is not None:
        cfg.shadow_exact_interval = args.shadow_exact_interval
    if args.invariants:
        cfg.invariants = True
    if args.racecheck:
        cfg.racecheck = True
    if args.shed_watermark is not None:
        cfg.shed_watermark = args.shed_watermark
    if args.shed_priority_threshold is not None:
        cfg.shed_priority_threshold = args.shed_priority_threshold
    if args.shed_age is not None:
        cfg.shed_age_s = args.shed_age
    if args.wave_deadline is not None:
        cfg.wave_deadline_s = args.wave_deadline
    if args.bind_journal is not None:
        cfg.bind_journal_path = args.bind_journal
    if args.spool_watermark is not None:
        cfg.spool_watermark = args.spool_watermark
    if args.store_breaker_threshold is not None:
        cfg.store_breaker_threshold = args.store_breaker_threshold
    if args.store_breaker_cooldown is not None:
        cfg.store_breaker_cooldown = args.store_breaker_cooldown
    for kv in filter(None, args.feature_gates.split(",")):
        k, _, v = kv.partition("=")
        cfg.feature_gates[k] = v.lower() in ("true", "1", "")

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    from ..client.rest import pem_arg

    try:
        return run(cfg, args.server, token=args.token, stop=stop,
                   once=args.once, ca_cert_pem=pem_arg(args.ca_cert_data),
                   client_cert_pem=pem_arg(args.client_cert_data),
                   client_key_pem=pem_arg(args.client_key_data),
                   profiling_enabled=args.profiling,
                   contention_profiling=args.contention_profiling,
                   tracing_enabled=args.tracing)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
