"""kubeadm-lite: one-command cluster bootstrap.

Reference: cmd/kubeadm/app/cmd/init.go (phases: preflight -> control
plane -> wait -> post-init) and join.go. `init` stands up the full
control plane in one process — apiserver (durable native store with
--data-dir, else in-memory), controller manager, scheduler, and
optionally N hollow nodes — then prints how to connect kubectl.
`join` registers a hollow kubelet against a running server.

Run as: python -m kubernetes_tpu.cli.kubeadm init [--data-dir D]
        [--hollow-nodes N] [--port P]
"""

from __future__ import annotations

import argparse
import os
import sys
import threading
import time
from typing import List, Optional

from ..api import types as api
from ..controllers.manager import ControllerManager
from ..runtime.store import ObjectStore
from ..sched.scheduler import Scheduler
from ..server.admission import AdmissionChain
from ..server.apiserver import APIServer


class Cluster:
    """A running control plane (the object form of `kubeadm init`)."""

    def __init__(self, data_dir: Optional[str] = None, port: int = 0,
                 hollow_nodes: int = 0, reconcile_endpoints: bool = True,
                 secure: bool = False, cluster_autoscaler: bool = False,
                 node_eviction_rate: Optional[float] = None,
                 secondary_node_eviction_rate: Optional[float] = None,
                 large_cluster_size_threshold: Optional[int] = None,
                 unhealthy_zone_threshold: Optional[float] = None):
        if data_dir:
            from ..runtime.nativestore import NativeObjectStore

            self.store = NativeObjectStore(path=data_dir)
        else:
            self.store = ObjectStore()
        authenticator = authorizer = None
        self.admin_token = self.bootstrap_token = None
        self.ca = None
        if secure:
            # init.go's certs + bootstrap-token + RBAC phases: cluster
            # CA, an HTTPS serving cert from it, admin + join
            # credentials, RBAC evaluated from served API objects
            # (runtime-reconfigurable). x509 identity comes from the TLS
            # handshake's verified peer chain.
            import secrets as _secrets

            from ..server import pki
            from ..server.auth import (AuthenticatorChain, RBACAuthorizer,
                                       UserInfo, cluster_admin_bindings)

            from ..controllers.bootstrap import (make_token_secret,
                                                 new_bootstrap_token)
            from ..runtime.store import Conflict

            self.ca = ca = pki.ensure_cluster_ca(self.store)
            self.admin_token = f"admin-{_secrets.token_hex(8)}"
            # bootstrap token lives as a kube-system Secret (id.secret
            # wire form): expiry/deletion revokes it live, and the
            # BootstrapSigner keys cluster-info signatures off it
            tid, tsec, self.bootstrap_token = new_bootstrap_token()
            tok_secret = make_token_secret(tid, tsec, ttl_seconds=86400.0)
            try:
                self.store.create("secrets", tok_secret)
            except Conflict:
                # re-init over a durable store that already holds a
                # token with this id: REPLACE it — keeping the old
                # Secret would make the token this init prints dead.
                # (update is last-writer-wins here; if the old Secret
                # vanished in between, fall back to create)
                try:
                    self.store.update("secrets", tok_secret)
                except KeyError:
                    self.store.create("secrets", tok_secret)
            authenticator = AuthenticatorChain(
                tokens={
                    self.admin_token: UserInfo(
                        "kubernetes-admin", ("system:masters",
                                             "system:authenticated")),
                },
                store=self.store, ca=ca)
            authorizer = RBACAuthorizer(
                bindings=cluster_admin_bindings(["system:masters"]),
                store=self.store)
            self._seed_rbac()
            self._publish_cluster_info()
        self.apiserver = APIServer(
            self.store, admission=AdmissionChain.default(), port=port,
            authenticator=authenticator, authorizer=authorizer,
            reconcile_endpoints=reconcile_endpoints, tls=self.ca)
        self.manager = ControllerManager(
            self.store,
            node_eviction_rate=node_eviction_rate,
            secondary_node_eviction_rate=secondary_node_eviction_rate,
            large_cluster_size_threshold=large_cluster_size_threshold,
            unhealthy_zone_threshold=unhealthy_zone_threshold)
        # the scheduler runs as an API CLIENT over a loopback watch
        # mirror — the reference's deployment shape (kube-scheduler
        # connects via client-go, cmd/kube-scheduler). Running it on the
        # raw shared store would invert Scheduler._mu against the store
        # lock: an apiserver handler thread mutating the store dispatches
        # informer events UNDER the store lock into scheduler handlers
        # that take _mu, while a scheduling wave holds _mu and writes the
        # store (observed deadlock under kubelet heartbeat load).
        from ..client.reflector import RemoteStore
        from ..client.rest import RESTClient

        self._sched_client = RESTClient(
            self.apiserver.url, token=self.admin_token,
            ca_cert_pem=self.ca.ca_cert_pem if self.ca else None)
        self._sched_store = RemoteStore(self._sched_client)
        self.scheduler = Scheduler(self._sched_store)
        self.cloud = None
        if cluster_autoscaler:
            # elastic NodeGroups behind the fake cloud seam: the
            # autoscaler controller watches the scheduler's
            # unschedulable map and resizes these groups through
            # on-device what-ifs (controllers/clusterautoscaler.py);
            # booted instances register as hollow-style ready nodes
            from ..cloud.provider import FakeCloud, node_from_template
            from ..controllers.clusterautoscaler import ClusterAutoscaler

            cloud = FakeCloud()
            cloud.joiner = lambda g, name: self.store.create(
                "nodes", node_from_template(g, name))
            for gname, cpu, mem, price in (
                    ("tpu-small", "16", "64Gi", 1.0),
                    ("tpu-large", "32", "128Gi", 2.3)):
                tmpl = api.Node(
                    metadata=api.ObjectMeta(name=f"template-{gname}"),
                    status=api.NodeStatus(allocatable=api.resource_list(
                        cpu=cpu, memory=mem, pods=110,
                        ephemeral_storage="200Gi")))
                cloud.add_node_group(gname, tmpl, min_size=0, max_size=32,
                                     price=price)
            self.cloud = cloud
            ca = ClusterAutoscaler(self.store, cloud, self.scheduler)
            self.manager.controllers[ca.name] = ca
        self.hollow = None
        self._hollow_nodes = hollow_nodes
        self._stop = threading.Event()
        self._sched_thread: Optional[threading.Thread] = None

    def write_admin_kubeconfig(self, path: str) -> None:
        """kubeadm's admin.conf (cmd/kubeadm/app/phases/kubeconfig):
        cluster CA bundle + the admin credential, ready for
        `kubectl --kubeconfig path` (or ~/.kube/config)."""
        from . import kubeconfig as kc

        kc.save(path, kc.new(
            cluster="kubernetes", server=self.apiserver.url,
            ca_pem=self.ca.ca_cert_pem if self.ca else None,
            user="kubernetes-admin", token=self.admin_token))

    def _seed_rbac(self):
        """Bootstrap RBAC objects (cmd/kubeadm/app/phases/bootstraptoken/
        clusterinfo + the reference's bootstrap policy): joiners may
        create and read CSRs, nothing else; node identity then comes
        from the signed cert + the node authorizer."""
        from ..runtime.store import Conflict

        # each create gets its OWN conflict guard: a crash between the
        # two must not leave the seed half-applied forever on re-init
        try:
            self.store.create("clusterroles", api.ClusterRole(
                metadata=api.ObjectMeta(name="system:node-bootstrapper"),
                rules=[api.RBACPolicyRule(
                    # create + named get only: a joiner polls its OWN
                    # CSR; list/watch would let any bootstrap-token
                    # holder enumerate other nodes' signed certs
                    verbs=["create", "get"],
                    api_groups=["certificates.k8s.io"],
                    resources=["certificatesigningrequests"])]))
        except Conflict:
            pass
        try:
            self.store.create("clusterrolebindings", api.ClusterRoleBinding(
                metadata=api.ObjectMeta(
                    name="kubeadm:kubelet-bootstrap"),
                subjects=[api.RBACSubject(kind="Group",
                                          name="system:bootstrappers")],
                role_ref=api.RoleRef(kind="ClusterRole",
                                     name="system:node-bootstrapper")))
        except Conflict:
            pass

    def _signed_cluster_info(self) -> api.ConfigMap:
        """cluster-info pre-signed for every live bootstrap token, so a
        join racing the controller's first pass still verifies; the
        BootstrapSigner controller maintains the signatures thereafter
        (token rotation/expiry)."""
        from ..controllers.bootstrap import compute_signatures

        data = {"ca.crt": self.ca.ca_cert_pem}
        data.update(compute_signatures(self.store, self.ca.ca_cert_pem))
        return api.ConfigMap(
            metadata=api.ObjectMeta(name="cluster-info",
                                    namespace="kube-public"),
            data=data)

    def _publish_cluster_info(self):
        """The cluster-info ConfigMap in kube-public, readable
        anonymously — how a joiner learns the CA bundle before it can
        authenticate (reference: clusterinfo phase). The BootstrapSigner
        machinery signs it per bootstrap token, so a token-holding
        joiner VERIFIES the CA instead of trusting first use; tokenless
        discovery remains TOFU."""
        from ..runtime.store import Conflict

        for obj_kind, obj in (
            ("namespaces", api.Namespace(
                metadata=api.ObjectMeta(name="kube-public"),
                status=api.NamespaceStatus(phase="Active"))),
            ("configmaps", self._signed_cluster_info()),
            ("roles", api.Role(
                metadata=api.ObjectMeta(name="kubeadm:bootstrap-signer",
                                        namespace="kube-public"),
                rules=[api.RBACPolicyRule(
                    verbs=["get"], api_groups=[""],
                    resources=["configmaps"],
                    resource_names=["cluster-info"])])),
            ("rolebindings", api.RoleBinding(
                metadata=api.ObjectMeta(name="kubeadm:cluster-info",
                                        namespace="kube-public"),
                subjects=[
                    api.RBACSubject(kind="Group",
                                    name="system:unauthenticated"),
                    api.RBACSubject(kind="Group",
                                    name="system:authenticated")],
                role_ref=api.RoleRef(kind="Role",
                                     name="kubeadm:bootstrap-signer"))),
        ):
            try:
                self.store.create(obj_kind, obj)
            except Conflict:
                pass

    @property
    def url(self) -> str:
        return self.apiserver.url

    def start(self) -> "Cluster":
        # phase order mirrors init.go: serve the API first, then the
        # controllers that need it, then nodes
        self.apiserver.start()
        self.manager.start()

        def sched_loop():
            while not self._stop.is_set():
                if self.scheduler.run_once(timeout=0.2) == 0:
                    self._stop.wait(0.02)
            self.scheduler.close()

        self._sched_thread = threading.Thread(target=sched_loop,
                                              name="scheduler", daemon=True)
        self._sched_thread.start()
        if self._hollow_nodes:
            from ..kubemark.hollow import HollowCluster

            self.hollow = HollowCluster(self.store, self._hollow_nodes).run()
        return self

    def stop(self):
        self._stop.set()
        if self._sched_thread is not None:
            self._sched_thread.join(timeout=5)
        if self.hollow is not None:
            self.hollow.stop()
        self._sched_store.stop()
        self.manager.stop()
        self.apiserver.stop()
        close = getattr(self.store, "close", None)
        if close is not None:
            close()

    def wait_ready(self, timeout: float = 10.0) -> bool:
        """Bootstrap settled: default namespace's service account exists
        (the init.go 'wait for control plane' phase analog)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            self.manager.sync_all(rounds=1)
            if self.store.get("serviceaccounts", "default",
                              "default") is not None:
                return True
            time.sleep(0.02)
        return False


def ensure_bootstrap_objects(store):
    """Seed objects every cluster needs (init.go uploadconfig +
    bootstrap-token phases analog): the default namespace object."""
    from ..runtime.store import Conflict

    for name in ("default", "kube-system"):
        try:
            store.create("namespaces", api.Namespace(
                metadata=api.ObjectMeta(name=name),
                status=api.NamespaceStatus(phase="Active")))
        except Conflict:
            pass


# -- phases architecture (cmd/kubeadm/app/phases/) ----------------------------
#
# init decomposes into named, IDEMPOTENT, individually re-runnable
# phases over the store — `kubeadm init phase <name>` re-runs one (e.g.
# after restoring a data-dir), `kubeadm init` runs them all in order.
# The serving processes (apiserver/controllers/scheduler) start after
# the store-level phases, like the reference's control-plane phase
# writing manifests the kubelet then runs.

CLUSTER_VERSION = "v1.11-tpu.5"
CLUSTER_CONFIG_NAME = "kubeadm-config"


def phase_preflight(store=None, data_dir=None, port=0):
    """preflight checks (cmd/kubeadm/app/preflight/checks.go): the
    environment problems that would make later phases fail confusingly.
    Returns a list of error strings (empty = pass)."""
    import os
    import socket

    errors = []
    if data_dir:
        # NativeObjectStore makedirs() the whole path, so probe by
        # doing exactly that (os.access lies under root); the dir is one
        # init would create anyway
        import tempfile

        try:
            os.makedirs(data_dir, exist_ok=True)
            with tempfile.TemporaryFile(dir=data_dir):
                pass
        except OSError as e:
            errors.append(f"data dir {data_dir!r} is not writable: {e}")
    if port:
        try:
            s = socket.socket()
            s.bind(("127.0.0.1", port))
            s.close()
        except OSError as e:
            errors.append(f"apiserver port {port} unavailable: {e}")
    try:
        import jax  # noqa: F401
    except Exception as e:  # pragma: no cover - environment-dependent
        errors.append(f"jax unavailable: {e}")
    return errors


def phase_certs(store):
    """certs phase: the cluster CA (+SA signing key) in kube-system."""
    from ..server import pki

    return pki.ensure_cluster_ca(store)


def phase_bootstrap_objects(store):
    ensure_bootstrap_objects(store)


def phase_upload_config(store):
    """uploadconfig phase: record the cluster version/config in a
    kube-system ConfigMap — what `kubeadm upgrade` reads and bumps."""
    from ..runtime.store import Conflict

    try:
        store.create("configmaps", api.ConfigMap(
            metadata=api.ObjectMeta(name=CLUSTER_CONFIG_NAME,
                                    namespace="kube-system"),
            data={"clusterVersion": CLUSTER_VERSION}))
    except Conflict:
        pass


def bump_cluster_version(store, to_version: str):
    """Record the new cluster version in kubeadm-config, creating it if
    absent; retried against the fresh object on CAS conflicts (a
    swallowed conflict would leave the upgrade unrecorded)."""
    from ..runtime.store import Conflict

    old_version = None
    for _ in range(8):
        cm = store.get("configmaps", "kube-system", CLUSTER_CONFIG_NAME)
        if cm is None:
            phase_upload_config(store)
            continue
        old_version = cm.data.get("clusterVersion")
        cm.data = dict(cm.data)
        cm.data["clusterVersion"] = to_version
        try:
            store.update("configmaps", cm)
            return old_version
        except Conflict:
            continue
    raise RuntimeError("could not record the new cluster version "
                       "(persistent write conflicts)")


# (name, description, fn(store)) — order matters; all idempotent
PHASES = [
    ("certs", "cluster CA + service-account signing key", phase_certs),
    ("bootstrap-objects", "default/kube-system namespaces",
     phase_bootstrap_objects),
    ("upload-config", "record cluster version in kubeadm-config",
     phase_upload_config),
]


def upgrade_cluster(cluster: "Cluster", to_version: str) -> "Cluster":
    """kubeadm upgrade apply: round-trip a LIVE cluster through an
    apiserver restart at a new version (cmd/kubeadm/app/cmd/upgrade/).
    The durable store (etcd analog) carries every object across; the
    replacement apiserver serves the same port so clients reconnect and
    relist; multi-version kinds keep serving through the conversion hub
    (api/conversion.py) — the part a real version skew exercises.
    Returns the same cluster object, upgraded in place."""
    from ..server.admission import AdmissionChain
    from ..server.apiserver import APIServer

    old = cluster.apiserver
    port = old.port
    reconcile = old.endpoint_reconciler is not None
    old.stop()
    # the new "binary" serves the SAME store (the etcd analog) on the
    # same port — object preservation is structural, not a copy; the
    # smoke check below proves the new server actually serves it
    cluster.apiserver = APIServer(
        cluster.store, admission=AdmissionChain.default(), port=port,
        authenticator=old.authenticator, authorizer=old.authorizer,
        reconcile_endpoints=reconcile, tls=cluster.ca).start()
    assert cluster.apiserver.store is cluster.store
    bump_cluster_version(cluster.store, to_version)
    return cluster


def cmd_phase(args) -> int:
    if args.phase == "list":
        print("preflight\t environment checks (run with init)")
        for name, desc, _ in PHASES:
            print(f"{name}\t {desc}")
        return 0
    if args.phase == "preflight":
        errors = phase_preflight(data_dir=args.data_dir, port=args.port)
        for e in errors:
            print(f"[preflight] ERROR: {e}", file=sys.stderr)
        print("preflight passed" if not errors else
              f"preflight failed ({len(errors)} errors)")
        return 1 if errors else 0
    fns = {name: fn for name, _, fn in PHASES}
    if args.phase not in fns:
        print(f"error: unknown phase {args.phase!r}", file=sys.stderr)
        return 1
    if args.data_dir:
        from ..runtime.nativestore import NativeObjectStore

        store = NativeObjectStore(path=args.data_dir)
    else:
        print("error: a store is required (--data-dir)", file=sys.stderr)
        return 1
    try:
        fns[args.phase](store)
        print(f"phase {args.phase} complete")
        return 0
    finally:
        close = getattr(store, "close", None)
        if close:
            close()


def cmd_init(args) -> int:
    if not getattr(args, "skip_preflight", False):
        errors = phase_preflight(data_dir=args.data_dir, port=args.port)
        if errors:
            for e in errors:
                print(f"[preflight] ERROR: {e}", file=sys.stderr)
            print("error: preflight failed (use --skip-preflight to "
                  "override)", file=sys.stderr)
            return 1
    cluster = Cluster(data_dir=args.data_dir, port=args.port,
                      hollow_nodes=args.hollow_nodes,
                      secure=getattr(args, "secure", False),
                      cluster_autoscaler=getattr(args, "cluster_autoscaler",
                                                 False),
                      node_eviction_rate=args.node_eviction_rate,
                      secondary_node_eviction_rate=(
                          args.secondary_node_eviction_rate),
                      large_cluster_size_threshold=(
                          args.large_cluster_size_threshold),
                      unhealthy_zone_threshold=args.unhealthy_zone_threshold)
    for _name, _desc, fn in PHASES:  # store-level phases, in order
        fn(cluster.store)
    cluster.start()
    if not cluster.wait_ready():
        print("error: control plane did not become ready "
              "(default service account never appeared)", file=sys.stderr)
        cluster.stop()
        return 1
    print(f"control plane ready at {cluster.url}")
    if cluster.admin_token:
        print(f"  admin token:     {cluster.admin_token}")
        print(f"  bootstrap token: {cluster.bootstrap_token}")
    print(f"  export KUBECTL_SERVER={cluster.url}")
    print(f"  python -m kubernetes_tpu.cli.kubectl get nodes")
    if args.once:
        cluster.stop()
        return 0
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        cluster.stop()
    return 0


def fetch_cluster_ca(server: str, token: Optional[str] = None) -> str:
    """CA discovery from the anonymous cluster-info ConfigMap
    (kube-public), fetched over an unverified TLS connection. With a
    bootstrap token the BootstrapSigner's signature for that token is
    VERIFIED (HMAC keyed by the token secret) — the reference discovery
    phase's JWS check, so a man-in-the-middle cannot substitute a CA
    without holding the token. Without a token this is trust-on-first-
    use (the reference's --discovery-token-unsafe-skip-ca-verification
    posture)."""
    from ..client.rest import RESTClient

    tofu = RESTClient(server, insecure_skip_verify=True)
    info = tofu.get("configmaps", "kube-public", "cluster-info")
    if token is not None:
        from ..controllers.bootstrap import verify_cluster_info

        ca = verify_cluster_info(info, token)
        if ca is None:
            raise RuntimeError(
                "cluster-info signature verification FAILED for this "
                "bootstrap token — possible man-in-the-middle, or the "
                "token expired")
        return ca
    return info.data["ca.crt"]


def join_with_csr(server: str, node_name: str, bootstrap_token: str,
                  timeout: float = 15.0, ca_cert_pem: Optional[str] = None):
    """kubeadm join's TLS-bootstrap phase: using only the bootstrap
    token, generate a key + CSR for system:node:<name>, submit it, wait
    for the approver+signer controllers, and return (key_pem, cert_pem,
    ca_cert_pem) — the kubelet mTLS credential + trust bundle every
    later request uses. Reference: cmd/kubeadm/app/phases/kubelet
    (bootstrap kubeconfig) + pkg/controller/certificates/."""
    import secrets as _secrets

    from ..client.rest import RESTClient
    from ..server import pki

    if ca_cert_pem is None and server.startswith("https"):
        # token in hand: discovery is VERIFIED, not TOFU
        ca_cert_pem = fetch_cluster_ca(server, token=bootstrap_token)
    boot = RESTClient(server, token=bootstrap_token,
                      ca_cert_pem=ca_cert_pem)
    key_pem, csr_pem = pki.make_csr(f"system:node:{node_name}",
                                    ("system:nodes",))
    # random suffix, like real kubeadm's node-csr-<rand>: a re-join
    # (restart, retry) must not 409 on the old object — and the old
    # cert would not match the freshly generated key anyway
    csr_name = f"node-csr-{node_name}-{_secrets.token_hex(4)}"
    csr = api.CertificateSigningRequest(
        metadata=api.ObjectMeta(name=csr_name, namespace=""),
        spec=api.CertificateSigningRequestSpec(
            request=csr_pem,
            usages=["digital signature", "key encipherment",
                    "client auth"]))
    boot.create("certificatesigningrequests", csr)
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        got = boot.get("certificatesigningrequests", "", csr_name)
        if got.status.certificate:
            return key_pem, got.status.certificate, ca_cert_pem
        time.sleep(0.05)
    raise TimeoutError(f"CSR for {node_name} was not signed "
                       f"within {timeout}s")


def cmd_join(args) -> int:
    from ..client.reflector import RemoteStore
    from ..client.rest import RESTClient
    from ..kubemark.hollow import HollowNode

    cert_pem = key_pem = ca_pem = None
    if args.bootstrap_token:
        key_pem, cert_pem, ca_pem = join_with_csr(
            args.server, args.node_name, args.bootstrap_token)
        print(f"obtained kubelet client cert for "
              f"system:node:{args.node_name} via CSR (mTLS)")
    elif args.server.startswith("https"):
        # tokenless join against a secure server still needs the CA
        # bundle to talk TLS at all (anonymous-readable cluster-info)
        ca_pem = fetch_cluster_ca(args.server)
    store = RemoteStore(RESTClient(args.server, client_cert_pem=cert_pem,
                                   client_key_pem=key_pem,
                                   ca_cert_pem=ca_pem))
    for kind in ("pods", "nodes"):
        store.mirror(kind)
    store.wait_for_sync()
    node = HollowNode(store, args.node_name).run()
    print(f"node {args.node_name} joined {args.server}")
    if args.once:
        node.stop()
        store.stop()
        return 0
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        node.stop()
        store.stop()
    return 0


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(prog="kubeadm")
    sub = ap.add_subparsers(dest="cmd", required=True)
    p_init = sub.add_parser("init", help="bootstrap a control plane")
    p_init.add_argument("--data-dir", default=None,
                        help="durable storage path (native WAL+snapshot "
                             "engine); omit for in-memory")
    p_init.add_argument("--port", type=int, default=0)
    p_init.add_argument("--hollow-nodes", type=int, default=0)
    p_init.add_argument("--once", action="store_true",
                        help="start, verify, and exit (smoke test)")
    p_init.add_argument("--secure", action="store_true",
                        help="enable authn (x509/SA-token/static) + "
                             "RBAC-from-API-objects")
    p_init.add_argument("--skip-preflight", action="store_true")
    p_init.add_argument("--cluster-autoscaler", action="store_true",
                        dest="cluster_autoscaler",
                        help="run the cluster autoscaler against two "
                             "fake-cloud NodeGroups (tpu-small/tpu-large): "
                             "unschedulable pods trigger simulated "
                             "scale-up, idle nodes drain and scale down")
    # eviction storm control (kube-controller-manager's node lifecycle
    # flags): zone disruption states + per-zone rate-limited eviction
    p_init.add_argument("--node-eviction-rate", type=float, default=None,
                        help="pod evictions/s per zone when the zone is "
                             "healthy (default 0.1)")
    p_init.add_argument("--secondary-node-eviction-rate", type=float,
                        default=None,
                        help="evictions/s in a PartialDisruption zone "
                             "larger than --large-cluster-size-threshold "
                             "(default 0.01); smaller disrupted zones "
                             "halt entirely")
    p_init.add_argument("--large-cluster-size-threshold", type=int,
                        default=None,
                        help="zones above this node count keep evicting "
                             "(at the secondary rate) under partial "
                             "disruption (default 50)")
    p_init.add_argument("--unhealthy-zone-threshold", type=float,
                        default=None,
                        help="fraction of a zone's nodes not-ready before "
                             "it is PartialDisruption (default 0.55); a "
                             "100%% not-ready zone is FullDisruption and "
                             "suspends eviction until heartbeats resume")
    p_phase = sub.add_parser("phase",
                             help="run one init phase (or 'list')")
    p_phase.add_argument("phase")
    p_phase.add_argument("--data-dir", default=None)
    p_phase.add_argument("--port", type=int, default=0)
    p_up = sub.add_parser(
        "upgrade", help="bump a data-dir cluster to a new version; "
                        "verifies every object round-trips through its "
                        "served versions' conversion hub first")
    p_up.add_argument("--data-dir", required=True)
    p_up.add_argument("--to-version", default=CLUSTER_VERSION)
    p_join = sub.add_parser("join", help="join a hollow node")
    p_join.add_argument("server")
    p_join.add_argument("--node-name", default="hollow-0")
    p_join.add_argument("--bootstrap-token", default=None,
                        help="TLS-bootstrap: obtain a kubelet client "
                             "cert via CSR before joining")
    p_join.add_argument("--once", action="store_true")
    p_tok = sub.add_parser("token",
                           help="manage bootstrap tokens on a running "
                                "cluster")
    p_tok.add_argument("action", choices=["create", "list", "delete"])
    p_tok.add_argument("target", nargs="?", default="",
                       help="delete: token or token-id")
    p_tok.add_argument("--server", required=True)
    p_tok.add_argument("--token", default=None,
                       help="admin credential for the API")
    p_tok.add_argument("--ttl", type=float, default=86400.0,
                       help="seconds until expiry (0 = never)")
    p_reset = sub.add_parser("reset",
                             help="wipe a cluster data-dir")
    p_reset.add_argument("--data-dir", required=True)
    p_reset.add_argument("--force", action="store_true")
    sub.add_parser("version")
    return ap


def cmd_upgrade(args) -> int:
    """Offline upgrade of a durable data-dir: verify the conversion hub
    round-trips every object at every served version, then bump the
    recorded cluster version. The live form is upgrade_cluster()."""
    from ..api import conversion, scheme
    from ..runtime.nativestore import NativeObjectStore

    store = NativeObjectStore(path=args.data_dir)
    try:
        # CRD kinds only join the scheme through a serving apiserver;
        # register the STORED CRDs so their custom resources (and extra
        # served versions) are verified too instead of silently skipped
        for crd in store.list("customresourcedefinitions"):
            try:
                scheme.register_dynamic(crd)
            except ValueError:
                pass
        checked = 0
        for kind in list(scheme._REGISTRY):
            plural = scheme.plural_for_kind(kind)
            hub_gv = scheme.api_version_for(kind)
            for obj in store.list(plural):
                hub = scheme.encode_object(obj)
                for gv in scheme.served_versions(kind):
                    wire = conversion.from_hub(kind, dict(hub), gv, hub_gv)
                    back = conversion.to_hub(kind, wire, gv, hub_gv)
                    scheme.decode(kind, back)  # must stay decodable
                    checked += 1
        old_version = bump_cluster_version(store, args.to_version)
        print(f"upgraded {old_version or '<unversioned>'} -> "
              f"{args.to_version}: {checked} object-version round-trips "
              f"verified")
        return 0
    finally:
        close = getattr(store, "close", None)
        if close:
            close()


def cmd_token(args) -> int:
    """kubeadm token create/list/delete (cmd/kubeadm/app/cmd/token.go)
    against a RUNNING cluster's API — bootstrap tokens are kube-system
    Secrets (phases/bootstraptoken/node/token.go), so every subcommand
    is ordinary Secret CRUD the BootstrapSigner/TokenCleaner observe."""
    from ..client.rest import APIStatusError, RESTClient
    from ..controllers import bootstrap as bt

    client = RESTClient(args.server, token=args.token)
    try:
        if args.action == "create":
            tid, tsec, wire = bt.new_bootstrap_token()
            sec = bt.make_token_secret(
                tid, tsec, ttl_seconds=args.ttl if args.ttl > 0 else None)
            client.create("secrets", sec, namespace=bt.TOKEN_NAMESPACE)
            print(wire)
            return 0
        if args.action == "list":
            secs, _ = client.list("secrets", bt.TOKEN_NAMESPACE)
            now = time.time()
            print("TOKEN\t\t\tTTL\tUSAGES")
            for s in secs:
                if s.type != bt.TOKEN_SECRET_TYPE:
                    continue
                tid = s.data.get("token-id", "?")
                exp = bt.parse_expiration(s.data.get("expiration"))
                ttl = ("<forever>" if exp is None else
                       f"{max(0, int(exp - now))}s")
                usages = ",".join(sorted(
                    k[len("usage-bootstrap-"):] for k, v in s.data.items()
                    if k.startswith("usage-bootstrap-") and v == "true"))
                print(f"{tid}.{'*' * 16}\t{ttl}\t{usages}")
            return 0
        # delete
        if not args.target:
            print("error: token delete needs a token or token-id",
                  file=sys.stderr)
            return 1
        tid = args.target.split(".")[0]
        name = (tid if tid.startswith(bt.TOKEN_SECRET_PREFIX)
                else bt.TOKEN_SECRET_PREFIX + tid)
        client.delete("secrets", bt.TOKEN_NAMESPACE, name)
        print(f"bootstrap token {tid!r} deleted")
        return 0
    except APIStatusError as e:
        if e.code == 404:
            print(f"error: token {args.target!r} not found",
                  file=sys.stderr)
        else:
            print(f"error from server: {e}", file=sys.stderr)
        return 1


def cmd_reset(args) -> int:
    """kubeadm reset (cmd/kubeadm/app/cmd/reset.go): wipe the local
    control-plane state this binary created — here, the durable
    data-dir (WAL + snapshots). Refuses without --force, like the
    reference's interactive confirmation."""
    import shutil

    if not os.path.isdir(args.data_dir):
        print(f"error: {args.data_dir!r} is not a directory",
              file=sys.stderr)
        return 1
    marker = [f for f in os.listdir(args.data_dir)
              if f.startswith(("wal", "snapshot"))]
    if not marker:
        print(f"error: {args.data_dir!r} does not look like a cluster "
              f"data-dir (no wal/snapshot files); not removing",
              file=sys.stderr)
        return 1
    if not args.force:
        print("error: pass --force to wipe the cluster state",
              file=sys.stderr)
        return 1
    shutil.rmtree(args.data_dir)
    print(f"cluster state at {args.data_dir} removed")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.cmd == "version":
        print(f"kubeadm version: {CLUSTER_VERSION}")
        return 0
    return {"init": cmd_init, "join": cmd_join, "phase": cmd_phase,
            "upgrade": cmd_upgrade, "token": cmd_token,
            "reset": cmd_reset}[args.cmd](args)


if __name__ == "__main__":
    sys.exit(main())
