"""kubeadm-lite: one-command cluster bootstrap.

Reference: cmd/kubeadm/app/cmd/init.go (phases: preflight -> control
plane -> wait -> post-init) and join.go. `init` stands up the full
control plane in one process — apiserver (durable native store with
--data-dir, else in-memory), controller manager, scheduler, and
optionally N hollow nodes — then prints how to connect kubectl.
`join` registers a hollow kubelet against a running server.

Run as: python -m kubernetes_tpu.cli.kubeadm init [--data-dir D]
        [--hollow-nodes N] [--port P]
"""

from __future__ import annotations

import argparse
import sys
import threading
import time
from typing import List, Optional

from ..api import types as api
from ..controllers.manager import ControllerManager
from ..runtime.store import ObjectStore
from ..sched.scheduler import Scheduler
from ..server.admission import AdmissionChain
from ..server.apiserver import APIServer


class Cluster:
    """A running control plane (the object form of `kubeadm init`)."""

    def __init__(self, data_dir: Optional[str] = None, port: int = 0,
                 hollow_nodes: int = 0, reconcile_endpoints: bool = True,
                 secure: bool = False):
        if data_dir:
            from ..runtime.nativestore import NativeObjectStore

            self.store = NativeObjectStore(path=data_dir)
        else:
            self.store = ObjectStore()
        authenticator = authorizer = None
        self.admin_token = self.bootstrap_token = None
        self.ca = None
        if secure:
            # init.go's certs + bootstrap-token + RBAC phases: cluster
            # CA, an HTTPS serving cert from it, admin + join
            # credentials, RBAC evaluated from served API objects
            # (runtime-reconfigurable). x509 identity comes from the TLS
            # handshake's verified peer chain.
            import secrets as _secrets

            from ..server import pki
            from ..server.auth import (AuthenticatorChain, RBACAuthorizer,
                                       UserInfo, cluster_admin_bindings)

            self.ca = ca = pki.ensure_cluster_ca(self.store)
            self.admin_token = f"admin-{_secrets.token_hex(8)}"
            self.bootstrap_token = f"bootstrap-{_secrets.token_hex(8)}"
            authenticator = AuthenticatorChain(
                tokens={
                    self.admin_token: UserInfo(
                        "kubernetes-admin", ("system:masters",
                                             "system:authenticated")),
                    self.bootstrap_token: UserInfo(
                        "system:bootstrap:kubeadm",
                        ("system:bootstrappers", "system:authenticated")),
                },
                store=self.store, ca=ca)
            authorizer = RBACAuthorizer(
                bindings=cluster_admin_bindings(["system:masters"]),
                store=self.store)
            self._seed_rbac()
            self._publish_cluster_info()
        self.apiserver = APIServer(
            self.store, admission=AdmissionChain.default(), port=port,
            authenticator=authenticator, authorizer=authorizer,
            reconcile_endpoints=reconcile_endpoints, tls=self.ca)
        self.manager = ControllerManager(self.store)
        # the scheduler runs as an API CLIENT over a loopback watch
        # mirror — the reference's deployment shape (kube-scheduler
        # connects via client-go, cmd/kube-scheduler). Running it on the
        # raw shared store would invert Scheduler._mu against the store
        # lock: an apiserver handler thread mutating the store dispatches
        # informer events UNDER the store lock into scheduler handlers
        # that take _mu, while a scheduling wave holds _mu and writes the
        # store (observed deadlock under kubelet heartbeat load).
        from ..client.reflector import RemoteStore
        from ..client.rest import RESTClient

        self._sched_client = RESTClient(
            self.apiserver.url, token=self.admin_token,
            ca_cert_pem=self.ca.ca_cert_pem if self.ca else None)
        self._sched_store = RemoteStore(self._sched_client)
        self.scheduler = Scheduler(self._sched_store)
        self.hollow = None
        self._hollow_nodes = hollow_nodes
        self._stop = threading.Event()
        self._sched_thread: Optional[threading.Thread] = None

    def _seed_rbac(self):
        """Bootstrap RBAC objects (cmd/kubeadm/app/phases/bootstraptoken/
        clusterinfo + the reference's bootstrap policy): joiners may
        create and read CSRs, nothing else; node identity then comes
        from the signed cert + the node authorizer."""
        from ..runtime.store import Conflict

        # each create gets its OWN conflict guard: a crash between the
        # two must not leave the seed half-applied forever on re-init
        try:
            self.store.create("clusterroles", api.ClusterRole(
                metadata=api.ObjectMeta(name="system:node-bootstrapper"),
                rules=[api.RBACPolicyRule(
                    # create + named get only: a joiner polls its OWN
                    # CSR; list/watch would let any bootstrap-token
                    # holder enumerate other nodes' signed certs
                    verbs=["create", "get"],
                    api_groups=["certificates.k8s.io"],
                    resources=["certificatesigningrequests"])]))
        except Conflict:
            pass
        try:
            self.store.create("clusterrolebindings", api.ClusterRoleBinding(
                metadata=api.ObjectMeta(
                    name="kubeadm:kubelet-bootstrap"),
                subjects=[api.RBACSubject(kind="Group",
                                          name="system:bootstrappers")],
                role_ref=api.RoleRef(kind="ClusterRole",
                                     name="system:node-bootstrapper")))
        except Conflict:
            pass

    def _publish_cluster_info(self):
        """The cluster-info ConfigMap in kube-public, readable
        anonymously — how a joiner learns the CA bundle before it can
        authenticate (reference: clusterinfo phase publishes a
        kubeconfig with the CA; BootstrapSigner makes it verifiable.
        Here the joiner fetches it trust-on-first-use over TLS — a
        documented simplification of the JWS-hash check)."""
        from ..runtime.store import Conflict

        for obj_kind, obj in (
            ("namespaces", api.Namespace(
                metadata=api.ObjectMeta(name="kube-public"),
                status=api.NamespaceStatus(phase="Active"))),
            ("configmaps", api.ConfigMap(
                metadata=api.ObjectMeta(name="cluster-info",
                                        namespace="kube-public"),
                data={"ca.crt": self.ca.ca_cert_pem})),
            ("roles", api.Role(
                metadata=api.ObjectMeta(name="kubeadm:bootstrap-signer",
                                        namespace="kube-public"),
                rules=[api.RBACPolicyRule(
                    verbs=["get"], api_groups=[""],
                    resources=["configmaps"],
                    resource_names=["cluster-info"])])),
            ("rolebindings", api.RoleBinding(
                metadata=api.ObjectMeta(name="kubeadm:cluster-info",
                                        namespace="kube-public"),
                subjects=[
                    api.RBACSubject(kind="Group",
                                    name="system:unauthenticated"),
                    api.RBACSubject(kind="Group",
                                    name="system:authenticated")],
                role_ref=api.RoleRef(kind="Role",
                                     name="kubeadm:bootstrap-signer"))),
        ):
            try:
                self.store.create(obj_kind, obj)
            except Conflict:
                pass

    @property
    def url(self) -> str:
        return self.apiserver.url

    def start(self) -> "Cluster":
        # phase order mirrors init.go: serve the API first, then the
        # controllers that need it, then nodes
        self.apiserver.start()
        self.manager.start()

        def sched_loop():
            while not self._stop.is_set():
                if self.scheduler.run_once(timeout=0.2) == 0:
                    self._stop.wait(0.02)
            self.scheduler.close()

        self._sched_thread = threading.Thread(target=sched_loop,
                                              name="scheduler", daemon=True)
        self._sched_thread.start()
        if self._hollow_nodes:
            from ..kubemark.hollow import HollowCluster

            self.hollow = HollowCluster(self.store, self._hollow_nodes).run()
        return self

    def stop(self):
        self._stop.set()
        if self._sched_thread is not None:
            self._sched_thread.join(timeout=5)
        if self.hollow is not None:
            self.hollow.stop()
        self._sched_store.stop()
        self.manager.stop()
        self.apiserver.stop()
        close = getattr(self.store, "close", None)
        if close is not None:
            close()

    def wait_ready(self, timeout: float = 10.0) -> bool:
        """Bootstrap settled: default namespace's service account exists
        (the init.go 'wait for control plane' phase analog)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            self.manager.sync_all(rounds=1)
            if self.store.get("serviceaccounts", "default",
                              "default") is not None:
                return True
            time.sleep(0.02)
        return False


def ensure_bootstrap_objects(store):
    """Seed objects every cluster needs (init.go uploadconfig +
    bootstrap-token phases analog): the default namespace object."""
    from ..runtime.store import Conflict

    for name in ("default", "kube-system"):
        try:
            store.create("namespaces", api.Namespace(
                metadata=api.ObjectMeta(name=name),
                status=api.NamespaceStatus(phase="Active")))
        except Conflict:
            pass


def cmd_init(args) -> int:
    cluster = Cluster(data_dir=args.data_dir, port=args.port,
                      hollow_nodes=args.hollow_nodes,
                      secure=getattr(args, "secure", False))
    ensure_bootstrap_objects(cluster.store)
    cluster.start()
    if not cluster.wait_ready():
        print("error: control plane did not become ready "
              "(default service account never appeared)", file=sys.stderr)
        cluster.stop()
        return 1
    print(f"control plane ready at {cluster.url}")
    if cluster.admin_token:
        print(f"  admin token:     {cluster.admin_token}")
        print(f"  bootstrap token: {cluster.bootstrap_token}")
    print(f"  export KUBECTL_SERVER={cluster.url}")
    print(f"  python -m kubernetes_tpu.cli.kubectl get nodes")
    if args.once:
        cluster.stop()
        return 0
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        cluster.stop()
    return 0


def fetch_cluster_ca(server: str) -> str:
    """Trust-on-first-use CA discovery: read the anonymous cluster-info
    ConfigMap (kube-public) over an UNVERIFIED TLS connection and return
    its CA bundle; every later connection verifies against it.
    Reference: the discovery phase's cluster-info fetch; the JWS
    token-signature check is simplified to TOFU (documented)."""
    from ..client.rest import RESTClient

    tofu = RESTClient(server, insecure_skip_verify=True)
    info = tofu.get("configmaps", "kube-public", "cluster-info")
    return info.data["ca.crt"]


def join_with_csr(server: str, node_name: str, bootstrap_token: str,
                  timeout: float = 15.0, ca_cert_pem: Optional[str] = None):
    """kubeadm join's TLS-bootstrap phase: using only the bootstrap
    token, generate a key + CSR for system:node:<name>, submit it, wait
    for the approver+signer controllers, and return (key_pem, cert_pem,
    ca_cert_pem) — the kubelet mTLS credential + trust bundle every
    later request uses. Reference: cmd/kubeadm/app/phases/kubelet
    (bootstrap kubeconfig) + pkg/controller/certificates/."""
    import secrets as _secrets

    from ..client.rest import RESTClient
    from ..server import pki

    if ca_cert_pem is None and server.startswith("https"):
        ca_cert_pem = fetch_cluster_ca(server)
    boot = RESTClient(server, token=bootstrap_token,
                      ca_cert_pem=ca_cert_pem)
    key_pem, csr_pem = pki.make_csr(f"system:node:{node_name}",
                                    ("system:nodes",))
    # random suffix, like real kubeadm's node-csr-<rand>: a re-join
    # (restart, retry) must not 409 on the old object — and the old
    # cert would not match the freshly generated key anyway
    csr_name = f"node-csr-{node_name}-{_secrets.token_hex(4)}"
    csr = api.CertificateSigningRequest(
        metadata=api.ObjectMeta(name=csr_name, namespace=""),
        spec=api.CertificateSigningRequestSpec(
            request=csr_pem,
            usages=["digital signature", "key encipherment",
                    "client auth"]))
    boot.create("certificatesigningrequests", csr)
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        got = boot.get("certificatesigningrequests", "", csr_name)
        if got.status.certificate:
            return key_pem, got.status.certificate, ca_cert_pem
        time.sleep(0.05)
    raise TimeoutError(f"CSR for {node_name} was not signed "
                       f"within {timeout}s")


def cmd_join(args) -> int:
    from ..client.reflector import RemoteStore
    from ..client.rest import RESTClient
    from ..kubemark.hollow import HollowNode

    cert_pem = key_pem = ca_pem = None
    if args.bootstrap_token:
        key_pem, cert_pem, ca_pem = join_with_csr(
            args.server, args.node_name, args.bootstrap_token)
        print(f"obtained kubelet client cert for "
              f"system:node:{args.node_name} via CSR (mTLS)")
    elif args.server.startswith("https"):
        # tokenless join against a secure server still needs the CA
        # bundle to talk TLS at all (anonymous-readable cluster-info)
        ca_pem = fetch_cluster_ca(args.server)
    store = RemoteStore(RESTClient(args.server, client_cert_pem=cert_pem,
                                   client_key_pem=key_pem,
                                   ca_cert_pem=ca_pem))
    for kind in ("pods", "nodes"):
        store.mirror(kind)
    store.wait_for_sync()
    node = HollowNode(store, args.node_name).run()
    print(f"node {args.node_name} joined {args.server}")
    if args.once:
        node.stop()
        store.stop()
        return 0
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        node.stop()
        store.stop()
    return 0


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(prog="kubeadm")
    sub = ap.add_subparsers(dest="cmd", required=True)
    p_init = sub.add_parser("init", help="bootstrap a control plane")
    p_init.add_argument("--data-dir", default=None,
                        help="durable storage path (native WAL+snapshot "
                             "engine); omit for in-memory")
    p_init.add_argument("--port", type=int, default=0)
    p_init.add_argument("--hollow-nodes", type=int, default=0)
    p_init.add_argument("--once", action="store_true",
                        help="start, verify, and exit (smoke test)")
    p_init.add_argument("--secure", action="store_true",
                        help="enable authn (x509/SA-token/static) + "
                             "RBAC-from-API-objects")
    p_join = sub.add_parser("join", help="join a hollow node")
    p_join.add_argument("server")
    p_join.add_argument("--node-name", default="hollow-0")
    p_join.add_argument("--bootstrap-token", default=None,
                        help="TLS-bootstrap: obtain a kubelet client "
                             "cert via CSR before joining")
    p_join.add_argument("--once", action="store_true")
    return ap


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    return {"init": cmd_init, "join": cmd_join}[args.cmd](args)


if __name__ == "__main__":
    sys.exit(main())
