"""kubeadm-lite: one-command cluster bootstrap.

Reference: cmd/kubeadm/app/cmd/init.go (phases: preflight -> control
plane -> wait -> post-init) and join.go. `init` stands up the full
control plane in one process — apiserver (durable native store with
--data-dir, else in-memory), controller manager, scheduler, and
optionally N hollow nodes — then prints how to connect kubectl.
`join` registers a hollow kubelet against a running server.

Run as: python -m kubernetes_tpu.cli.kubeadm init [--data-dir D]
        [--hollow-nodes N] [--port P]
"""

from __future__ import annotations

import argparse
import sys
import threading
import time
from typing import List, Optional

from ..api import types as api
from ..controllers.manager import ControllerManager
from ..runtime.store import ObjectStore
from ..sched.scheduler import Scheduler
from ..server.admission import AdmissionChain
from ..server.apiserver import APIServer


class Cluster:
    """A running control plane (the object form of `kubeadm init`)."""

    def __init__(self, data_dir: Optional[str] = None, port: int = 0,
                 hollow_nodes: int = 0, reconcile_endpoints: bool = True):
        if data_dir:
            from ..runtime.nativestore import NativeObjectStore

            self.store = NativeObjectStore(path=data_dir)
        else:
            self.store = ObjectStore()
        self.apiserver = APIServer(
            self.store, admission=AdmissionChain.default(), port=port,
            reconcile_endpoints=reconcile_endpoints)
        self.manager = ControllerManager(self.store)
        self.scheduler = Scheduler(self.store)
        self.hollow = None
        self._hollow_nodes = hollow_nodes
        self._stop = threading.Event()
        self._sched_thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        return self.apiserver.url

    def start(self) -> "Cluster":
        # phase order mirrors init.go: serve the API first, then the
        # controllers that need it, then nodes
        self.apiserver.start()
        self.manager.start()

        def sched_loop():
            while not self._stop.is_set():
                if self.scheduler.run_once(timeout=0.2) == 0:
                    self._stop.wait(0.02)
            self.scheduler.close()

        self._sched_thread = threading.Thread(target=sched_loop,
                                              name="scheduler", daemon=True)
        self._sched_thread.start()
        if self._hollow_nodes:
            from ..kubemark.hollow import HollowCluster

            self.hollow = HollowCluster(self.store, self._hollow_nodes).run()
        return self

    def stop(self):
        self._stop.set()
        if self._sched_thread is not None:
            self._sched_thread.join(timeout=5)
        if self.hollow is not None:
            self.hollow.stop()
        self.manager.stop()
        self.apiserver.stop()
        close = getattr(self.store, "close", None)
        if close is not None:
            close()

    def wait_ready(self, timeout: float = 10.0) -> bool:
        """Bootstrap settled: default namespace's service account exists
        (the init.go 'wait for control plane' phase analog)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            self.manager.sync_all(rounds=1)
            if self.store.get("serviceaccounts", "default",
                              "default") is not None:
                return True
            time.sleep(0.02)
        return False


def ensure_bootstrap_objects(store):
    """Seed objects every cluster needs (init.go uploadconfig +
    bootstrap-token phases analog): the default namespace object."""
    from ..runtime.store import Conflict

    for name in ("default", "kube-system"):
        try:
            store.create("namespaces", api.Namespace(
                metadata=api.ObjectMeta(name=name),
                status=api.NamespaceStatus(phase="Active")))
        except Conflict:
            pass


def cmd_init(args) -> int:
    cluster = Cluster(data_dir=args.data_dir, port=args.port,
                      hollow_nodes=args.hollow_nodes)
    ensure_bootstrap_objects(cluster.store)
    cluster.start()
    if not cluster.wait_ready():
        print("error: control plane did not become ready "
              "(default service account never appeared)", file=sys.stderr)
        cluster.stop()
        return 1
    print(f"control plane ready at {cluster.url}")
    print(f"  export KUBECTL_SERVER={cluster.url}")
    print(f"  python -m kubernetes_tpu.cli.kubectl get nodes")
    if args.once:
        cluster.stop()
        return 0
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        cluster.stop()
    return 0


def cmd_join(args) -> int:
    from ..client.reflector import RemoteStore
    from ..client.rest import RESTClient
    from ..kubemark.hollow import HollowNode

    store = RemoteStore(RESTClient(args.server))
    for kind in ("pods", "nodes"):
        store.mirror(kind)
    store.wait_for_sync()
    node = HollowNode(store, args.node_name).run()
    print(f"node {args.node_name} joined {args.server}")
    if args.once:
        node.stop()
        store.stop()
        return 0
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        node.stop()
        store.stop()
    return 0


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(prog="kubeadm")
    sub = ap.add_subparsers(dest="cmd", required=True)
    p_init = sub.add_parser("init", help="bootstrap a control plane")
    p_init.add_argument("--data-dir", default=None,
                        help="durable storage path (native WAL+snapshot "
                             "engine); omit for in-memory")
    p_init.add_argument("--port", type=int, default=0)
    p_init.add_argument("--hollow-nodes", type=int, default=0)
    p_init.add_argument("--once", action="store_true",
                        help="start, verify, and exit (smoke test)")
    p_join = sub.add_parser("join", help="join a hollow node")
    p_join.add_argument("server")
    p_join.add_argument("--node-name", default="hollow-0")
    p_join.add_argument("--once", action="store_true")
    return ap


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    return {"init": cmd_init, "join": cmd_join}[args.cmd](args)


if __name__ == "__main__":
    sys.exit(main())
