"""kubeconfig files — the client configuration format every kubectl
user carries.

Reference: client-go tools/clientcmd (api/types.go Config: clusters,
users (AuthInfo), contexts, current-context; loader.go precedence rules;
inline *-data fields are base64). kubeadm writes admin.conf from the
cluster CA + admin credential (cmd/kubeadm/app/phases/kubeconfig);
kubectl loads $KUBECONFIG (else ~/.kube/config) when --server is absent,
with flags overriding file values — the same precedence clientcmd's
DeferredLoadingClientConfig implements.
"""

from __future__ import annotations

import base64
import os
from typing import Optional


def _b64(s: Optional[str]) -> Optional[str]:
    return base64.b64encode(s.encode()).decode() if s else None


def _unb64(s: Optional[str]) -> Optional[str]:
    return base64.b64decode(s).decode() if s else None


def new(cluster: str, server: str, ca_pem: Optional[str] = None,
        user: str = "kubernetes-admin", token: Optional[str] = None,
        client_cert_pem: Optional[str] = None,
        client_key_pem: Optional[str] = None,
        namespace: str = "") -> dict:
    """A single-context Config (what `kubeadm init` emits as
    admin.conf)."""
    ctx = f"{user}@{cluster}"
    user_entry = {}
    if token:
        user_entry["token"] = token
    if client_cert_pem:
        user_entry["client-certificate-data"] = _b64(client_cert_pem)
    if client_key_pem:
        user_entry["client-key-data"] = _b64(client_key_pem)
    cluster_entry = {"server": server}
    if ca_pem:
        cluster_entry["certificate-authority-data"] = _b64(ca_pem)
    context_entry = {"cluster": cluster, "user": user}
    if namespace:
        context_entry["namespace"] = namespace
    return {"apiVersion": "v1", "kind": "Config",
            "clusters": [{"name": cluster, "cluster": cluster_entry}],
            "users": [{"name": user, "user": user_entry}],
            "contexts": [{"name": ctx, "context": context_entry}],
            "current-context": ctx}


def load(path: str) -> dict:
    import yaml

    with open(path) as f:
        cfg = yaml.safe_load(f) or {}
    if cfg.get("kind", "Config") != "Config":
        raise ValueError(f"{path} is not a kubeconfig (kind "
                         f"{cfg.get('kind')!r})")
    for key in ("clusters", "users", "contexts"):
        cfg.setdefault(key, [])
    return cfg


def save(path: str, cfg: dict) -> None:
    import yaml

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        yaml.safe_dump(cfg, f, sort_keys=False)


def default_path() -> str:
    """$KUBECONFIG else ~/.kube/config (loader.go defaults; the
    multi-file KUBECONFIG merge collapses to first-path-wins here)."""
    env = os.environ.get("KUBECONFIG")
    if env:
        return env.split(os.pathsep)[0]
    return os.path.join(os.path.expanduser("~"), ".kube", "config")


def _by_name(entries, name):
    return next((e for e in entries if e.get("name") == name), None)


def resolve(cfg: dict, context: Optional[str] = None) -> dict:
    """Config (+ optional context override) -> connection parameters:
    {server, ca_pem, client_cert_pem, client_key_pem, token, namespace}.
    Raises ValueError when the context graph dangles."""
    ctx_name = context or cfg.get("current-context")
    if not ctx_name:
        raise ValueError("kubeconfig has no current-context")
    ctx = _by_name(cfg.get("contexts", []), ctx_name)
    if ctx is None:
        raise ValueError(f"context {ctx_name!r} not found")
    c = ctx.get("context", {})
    cl = _by_name(cfg.get("clusters", []), c.get("cluster"))
    if cl is None:
        raise ValueError(f"cluster {c.get('cluster')!r} not found")
    u = _by_name(cfg.get("users", []), c.get("user")) or {"user": {}}
    cluster = cl.get("cluster", {})
    user = u.get("user", {})
    return {"server": cluster.get("server"),
            "ca_pem": _unb64(cluster.get("certificate-authority-data")),
            "client_cert_pem": _unb64(user.get("client-certificate-data")),
            "client_key_pem": _unb64(user.get("client-key-data")),
            "token": user.get("token"),
            "namespace": c.get("namespace", "")}
