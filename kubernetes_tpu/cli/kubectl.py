"""kubectl: the user-facing CLI over the REST API.

Reference: pkg/kubectl + cmd/kubectl — verbs get/describe/create/apply/
delete/scale/cordon/uncordon/drain/label/logs-ish/version, table
printers (pkg/printers), YAML/JSON output, manifest files (YAML or JSON,
multi-document). Server address via --server or $KUBECTL_SERVER.

Run as: python -m kubernetes_tpu.cli.kubectl <verb> ...
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import List, Optional

from ..api import scheme
from ..api import types as api
from ..client.rest import APIStatusError, RESTClient

# -- printers (pkg/printers/internalversion/printers.go table defs) -----------


def _age(obj, now=None) -> str:
    ts = getattr(obj.status, "start_time", None) if hasattr(obj, "status") else None
    if ts is None:
        return "-"
    secs = max(0, (now or time.time()) - ts)
    if secs < 120:
        return f"{int(secs)}s"
    if secs < 7200:
        return f"{int(secs // 60)}m"
    return f"{int(secs // 3600)}h"


def _pod_row(p: api.Pod):
    total = len(p.spec.containers)
    ready = sum(1 for c, s in p.status.conditions
                if c == "Ready" and str(s).startswith("True"))
    ready_str = f"{total if ready else 0}/{total}"
    return [p.metadata.name, ready_str, p.status.phase or "Pending",
            p.spec.node_name or "<none>", _age(p)]


def _node_row(n: api.Node):
    ready = next((c.status for c in n.status.conditions
                  if c.type == api.NODE_READY), "Unknown")
    status = "Ready" if ready == "True" else "NotReady"
    if n.spec.unschedulable:
        status += ",SchedulingDisabled"
    roles = ",".join(sorted(
        k.rsplit("/", 1)[1] for k in (n.metadata.labels or {})
        if k.startswith("node-role.kubernetes.io/"))) or "<none>"
    return [n.metadata.name, status, roles,
            str(len(n.spec.taints)) + " taints" if n.spec.taints else "-"]


_COLUMNS = {
    "pods": (["NAME", "READY", "STATUS", "NODE", "AGE"], _pod_row),
    "nodes": (["NAME", "STATUS", "ROLES", "TAINTS"], _node_row),
    "services": (["NAME", "CLUSTER-IP", "PORTS"],
                 lambda s: [s.metadata.name, s.spec.cluster_ip or "<auto>",
                            ",".join(f"{p.port}/{p.protocol}"
                                     for p in s.spec.ports) or "<none>"]),
    "deployments": (["NAME", "DESIRED", "CURRENT", "READY"],
                    lambda d: [d.metadata.name, str(d.spec.replicas),
                               str(d.status.replicas),
                               str(d.status.ready_replicas)]),
    "replicasets": (["NAME", "DESIRED", "CURRENT", "READY"],
                    lambda r: [r.metadata.name, str(r.spec.replicas),
                               str(r.status.replicas),
                               str(r.status.ready_replicas)]),
    "jobs": (["NAME", "COMPLETIONS", "ACTIVE"],
             lambda j: [j.metadata.name,
                        f"{j.status.succeeded}/{j.spec.completions}",
                        str(j.status.active)]),
    "events": (["NAME", "TYPE", "REASON", "OBJECT", "COUNT", "MESSAGE"],
               lambda e: [e.metadata.name, e.type, e.reason,
                          f"{e.involved_kind}/{e.involved_name}",
                          str(e.count), e.message[:60]]),
}


def _write_table(headers: List[str], rows: List[List[str]], out):
    widths = [max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
              for i, h in enumerate(headers)]
    out.write("  ".join(h.ljust(w) for h, w in zip(headers, widths)).rstrip()
              + "\n")
    for r in rows:
        out.write("  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip()
                  + "\n")




def _dump(obj, fmt: str, out):
    data = scheme.encode_object(obj)
    if fmt == "json":
        out.write(json.dumps(data, indent=2) + "\n")
    else:
        import yaml
        out.write(yaml.safe_dump(data, sort_keys=False) + "---\n")


# -- manifest loading ---------------------------------------------------------


def load_manifests(path: str, recursive: bool = False) -> List[dict]:
    """YAML (multi-doc) or JSON manifest -> raw doc dicts. Decoding is
    deferred to per-doc apply time: a CustomResourceDefinition earlier in
    the file must register its kind before later docs of that kind can
    decode (the reference kubectl's sequential server-side discovery).

    A DIRECTORY path loads every *.yaml/*.yml/*.json inside it in sorted
    order (resource.Builder's FilenameParam); recursive descends
    subdirectories (-R)."""
    import os

    if path != "-" and os.path.isdir(path):
        docs: List[dict] = []
        if recursive:
            walker = sorted(
                os.path.join(r, f)
                for r, _, files in os.walk(path) for f in files)
        else:
            walker = sorted(os.path.join(path, f)
                            for f in os.listdir(path))
        for fp in walker:
            if fp.endswith((".yaml", ".yml", ".json")) \
                    and os.path.isfile(fp):
                docs.extend(load_manifests(fp))
        if not docs:
            raise ManifestError(f"no manifests found in {path}")
        return docs
    text = sys.stdin.read() if path == "-" else open(path).read()
    docs: List[dict] = []
    try:
        if text.lstrip().startswith("{"):
            docs = [json.loads(text)]
        else:
            import yaml
            docs = [d for d in yaml.safe_load_all(text) if d]
    except Exception as e:
        # a parse failure is a user-manifest problem, not an internal bug
        raise ManifestError(f"cannot parse {path}: {e}") from e
    return docs


class ManifestError(ValueError):
    """A user-manifest problem (unknown kind, unserved apiVersion) —
    reported as a clean CLI error; internal ValueErrors keep their
    traceback."""


def _decode_doc(doc: dict):
    """Manifest doc -> (hub object, kind). A non-hub apiVersion (an
    extensions/v1beta1 Deployment, say) decodes THROUGH the conversion
    hub so legacy defaulting (nil-selector etc.) applies — the
    reference client's universal decoder converts to the internal
    version the same way. MUTATES `doc` to its hub wire form so
    callers' three-way merges compare like with like."""
    kind = doc.get("kind")
    if not kind or not scheme.is_registered(kind):
        raise ManifestError(f"unknown kind {kind!r}")
    ver = doc.get("apiVersion")
    hub = scheme.api_version_for(kind)
    if ver and ver != hub:
        if not scheme.serves(kind, ver):
            raise ManifestError(f"{kind} is not served at {ver!r}")
        from ..api import conversion

        converted = conversion.to_hub(kind, doc, ver, hub)
        doc.clear()
        doc.update(converted)
    obj = scheme.decode_request(kind, doc)
    return obj, kind


# -- verbs --------------------------------------------------------------------


def _jsonpath_get(doc, path: str) -> list:
    """Evaluate a dotted jsonpath (`.a.b[*].c`, `[N]`) against JSON-ish
    data; wildcards fan out, so the result is a LIST of matches
    (client-go util/jsonpath's core subset)."""
    import re

    cur = [doc]
    for seg in re.findall(r"[^.\[\]]+|\[\*\]|\[\d+\]",
                          path.strip().lstrip(".")):
        nxt = []
        for c in cur:
            if seg == "[*]":
                if isinstance(c, list):
                    nxt.extend(c)
                elif isinstance(c, dict):
                    nxt.extend(c.values())
            elif seg.startswith("["):
                i = int(seg[1:-1])
                if isinstance(c, list) and i < len(c):
                    nxt.append(c[i])
            elif isinstance(c, dict) and seg in c:
                nxt.append(c[seg])
        cur = nxt
    return cur


def _jp_fmt(v) -> str:
    """One value -> text, shared by jsonpath and custom-columns output:
    composites as JSON, booleans lowercase (kubectl's conventions)."""
    return (json.dumps(v) if isinstance(v, (dict, list))
            else str(v).lower() if isinstance(v, bool) else str(v))


def _render_jsonpath(tmpl: str, doc) -> str:
    """Render a jsonpath TEMPLATE — literals, {PATH}, {"quoted"}, and
    {range PATH}...{end} blocks — against one document."""
    import re

    toks = [t for t in re.split(r"(\{[^}]*\})", tmpl) if t]
    fmt = _jp_fmt

    def render_seq(toks, doc):
        res = []
        i = 0
        while i < len(toks):
            t = toks[i]
            if t.startswith("{") and t.endswith("}"):
                inner = t[1:-1].strip()
                if inner.startswith("range "):
                    j, depth = i + 1, 1
                    while j < len(toks):
                        tj = toks[j].strip()
                        if tj.startswith("{") and tj.endswith("}"):
                            tji = tj[1:-1].strip()
                            if tji.startswith("range "):
                                depth += 1
                            elif tji == "end":
                                depth -= 1
                                if depth == 0:
                                    break
                        j += 1
                    body = toks[i + 1:j]
                    for item in _jsonpath_get(doc, inner[6:]):
                        res.append(render_seq(body, item))
                    i = j + 1
                    continue
                if inner.startswith('"'):
                    res.append(inner[1:-1].encode().decode("unicode_escape"))
                elif inner != "end":
                    res.append(" ".join(fmt(v)
                                        for v in _jsonpath_get(doc, inner)))
            else:
                res.append(t)
            i += 1
        return "".join(res)

    return render_seq(toks, doc)


def _parse_selector_flags(args):
    sel = getattr(args, "selector", None)
    fsel = getattr(args, "field_selector", None)
    return sel or None, fsel or None


GET_ALL_KINDS = ("pods", "replicationcontrollers", "services",
                 "daemonsets", "deployments", "replicasets",
                 "statefulsets", "jobs", "cronjobs")


def cmd_get(client, args, out):
    if args.kind == "all":
        # `kubectl get all` — the category expansion (pkg/kubectl
        # categories.go legacyUserResources)
        sel, fsel = _parse_selector_flags(args)
        first = True
        for plural in GET_ALL_KINDS:
            ns = None if args.all_namespaces else args.namespace
            objs, _ = client.list(plural, ns, label_selector=sel,
                                  field_selector=fsel)
            if not objs:
                continue
            if not first:
                out.write("\n")
            first = False
            headers, row_fn = _COLUMNS.get(
                plural, (["NAME", "AGE"],
                         lambda o: [o.metadata.name, _age(o)]))
            _write_table(headers,
                         [[f"{plural}/{r[0]}"] + r[1:]
                          for r in (row_fn(o) for o in objs)], out)
        return
    plural = _resolve_kind(args.kind)
    sel, fsel = _parse_selector_flags(args)
    list_rv = None
    if args.name:
        obj = client.get(plural, args.namespace, args.name)
        objs = [obj]
    else:
        ns = None if args.all_namespaces else args.namespace
        objs, list_rv = client.list(plural, ns, label_selector=sel,
                                    field_selector=fsel)
    if args.watch:
        # get -w (resource_printer + watch): print current rows, then
        # one row per event from the LIST's resourceVersion on (no
        # duplicated synthetic ADDEDs) until --watch-timeout expires
        headers, row_fn = _COLUMNS.get(
            plural, (["NAME", "AGE"], lambda o: [o.metadata.name, _age(o)]))
        _write_table(headers, [list(row_fn(o)) for o in objs], out)
        for etype, obj in client.watch(
                plural, resource_version=list_rv,
                timeout_seconds=args.watch_timeout,
                label_selector=sel):
            out.write("  ".join([etype] + [str(c) for c in row_fn(obj)])
                      + "\n")
        return
    fmt = args.output
    if fmt in ("yaml", "json"):
        for o in objs:
            _dump(o, fmt, out)
    elif fmt.startswith("jsonpath="):
        tmpl = fmt[len("jsonpath="):].strip("'")
        doc = ({"kind": "List",
                "items": [scheme.encode_object(o) for o in objs]}
               if not args.name else scheme.encode_object(objs[0]))
        out.write(_render_jsonpath(tmpl, doc))
        out.write("\n")
    elif fmt.startswith("custom-columns="):
        cols = [c.partition(":") for c in
                fmt[len("custom-columns="):].split(",")]
        headers = [c[0] for c in cols]
        rows = []
        for o in objs:
            doc = scheme.encode_object(o)
            rows.append([" ".join(_jp_fmt(v) for v in
                                  _jsonpath_get(doc, c[2])) or "<none>"
                         for c in cols])
        _write_table(headers, rows, out)
    elif fmt in ("table", "wide"):
        headers, row_fn = _COLUMNS.get(
            plural, (["NAME", "AGE"], lambda o: [o.metadata.name, _age(o)]))
        headers = list(headers)
        rows = [list(row_fn(o)) for o in objs]
        if fmt == "wide" and plural == "pods":
            headers.append("NOMINATED NODE")
            for r, o in zip(rows, objs):
                r.append(o.status.nominated_node_name or "<none>")
        if args.show_labels:
            headers.append("LABELS")
            for r, o in zip(rows, objs):
                r.append(",".join(f"{k}={v}" for k, v in sorted(
                    (o.metadata.labels or {}).items())) or "<none>")
        _write_table(headers, rows, out)
    else:
        raise ManifestError(f"unknown output format {fmt!r}")


def cmd_logs(client, args, out):
    """kubectl logs <pod> [-c container] [--tail N] [-f] — the
    apiserver's pods/<name>/log subresource proxies to the kubelet
    (pkg/kubectl/cmd/logs.go -> registry/core/pod/rest/log.go).
    --follow re-arms the pods/<name>/attach long-poll over the same
    container stream (SPDY streaming collapsed to cursor polls, like
    kubectl attach) for --follow-rounds rounds."""
    if args.follow:
        return _follow_stream(client, args, out, tail=args.tail)
    q = []
    if args.container:
        q.append(f"container={args.container}")
    if args.tail is not None:
        q.append(f"tailLines={args.tail}")
    if getattr(args, "previous", False):
        q.append("previous=true")
    path = client._path("pods", args.namespace, args.name, "log")
    raw, _ = client.request_bytes("GET", path, query="&".join(q))
    out.write(raw.decode())


def cmd_exec(client, args, out):
    """kubectl exec <pod> [-c container] -- cmd... — one-shot exec via
    the pods/<name>/exec subresource (pkg/kubectl/cmd/exec.go)."""
    path = client._path("pods", args.namespace, args.name, "exec")
    body = {"command": args.command}
    if args.container:
        body["container"] = args.container
    resp = client.request("POST", path, body=body)
    out.write(resp.get("output", "") + "\n")
    # the exec API call succeeded; the COMMAND's exit code propagates as
    # the process exit code, like real kubectl exec — not as a fake
    # server error
    return int(resp.get("exitCode", 0))


def _follow_stream(client, args, out, tail=None) -> int:
    """Re-armed long-poll over pods/<name>/attach — the shared follow
    loop behind `kubectl attach` and `kubectl logs -f`. tail=N starts
    the cursor N lines before the current end instead of replaying the
    whole history (logs --tail semantics under -f)."""
    path = client._path("pods", args.namespace, args.name, "attach")

    def poll(since: int, wait: float):
        q = [f"since={since}", f"waitSeconds={wait:g}"]
        if args.container:
            q.append(f"container={args.container}")
        return client.request("GET", path, query="&".join(q))

    since = 0
    if tail is not None:
        # learn the current end without waiting, then back the cursor up
        resp = poll(0, 0.0)
        since = max(0, int(resp.get("next", 0)) - max(0, tail))
    for _ in range(max(1, args.follow_rounds)):
        resp = poll(since, args.wait)
        for line in resp.get("lines", []):
            out.write(line + "\n")
        since = int(resp.get("next", since))
    return 0


def cmd_attach(client, args, out):
    """kubectl attach <pod> [-c container] [--follow-rounds N] — follow
    the container's live output via the pods/<name>/attach long-poll
    (pkg/kubectl/cmd/attach.go; SPDY collapsed to re-armed polls)."""
    return _follow_stream(client, args, out)


def cmd_port_forward(client, args, out):
    """kubectl port-forward <pod> <local:remote> — opens a LOCAL
    listener relaying TCP to the kubelet's relay for the pod's port
    (pkg/kubectl/cmd/portforward.go). Bytes flow
    local->kubelet->pod-backend for real. Prints the local port. With
    --once the listener serves exactly one connection in the background
    and the command returns immediately (in-process/CI callers connect
    after it prints); without it the command blocks serving the
    connection, like real kubectl."""
    import socket
    import threading as _threading

    from ..utils.net import relay_once

    local, _, remote = args.ports.partition(":")
    if not remote:
        local, remote = "0", local
    try:
        remote_port = int(remote)
        local_port = int(local)
    except ValueError:
        print(f"error: ports must be LOCAL:REMOTE integers, "
              f"got {args.ports!r}", file=sys.stderr)
        return 1
    path = client._path("pods", args.namespace, args.name, "portforward")
    resp = client.request("POST", path, body={"port": remote_port})
    relay = (resp["host"], int(resp["port"]))

    lsock = socket.socket()
    lsock.bind(("127.0.0.1", local_port))
    lsock.listen(1)
    lport = lsock.getsockname()[1]
    out.write(f"Forwarding from 127.0.0.1:{lport} -> {remote}\n")
    out.flush()
    if args.once:
        _threading.Thread(target=relay_once, args=(lsock, relay),
                          kwargs={"accept_timeout": args.wait},
                          daemon=True).start()
    else:
        relay_once(lsock, relay)
    return 0


def cmd_patch(client, args, out):
    """kubectl patch <kind> <name> -p '<json>' — strategic-merge-lite:
    the server's merge-patch (pkg/kubectl/cmd/patch.go ->
    endpoints/handlers PatchResource)."""
    plural = _resolve_kind(args.kind)
    try:
        patch = json.loads(args.patch)
    except json.JSONDecodeError as e:
        print(f"error: invalid patch JSON: {e}", file=sys.stderr)
        return 1
    ns = args.namespace if scheme.is_namespaced(
        scheme.kind_for_plural(plural)) else ""
    obj = client.patch(plural, ns, args.name, patch)
    out.write(f"{plural}/{obj.metadata.name} patched\n")
    return 0


def cmd_annotate(client, args, out):
    """kubectl annotate <kind> <name> k=v ... k- — merge-patch on
    metadata.annotations; trailing '-' removes (cmd/annotate.go)."""
    plural = _resolve_kind(args.kind)
    ann = {}
    for kv in args.annotations:
        if kv.endswith("-") and "=" not in kv:
            ann[kv[:-1]] = None  # JSON merge-patch null deletes the key
        else:
            k, _, v = kv.partition("=")
            ann[k] = v
    ns = args.namespace if scheme.is_namespaced(
        scheme.kind_for_plural(plural)) else ""
    client.patch(plural, ns, args.name,
                 {"metadata": {"annotations": ann}})
    out.write(f"{plural}/{args.name} annotated\n")
    return 0


def cmd_edit(client, args, out):
    """kubectl edit <kind> <name> — dump to a temp file, run
    $KUBE_EDITOR/$EDITOR, apply the result as an update
    (cmd/editor/editoptions.go)."""
    import os
    import subprocess
    import tempfile

    import yaml

    plural = _resolve_kind(args.kind)
    ns = args.namespace if scheme.is_namespaced(
        scheme.kind_for_plural(plural)) else ""
    obj = client.get(plural, ns, args.name)
    before = yaml.safe_dump(scheme.encode_object(obj), sort_keys=False)
    editor = os.environ.get("KUBE_EDITOR") or os.environ.get("EDITOR")
    if not editor:
        print("error: set KUBE_EDITOR or EDITOR to edit", file=sys.stderr)
        return 1
    with tempfile.NamedTemporaryFile("w", suffix=".yaml",
                                     delete=False) as f:
        f.write(before)
        tmp = f.name
    try:
        rc = subprocess.call(f"{editor} {tmp}", shell=True)
        if rc != 0:
            print(f"error: editor exited {rc}; changes not applied",
                  file=sys.stderr)
            return 1
        after = open(tmp).read()
    finally:
        os.unlink(tmp)
    if after == before:
        out.write("Edit cancelled, no changes made.\n")
        return 0
    try:
        edited = scheme.decode_object(yaml.safe_load(after))
    except Exception as e:
        # a broken edited buffer (bad YAML, kind changed to something
        # unregistered) is a user error, not an internal traceback
        raise ManifestError(f"edited object is invalid: {e}") from e
    client.update(plural, edited)
    out.write(f"{plural}/{args.name} edited\n")
    return 0


def cmd_cp(client, args, out):
    """kubectl cp <pod>:<path> <localpath> (download) or
    <localpath> <pod>:<path> (upload) — over the exec subresource's
    cat / `sh -c 'cat > path'` with stdin (cmd/cp.go's tar pipe,
    collapsed to single files)."""
    def parse(spec):
        if ":" in spec and not spec.startswith("/") and "/" not in \
                spec.split(":", 1)[0]:
            pod, _, path = spec.partition(":")
            return pod, path
        return None, spec

    src_pod, src_path = parse(args.src)
    dst_pod, dst_path = parse(args.dst)
    if (src_pod is None) == (dst_pod is None):
        print("error: exactly one of src/dst must be pod:path",
              file=sys.stderr)
        return 1
    exec_path = client._path("pods", args.namespace,
                             src_pod or dst_pod, "exec")
    if src_pod is not None:  # download
        body = {"command": ["cat", src_path]}
        if args.container:
            body["container"] = args.container
        resp = client.request("POST", exec_path, body=body)
        if int(resp.get("exitCode", 1)) != 0:
            print(f"error: {resp.get('output')}", file=sys.stderr)
            return 1
        with open(args.dst, "w") as f:
            f.write(resp.get("output", ""))
    else:  # upload
        content = open(args.src).read()
        body = {"command": ["sh", "-c", f"cat > {dst_path}"],
                "stdin": content}
        if args.container:
            body["container"] = args.container
        resp = client.request("POST", exec_path, body=body)
        if int(resp.get("exitCode", 1)) != 0:
            print(f"error: {resp.get('output')}", file=sys.stderr)
            return 1
    return 0


def cmd_diff(client, args, out):
    """kubectl diff -f manifest — unified diff of live objects vs the
    manifest's desired state (pkg/kubectl/cmd/diff.go; server-side
    dry-run collapsed to a local object diff). Exit 1 when differences
    exist, like the reference."""
    import difflib

    import yaml

    changed = False
    for doc in load_manifests(args.filename):
        obj, kind = _decode_doc(doc)
        plural = scheme.plural_for_kind(kind)
        # namespace resolution MATCHES create/apply exactly (a non-
        # default -n overrides the manifest; comparing against a
        # namespace create never writes to would fabricate drift)
        if scheme.is_namespaced(kind):
            if args.namespace != "default":
                obj.metadata.namespace = args.namespace
            ns = obj.metadata.namespace
        else:
            ns = ""
        try:
            live = client.get(plural, ns, obj.metadata.name)
        except APIStatusError as e:
            if e.code != 404:
                raise
            out.write(f"--- (none)\n+++ {plural}/{obj.metadata.name} "
                      f"(created)\n")
            changed = True
            continue
        live_doc = scheme.encode_object(live)
        want_doc = scheme.encode_object(obj)

        # server-owned identity fields never diff — including in NESTED
        # metadata (pod templates get fresh uids on every decode)
        def scrub(node):
            # only METADATA dicts lose their server-owned identity
            # fields — a user label/annotation/data key happening to be
            # named "uid" is real content and must keep diffing
            if isinstance(node, dict):
                meta = node.get("metadata")
                if isinstance(meta, dict):
                    for k in ("resourceVersion", "uid", "generation"):
                        meta.pop(k, None)
                for v in node.values():
                    scrub(v)
            elif isinstance(node, list):
                for v in node:
                    scrub(v)

        # controller-owned status never diffs against a manifest's
        # desired state (the reference diffs only the spec'd object)
        live_doc.pop("status", None)
        want_doc.pop("status", None)
        scrub(live_doc)
        scrub(want_doc)
        a = yaml.safe_dump(live_doc, sort_keys=True).splitlines(True)
        b = yaml.safe_dump(want_doc, sort_keys=True).splitlines(True)
        delta = list(difflib.unified_diff(
            a, b, fromfile=f"live/{plural}/{obj.metadata.name}",
            tofile=f"manifest/{plural}/{obj.metadata.name}"))
        if delta:
            out.writelines(delta)
            changed = True
    return 1 if changed else 0


def _describe_pod(client, pod, out):
    out.write(f"Name:         {pod.metadata.name}\n")
    out.write(f"Namespace:    {pod.metadata.namespace}\n")
    out.write(f"Node:         {pod.spec.node_name or '<none>'}\n")
    out.write(f"Status:       {pod.status.phase or 'Pending'}\n")
    if pod.status.nominated_node_name:
        out.write(f"NominatedNodeName:  "
                  f"{pod.status.nominated_node_name}\n")
    if pod.metadata.labels:
        out.write("Labels:       " + ",".join(
            f"{k}={v}" for k, v in sorted(pod.metadata.labels.items()))
            + "\n")
    if pod.status.qos_class:
        out.write(f"QoS Class:    {pod.status.qos_class}\n")
    out.write("Containers:\n")
    for c in pod.spec.containers:
        out.write(f"  {c.name}:\n")
        out.write(f"    Image:  {c.image or '<none>'}\n")
        req = c.resources.requests
        if req:
            out.write("    Requests:  " + ", ".join(
                f"{k}={v}" for k, v in sorted(req.items())) + "\n")
    if pod.spec.volumes:
        out.write("Volumes:\n")
        for v in pod.spec.volumes:
            src = (f"PVC {v.pvc_name}" if v.pvc_name
                   else f"Secret {v.secret}" if v.secret
                   else f"ConfigMap {v.config_map}" if v.config_map
                   else "EmptyDir" if v.empty_dir else v.source_kind
                   or "other")
            out.write(f"  {v.name}: {src}\n")
    if pod.status.conditions:
        out.write("Conditions:\n")
        for t, s in pod.status.conditions:
            out.write(f"  {t}\t{s}\n")
    if pod.spec.tolerations:
        out.write("Tolerations:  " + "; ".join(
            f"{t.key or '<all>'}:{t.effect or '<all>'}"
            for t in pod.spec.tolerations) + "\n")


def _describe_node(client, node, out):
    """describe.go describeNode: conditions, capacity, and the
    allocated-resources table summed over non-terminated pods."""
    out.write(f"Name:         {node.metadata.name}\n")
    if node.metadata.labels:
        out.write("Labels:       " + ",".join(
            f"{k}={v}" for k, v in sorted(node.metadata.labels.items()))
            + "\n")
    out.write(f"Unschedulable: {node.spec.unschedulable}\n")
    if node.spec.taints:
        out.write("Taints:       " + "; ".join(
            f"{t.key}={t.value}:{t.effect}" for t in node.spec.taints)
            + "\n")
    out.write("Conditions:\n")
    for c in node.status.conditions:
        out.write(f"  {c.type}\t{c.status}\n")
    alloc = node.status.allocatable or {}
    if alloc:
        out.write("Allocatable:\n")
        for k, v in sorted(alloc.items()):
            out.write(f"  {k}: {v}\n")
    pods, _ = client.list("pods", None)
    mine = [p for p in pods if p.spec.node_name == node.metadata.name
            and p.status.phase not in ("Succeeded", "Failed")]
    out.write(f"Non-terminated Pods:  ({len(mine)} in total)\n")
    used: dict = {}
    for p in mine:
        out.write(f"  {p.metadata.namespace}/{p.metadata.name}\n")
        for k, v in api.get_resource_request(p).items():
            used[k] = used.get(k, 0) + v
    if used:
        out.write("Allocated resources:\n")
        for k, v in sorted(used.items()):
            pct = f" ({100 * v // alloc[k]}%)" if alloc.get(k) else ""
            out.write(f"  {k}: {v}{pct}\n")


def _describe_service(client, svc, out):
    out.write(f"Name:         {svc.metadata.name}\n")
    out.write(f"Type:         {svc.spec.type}\n")
    out.write(f"IP:           {svc.spec.cluster_ip or '<none>'}\n")
    if svc.spec.selector:
        out.write("Selector:     " + ",".join(
            f"{k}={v}" for k, v in sorted(svc.spec.selector.items()))
            + "\n")
    for p in svc.spec.ports:
        np = f"  NodePort: {p.node_port}" if p.node_port else ""
        out.write(f"Port:         {p.port}/{p.protocol} -> "
                  f"{p.target_port or p.port}{np}\n")
    try:
        ep = client.get("endpoints", svc.metadata.namespace,
                        svc.metadata.name)
        addrs = [f"{a.ip}" for ss in ep.subsets for a in ss.addresses]
        out.write(f"Endpoints:    {','.join(addrs) or '<none>'}\n")
    except APIStatusError:
        out.write("Endpoints:    <none>\n")


def _describe_deployment(client, dep, out):
    """describe.go DeploymentDescriber: replica rollup, strategy, the
    per-revision ReplicaSet table."""
    from ..controllers.deployment import REVISION_ANNOTATION

    st = dep.status
    out.write(f"Name:               {dep.metadata.name}\n")
    out.write(f"Replicas:           {dep.spec.replicas} desired | "
              f"{st.updated_replicas} updated | {st.replicas} total | "
              f"{st.ready_replicas} available | "
              f"{st.unavailable_replicas} unavailable\n")
    out.write(f"StrategyType:       {dep.spec.strategy.type}\n")
    if dep.spec.strategy.type == "RollingUpdate":
        out.write(f"RollingUpdateStrategy:  "
                  f"{dep.spec.strategy.max_unavailable} max unavailable, "
                  f"{dep.spec.strategy.max_surge} max surge\n")
    owned = sorted(_owned_replicasets(client, dep.metadata.namespace,
                                      dep.metadata.name),
                   key=lambda r: int(r.metadata.annotations.get(
                       REVISION_ANNOTATION, 0)))
    if owned:
        out.write("ReplicaSets:\n")
        for rs in owned:
            rev = rs.metadata.annotations.get(REVISION_ANNOTATION, "?")
            out.write(f"  {rs.metadata.name}\trevision={rev}\t"
                      f"{rs.status.ready_replicas}/{rs.spec.replicas} "
                      f"ready\n")


def _describe_revisioned(kind_label):
    """describe.go DaemonSetDescriber/StatefulSetDescriber: status
    rollup + the ControllerRevision history."""

    def describe(client, obj, out):
        st = obj.status
        out.write(f"Name:            {obj.metadata.name}\n")
        if kind_label == "DaemonSet":
            out.write(f"Desired Number of Nodes Scheduled: "
                      f"{st.desired_number_scheduled}\n")
            out.write(f"Current Number of Nodes Scheduled: "
                      f"{st.current_number_scheduled}\n")
            out.write(f"Number of Nodes Scheduled with Up-to-date Pods: "
                      f"{st.updated_number_scheduled}\n")
            out.write(f"Number of Nodes Misscheduled: "
                      f"{st.number_misscheduled}\n")
            out.write(f"Pods Status:  {st.number_ready} ready\n")
        else:
            out.write(f"Replicas:        {st.replicas} current / "
                      f"{obj.spec.replicas} desired\n")
            out.write(f"Update Strategy: "
                      f"{obj.spec.update_strategy.type}\n")
            if obj.spec.update_strategy.type == "RollingUpdate" and \
                    obj.spec.update_strategy.partition:
                out.write(f"  Partition:     "
                          f"{obj.spec.update_strategy.partition}\n")
            out.write(f"Pods Status:     {st.ready_replicas} ready / "
                      f"{st.updated_replicas} updated\n")
            if st.current_revision:
                out.write(f"Current Revision: {st.current_revision}\n")
            if st.update_revision and \
                    st.update_revision != st.current_revision:
                out.write(f"Update Revision:  {st.update_revision}\n")
        revs, _ = client.list("controllerrevisions",
                              obj.metadata.namespace)
        owned = sorted((r for r in revs
                        if any(o.controller and o.uid == obj.metadata.uid
                               for o in r.metadata.owner_references)),
                       key=lambda r: r.revision)
        if owned:
            out.write("Revisions:\n")
            for r in owned:
                out.write(f"  {r.revision}\t{r.metadata.name}\n")
    return describe


_DESCRIBERS = {"pods": _describe_pod, "nodes": _describe_node,
               "services": _describe_service,
               "deployments": _describe_deployment,
               "daemonsets": _describe_revisioned("DaemonSet"),
               "statefulsets": _describe_revisioned("StatefulSet")}


def cmd_describe(client, args, out):
    """Per-kind describers for the big three (pkg/printers/
    internalversion/describe.go describePod/describeNode/
    describeService); every other kind dumps YAML. Events always
    trail."""
    plural = _resolve_kind(args.kind)
    obj = client.get(plural, args.namespace, args.name)
    describer = _DESCRIBERS.get(plural)
    if describer is not None:
        describer(client, obj, out)
    else:
        _dump(obj, "yaml", out)
    evs, _ = client.list("events", args.namespace)
    related = [e for e in evs if e.involved_name == args.name]
    if related:
        out.write("Events:\n")
        for e in related:
            out.write(f"  {e.type}\t{e.reason}\tx{e.count}\t{e.message}\n")


def _kv_pairs(items, what):
    out = {}
    for kv in items or []:
        k, eq, v = kv.partition("=")
        if not eq:
            raise ManifestError(f"{what} needs KEY=VALUE, got {kv!r}")
        out[k] = v
    return out


def _file_pairs(items):
    import os

    out = {}
    for spec in items or []:
        key, eq, path = spec.partition("=")
        if not eq:
            key, path = os.path.basename(spec), spec
        try:
            with open(path) as f:
                out[key] = f.read()
        except (OSError, UnicodeDecodeError) as e:
            raise ManifestError(f"--from-file {path}: {e}") from e
    return out


def _create_generated(client, args, out):
    """`kubectl create <kind> NAME ...` generators
    (pkg/kubectl/cmd/create_*.go): build the object from flags instead
    of a manifest. Secret/ConfigMap values stay plain strings — this
    API's Secret.data convention (see controllers/bootstrap.py)."""
    gen, name, ns = args.gen, args.name, args.namespace
    if gen == "secret":
        # `kubectl create secret generic NAME`: the subtype word sits
        # between (create_secret.go); only the generic generator exists
        # here — tls/docker-registry need cert/registry machinery
        if name == "generic":
            name = args.extra_name
        elif name in ("tls", "docker-registry"):
            raise ManifestError(f"create secret {name} is not supported; "
                                f"use 'generic' or a manifest")
    if not name:
        raise ManifestError(f"create {gen} needs a NAME")
    data = dict(_kv_pairs(args.from_literal, "--from-literal"),
                **_file_pairs(args.from_file))
    meta = api.ObjectMeta(name=name, namespace=ns)
    if gen == "configmap":
        obj, plural = api.ConfigMap(metadata=meta, data=data), "configmaps"
    elif gen == "secret":
        obj, plural = api.Secret(metadata=meta, data=data,
                                 type=args.type), "secrets"
    elif gen == "namespace":
        obj, plural = api.Namespace(
            metadata=api.ObjectMeta(name=name)), "namespaces"
    elif gen == "serviceaccount":
        obj, plural = api.ServiceAccount(metadata=meta), "serviceaccounts"
    elif gen == "quota":
        from ..api.resources import parse_quantity

        hard = {}
        for kv in args.hard.split(",") if args.hard else []:
            k, eq, v = kv.partition("=")
            if not eq:
                raise ManifestError(f"--hard needs KEY=VALUE, got {kv!r}")
            hard[k] = parse_quantity(v)
        obj = api.ResourceQuota(metadata=meta,
                                spec=api.ResourceQuotaSpec(hard=hard))
        plural = "resourcequotas"
    elif gen == "priorityclass":
        obj = api.PriorityClass(metadata=api.ObjectMeta(name=name),
                                value=args.value,
                                global_default=args.global_default,
                                description=args.description)
        plural = "priorityclasses"
    elif gen == "deployment":
        if not args.image:
            raise ManifestError("create deployment needs --image")
        obj = api.Deployment(
            metadata=meta,
            spec=api.DeploymentSpec(
                replicas=args.replicas,
                selector=api.LabelSelector(match_labels={"app": name}),
                template=api.PodTemplateSpec(
                    metadata=api.ObjectMeta(labels={"app": name}),
                    spec=api.PodSpec(containers=[
                        api.Container(name=name, image=args.image)]))))
        plural = "deployments"
    elif gen == "job":
        if not args.image:
            raise ManifestError("create job needs --image")
        obj = api.Job(
            metadata=meta,
            spec=api.JobSpec(template=api.PodTemplateSpec(
                metadata=api.ObjectMeta(labels={"job-name": name}),
                spec=api.PodSpec(restart_policy="Never", containers=[
                    api.Container(name=name, image=args.image)]))))
        plural = "jobs"
    elif gen == "service":
        # create service clusterip|nodeport NAME --tcp=port[:target]
        if name in ("clusterip", "nodeport"):
            svc_type = {"clusterip": "ClusterIP",
                        "nodeport": "NodePort"}[name]
            name = args.extra_name
            if not name:
                raise ManifestError("create service needs a NAME")
        else:
            svc_type = "ClusterIP"
        ports = []
        for spec in args.tcp or []:
            port, _, target = spec.partition(":")
            ports.append(api.ServicePort(
                port=int(port), target_port=int(target or port),
                protocol="TCP"))
        obj = api.Service(
            metadata=api.ObjectMeta(name=name, namespace=ns),
            spec=api.ServiceSpec(selector={"app": name}, ports=ports,
                                 type=svc_type))
        plural = "services"
    elif gen in ("role", "clusterrole"):
        rule = api.RBACPolicyRule(
            verbs=args.rbac_verbs or [], resources=args.resource or [],
            api_groups=[""])
        if gen == "role":
            obj = api.Role(metadata=meta, rules=[rule])
        else:
            obj = api.ClusterRole(metadata=api.ObjectMeta(name=name),
                                  rules=[rule])
        plural = gen + "s"
    elif gen in ("rolebinding", "clusterrolebinding"):
        subjects = [api.RBACSubject(kind="User", name=u)
                    for u in args.user or []]
        for sa in args.serviceaccount or []:
            sns, colon, sname = sa.partition(":")
            if not colon or not sns or not sname:
                raise ManifestError(
                    f"--serviceaccount needs NAMESPACE:NAME, got {sa!r}")
            subjects.append(api.RBACSubject(
                kind="ServiceAccount", name=sname, namespace=sns))
        ref_kind = "ClusterRole" if args.clusterrole else "Role"
        ref_name = args.clusterrole or args.role
        if not ref_name:
            raise ManifestError(f"create {gen} needs --role/--clusterrole")
        if gen == "rolebinding":
            obj = api.RoleBinding(
                metadata=meta, subjects=subjects,
                role_ref=api.RoleRef(kind=ref_kind, name=ref_name))
        else:
            obj = api.ClusterRoleBinding(
                metadata=api.ObjectMeta(name=name), subjects=subjects,
                role_ref=api.RoleRef(kind="ClusterRole", name=ref_name))
        plural = gen + "s"
    elif gen == "poddisruptionbudget":
        obj = api.PodDisruptionBudget(
            metadata=meta,
            spec=api.PodDisruptionBudgetSpec(
                min_available=args.min_available,
                selector=api.LabelSelector(
                    match_labels=_kv_pairs(
                        (args.selector or "").split(",") if args.selector
                        else [], "--selector"))))
        plural = "poddisruptionbudgets"
    else:
        raise ManifestError(f"unknown create generator {gen!r}")
    if args.dry_run:
        out.write(f"{plural}/{obj.metadata.name} created (dry run)\n")
        return
    client.create(plural, obj)
    out.write(f"{plural}/{obj.metadata.name} created\n")


def cmd_create(client, args, out):
    if getattr(args, "gen", None):
        return _create_generated(client, args, out)
    if not args.filename:
        raise ManifestError("create requires -f FILENAME or a generator "
                            "(configmap, secret, namespace, ...)")
    for doc in load_manifests(args.filename,
                              recursive=getattr(args, "recursive", False)):
        obj, kind = _decode_doc(doc)
        plural = scheme.plural_for_kind(kind)
        if scheme.is_namespaced(kind) and args.namespace != "default":
            obj.metadata.namespace = args.namespace
        if args.dry_run:
            # client-side --dry-run (1.11 kubectl): decode + print, no
            # write; decoding already surfaced manifest errors
            out.write(f"{plural}/{obj.metadata.name} created (dry run)\n")
            continue
        client.create(plural, obj)
        if isinstance(obj, api.CustomResourceDefinition):
            scheme.register_dynamic(obj)  # later docs may use the kind
        out.write(f"{plural}/{obj.metadata.name} created\n")


LAST_APPLIED_ANNOTATION = "kubectl.kubernetes.io/last-applied-configuration"


def _mp_changes(live, new):
    """Adds/changes (NO deletion markers) taking `live` toward `new` —
    the three-way apply's 'revert drift on declared fields' half
    (reference CreateThreeWayJSONMergePatch diffs modified vs CURRENT).
    Lists replace wholesale; strategic merge keys are out of scope."""
    patch = {}
    for k, v in new.items():
        lv = live.get(k)
        if isinstance(v, dict) and isinstance(lv, dict):
            sub = _mp_changes(lv, v)
            if sub:
                patch[k] = sub
        elif lv != v:
            patch[k] = v
    return patch


def _mp_deletions(last, new):
    """Null markers for keys the PREVIOUS apply declared and this
    manifest dropped — the only deletions apply may make."""
    patch = {}
    for k, lv in last.items():
        if k not in new:
            patch[k] = None
        elif isinstance(lv, dict) and isinstance(new.get(k), dict):
            sub = _mp_deletions(lv, new[k])
            if sub:
                patch[k] = sub
    return patch


def _merge_dicts(a, b):
    for k, v in b.items():
        if isinstance(v, dict) and isinstance(a.get(k), dict):
            _merge_dicts(a[k], v)
        else:
            a[k] = v
    return a


def cmd_apply(client, args, out):
    """Three-way apply (pkg/kubectl/cmd/apply.go): merge what the
    MANIFEST declares into the live object, delete only the fields the
    PREVIOUS apply declared and this one dropped (the
    last-applied-configuration annotation), and leave every field other
    actors own — status, scheduler/controller writes, out-of-band
    labels — untouched.

    Subcommands (pkg/kubectl/cmd/apply_view_last_applied.go /
    apply_set_last_applied.go): view-last-applied prints the stored
    annotation; set-last-applied rewrites it from a manifest WITHOUT
    touching the live spec (the migration tool for adopting objects
    into apply management)."""
    action = getattr(args, "action", None)
    if action == "view-last-applied":
        plural = _resolve_kind(args.kind)
        cur = client.get(plural, args.namespace, args.name)
        last = (cur.metadata.annotations or {}).get(LAST_APPLIED_ANNOTATION)
        if not last:
            raise ManifestError(
                f"no last-applied-configuration annotation found on "
                f"{plural}/{args.name}")
        out.write(json.dumps(json.loads(last), indent=2) + "\n")
        return
    if action == "set-last-applied":
        if not args.filename:
            raise ManifestError("apply set-last-applied requires -f")
        for doc in load_manifests(args.filename):
            obj, kind = _decode_doc(doc)
            plural = scheme.plural_for_kind(kind)
            # namespace resolution must MATCH plain apply's (-n wins
            # over the manifest) or the annotation lands on a different
            # object than the one apply manages
            ns = obj.metadata.namespace
            if scheme.is_namespaced(kind) and args.namespace != "default":
                ns = args.namespace
            client.patch(plural, ns, obj.metadata.name,
                         {"metadata": {"annotations": {
                             LAST_APPLIED_ANNOTATION:
                                 json.dumps(doc, sort_keys=True)}}})
            out.write(f"{plural}/{obj.metadata.name} configured\n")
        return
    if not args.filename:
        raise ManifestError("apply requires -f FILENAME")
    applied: set = set()
    for doc in load_manifests(args.filename,
                              recursive=getattr(args, "recursive", False)):
        obj, kind = _decode_doc(doc)
        plural = scheme.plural_for_kind(kind)
        if scheme.is_namespaced(kind) and args.namespace != "default":
            obj.metadata.namespace = args.namespace
            doc.setdefault("metadata", {})["namespace"] = args.namespace
        applied.add((plural, obj.metadata.namespace, obj.metadata.name))
        try:
            cur = client.get(plural, obj.metadata.namespace,
                             obj.metadata.name)
        except APIStatusError as e:
            if e.code != 404:
                raise
            if args.dry_run:
                out.write(f"{plural}/{obj.metadata.name} created "
                          f"(dry run)\n")
                continue
            obj.metadata.annotations = dict(obj.metadata.annotations or {})
            obj.metadata.annotations[LAST_APPLIED_ANNOTATION] = \
                json.dumps(doc, sort_keys=True)
            client.create(plural, obj)
            out.write(f"{plural}/{obj.metadata.name} created\n")
            if isinstance(obj, api.CustomResourceDefinition):
                scheme.register_dynamic(obj)  # later docs may use the kind
            continue
        live_doc = scheme.encode_object(cur)
        try:
            last = json.loads((cur.metadata.annotations or {}).get(
                LAST_APPLIED_ANNOTATION, "{}"))
        except json.JSONDecodeError:
            last = {}
        # three-way patch: deletions from (last -> manifest), adds/
        # changes from (LIVE -> manifest) so out-of-band drift on
        # declared fields is reverted; sent through the server's PATCH
        # so the merge happens atomically under the server's lock (and
        # the null-stripping lives in ONE place, the server)
        patch = _merge_dicts(_mp_deletions(last, doc),
                             _mp_changes(live_doc, doc))
        _merge_dicts(patch, {"metadata": {"annotations": {
            LAST_APPLIED_ANNOTATION: json.dumps(doc, sort_keys=True)}}})
        if args.dry_run:
            out.write(f"{plural}/{obj.metadata.name} configured "
                      f"(dry run)\n")
            continue
        client.patch(plural, obj.metadata.namespace, obj.metadata.name,
                     patch)
        out.write(f"{plural}/{obj.metadata.name} configured\n")
        if isinstance(obj, api.CustomResourceDefinition):
            scheme.register_dynamic(obj)
    if args.prune:
        _apply_prune(client, args, applied, out)


# the reference's default prune whitelist (apply.go prune.go
# pruneResources): the workload + config kinds apply typically manages
PRUNE_WHITELIST = ("configmaps", "secrets", "services", "endpoints",
                   "persistentvolumeclaims", "pods",
                   "replicationcontrollers", "deployments", "replicasets",
                   "statefulsets", "daemonsets", "jobs", "cronjobs")


def _apply_prune(client, args, applied: set, out):
    """apply --prune -l SELECTOR (pkg/kubectl/cmd/apply.go prune):
    delete objects that (a) match the selector, (b) carry the
    last-applied annotation (so only apply-managed objects are ever
    pruned), and (c) are absent from this apply's manifest set."""
    if not args.selector:
        raise ManifestError("--prune requires -l (a label selector "
                            "scoping what this apply owns)")
    # prune everywhere this apply touched, not just -n: a manifest may
    # declare its own metadata.namespace (the reference prunes across
    # every namespace the apply visited)
    namespaces = {args.namespace} | {ns for _, ns, _ in applied if ns}
    for plural in PRUNE_WHITELIST:
        for ns in sorted(namespaces):
            try:
                objs, _ = client.list(plural, ns,
                                      label_selector=args.selector)
            except APIStatusError:
                continue
            for o in objs:
                key = (plural, o.metadata.namespace, o.metadata.name)
                if key in applied:
                    continue
                if LAST_APPLIED_ANNOTATION not in (o.metadata.annotations
                                                   or {}):
                    continue
                if args.dry_run:
                    out.write(f"{plural}/{o.metadata.name} pruned "
                              f"(dry run)\n")
                    continue
                client.delete(plural, o.metadata.namespace,
                              o.metadata.name)
                out.write(f"{plural}/{o.metadata.name} pruned\n")


def cmd_delete(client, args, out):
    plural = _resolve_kind(args.kind)
    # delete.go grace handling: --now = 1s, --force = 0 (immediate),
    # --grace-period=N explicit; conflicting combinations are ERRORS
    # (delete.go: "--force and --grace-period > 0 cannot be specified
    # together"), never silent overrides
    grace = getattr(args, "grace_period", None)
    force = getattr(args, "force", False)
    now_flag = getattr(args, "now", False)
    if now_flag and grace is not None:
        raise SystemExit("error: --now and --grace-period cannot be "
                         "specified together")
    if now_flag:
        grace = 1  # resolved first, like delete.go, so --force errors
    if force and grace is not None and grace > 0:
        raise SystemExit("error: --force and --grace-period > 0 cannot "
                         "be specified together")
    if force:
        grace = 0
    if args.name:
        client.delete(plural, args.namespace, args.name,
                      grace_period_seconds=grace)
        out.write(f"{plural}/{args.name} deleted\n")
        return
    sel, fsel = _parse_selector_flags(args)
    if not sel and not fsel:
        raise ManifestError("delete needs a name or -l/--field-selector")
    objs, _ = client.list(plural, args.namespace, label_selector=sel,
                          field_selector=fsel)
    for o in objs:
        client.delete(plural, o.metadata.namespace or args.namespace,
                      o.metadata.name, grace_period_seconds=grace)
        out.write(f"{plural}/{o.metadata.name} deleted\n")


def _plugin_dirs():
    import os

    env = os.environ.get("KUBECTL_PLUGINS_PATH", "")
    if env:
        return [d for d in env.split(os.pathsep) if d]
    return [os.path.expanduser("~/.kube/plugins")]


def _load_plugins():
    """pkg/kubectl/plugins/loader.go: every subdirectory of the plugin
    path carrying a plugin.yaml descriptor (name, shortDesc, command)
    is a runnable plugin."""
    import os

    import yaml

    found = {}
    for root in _plugin_dirs():
        if not os.path.isdir(root):
            continue
        for entry in sorted(os.listdir(root)):
            desc_path = os.path.join(root, entry, "plugin.yaml")
            if not os.path.isfile(desc_path):
                continue
            try:
                with open(desc_path) as f:
                    desc = yaml.safe_load(f) or {}
            except (OSError, yaml.YAMLError):
                continue
            name = desc.get("name") or entry
            if name not in found and desc.get("command"):
                desc["_dir"] = os.path.join(root, entry)
                found[name] = desc
    return found


def cmd_plugin(client, args, out):
    """kubectl plugin [NAME [args...]] — the 1.11 plugin mechanism
    (pkg/kubectl/plugins/runner.go): the descriptor's command runs with
    the KUBECTL_PLUGINS_* environment describing the caller, current
    namespace, and the plugin's own descriptor."""
    import os
    import subprocess
    import sys

    plugins = _load_plugins()
    if not args.plugin_name:
        if not plugins:
            out.write("No plugins installed.\n")
            return
        out.write("Available plugins:\n")
        for name, desc in sorted(plugins.items()):
            out.write(f"  {name}\t{desc.get('shortDesc', '')}\n")
        return
    desc = plugins.get(args.plugin_name)
    if desc is None:
        raise SystemExit(f"error: plugin {args.plugin_name!r} not found "
                         f"in {os.pathsep.join(_plugin_dirs())}")
    import shlex

    env = dict(os.environ)
    env.update({
        "KUBECTL_PLUGINS_CALLER": sys.argv[0],
        "KUBECTL_PLUGINS_CURRENT_NAMESPACE": args.namespace,
        "KUBECTL_PLUGINS_DESCRIPTOR_NAME": desc.get("name", ""),
        "KUBECTL_PLUGINS_DESCRIPTOR_SHORT_DESC": desc.get("shortDesc", ""),
        "KUBECTL_PLUGINS_DESCRIPTOR_COMMAND": desc.get("command", ""),
    })
    # shlex: a quoted path or argument with spaces survives
    # (divergence, noted: output is captured, not streamed — an
    # interactive plugin prompting on stdout won't show its prompt)
    # DESCRIPTOR tokens that name files shipped with the plugin resolve
    # against the plugin dir ('bash run.sh', 'python -u hello.py'); the
    # child still runs in the CALLER's cwd (reference runner semantics:
    # file-producing plugins write where the user invoked kubectl).
    # USER arguments are never rewritten — 'process.py' on the command
    # line means the user's file, even if the plugin ships one.
    desc_tokens = shlex.split(desc["command"])
    for i, tok in enumerate(desc_tokens):
        cand = os.path.join(desc["_dir"], tok)
        if not os.path.isabs(tok) and os.path.isfile(cand):
            desc_tokens[i] = cand
    argv = desc_tokens + list(args.plugin_args or [])
    try:
        proc = subprocess.run(argv, env=env, capture_output=True,
                              text=True)
    except (FileNotFoundError, PermissionError) as e:
        raise SystemExit(f"error: unable to run plugin "
                         f"{args.plugin_name!r}: {e}")
    out.write(proc.stdout)
    if proc.stderr:
        out.write(proc.stderr)  # warnings survive success too
    return proc.returncode


def cmd_scale(client, args, out):
    """scale.go: go through the polymorphic /scale subresource when the
    kind serves one (incl. CRDs declaring subresources.scale); fall back
    to a spec.replicas update for kinds without it (jobs)."""
    plural = _resolve_kind(args.kind)
    try:
        client.update_scale(plural, args.namespace, args.name,
                            args.replicas)
    except APIStatusError as e:
        if e.code != 404:
            raise
        obj = client.get(plural, args.namespace, args.name)
        if plural == "jobs":
            # ScalePrecondition for jobs targets spec.parallelism
            # (kubectl scale.go JobPsuedoScaler)
            obj.spec.parallelism = args.replicas
        else:
            obj.spec.replicas = args.replicas
        client.update(plural, obj)
    out.write(f"{plural}/{args.name} scaled to {args.replicas}\n")


def _set_unschedulable(client, name: str, value: bool):
    node = client.get("nodes", None, name)
    node.spec.unschedulable = value
    client.update("nodes", node)


def cmd_cordon(client, args, out):
    _set_unschedulable(client, args.name, True)
    out.write(f"node/{args.name} cordoned\n")


def cmd_uncordon(client, args, out):
    _set_unschedulable(client, args.name, False)
    out.write(f"node/{args.name} uncordoned\n")


def cmd_drain(client, args, out):
    """Cordon + evict all pods on the node (kubectl drain; uses the
    eviction subresource so PDBs are honored)."""
    _set_unschedulable(client, args.name, True)
    pods, _ = client.list("pods")
    for p in pods:
        if p.spec.node_name != args.name:
            continue
        try:
            client.evict(p.metadata.namespace, p.metadata.name)
            out.write(f"pod/{p.metadata.name} evicted\n")
        except APIStatusError as e:
            out.write(f"pod/{p.metadata.name} eviction blocked: {e}\n")
    out.write(f"node/{args.name} drained\n")


def cmd_label(client, args, out):
    plural = _resolve_kind(args.kind)
    obj = client.get(plural, args.namespace, args.name)
    for kv in args.labels:
        if kv.endswith("-"):
            obj.metadata.labels.pop(kv[:-1], None)
        else:
            k, _, v = kv.partition("=")
            obj.metadata.labels[k] = v
    client.update(plural, obj)
    out.write(f"{plural}/{args.name} labeled\n")


def cmd_version(client, args, out):
    v = client.request("GET", "/version")
    out.write(f"Server Version: {v.get('gitVersion')}\n")


# -- rollout (pkg/kubectl/cmd/rollout/) ---------------------------------------


def _owned_replicasets(client, namespace, dep_name):
    """The ReplicaSets a Deployment controller-owns — THE ownership
    predicate, shared by rollout and describe."""
    rss, _ = client.list("replicasets", namespace)
    return [rs for rs in rss
            if any(r.controller and r.kind == "Deployment"
                   and r.name == dep_name
                   for r in rs.metadata.owner_references)]


def _deployment_and_rss(client, args):
    dep = client.get("deployments", args.namespace, args.name)
    return dep, _owned_replicasets(client, args.namespace,
                                   dep.metadata.name)


def _print_template(tmpl_wire: dict, out):
    """history.go printTemplate: labels + per-container image/ports."""
    labels = (tmpl_wire.get("metadata") or {}).get("labels") or {}
    if labels:
        out.write("  Labels:\t" + ",".join(
            f"{k}={v}" for k, v in sorted(labels.items())) + "\n")
    out.write("  Containers:\n")
    for c in (tmpl_wire.get("spec") or {}).get("containers") or []:
        out.write(f"   {c.get('name', '?')}:\n")
        out.write(f"    Image:\t{c.get('image', '<none>')}\n")
        ports = [str(p.get("containerPort"))
                 for p in c.get("ports") or []]
        if ports:
            out.write(f"    Ports:\t{','.join(ports)}\n")


def _rollout_revisioned(client, args, out, plural):
    """rollout history/undo/status for ControllerRevision-backed kinds
    (pkg/kubectl/history.go DaemonSetHistoryViewer:154 /
    StatefulSetHistoryViewer:205, rollback.go DaemonSetRollbacker:198):
    history lists the owned ControllerRevisions; undo splices the target
    revision's template snapshot back into the workload spec."""
    from ..api import scheme as _scheme
    from ..api import types as _api

    kind = "daemonset" if plural == "daemonsets" else "statefulset"
    obj = client.get(plural, args.namespace, args.name)
    if obj is None:
        raise SystemExit(f"error: {kind} {args.name!r} not found")
    revs, _ = client.list("controllerrevisions", args.namespace)
    owned = sorted(
        (r for r in revs
         if any(o.controller and o.uid == obj.metadata.uid
                for o in r.metadata.owner_references)),
        key=lambda r: r.revision)
    name = obj.metadata.name
    if args.action == "history":
        if getattr(args, "revision", 0):
            # history --revision=N: the revision's template detail
            # (history.go printTemplate via the HistoryViewer)
            target = next((r for r in owned
                           if r.revision == args.revision), None)
            if target is None:
                raise SystemExit(
                    f"error: revision {args.revision} not found")
            out.write(f"{kind}.apps/{name} with revision "
                      f"#{args.revision}\nPod Template:\n")
            _print_template(target.data["spec"]["template"], out)
            return
        out.write(f"{kind}.apps/{name}\nREVISION\n")
        for r in owned:
            out.write(f"{r.revision}\n")
    elif args.action == "undo":
        if args.to_revision:
            target = next((r for r in owned
                           if r.revision == int(args.to_revision)), None)
            if target is None:
                raise SystemExit(
                    f"error: revision {args.to_revision} not found")
        else:
            if len(owned) < 2:
                raise SystemExit("error: no rollout history found")
            target = owned[-2]
        tmpl = _scheme.decode(_api.PodTemplateSpec,
                              target.data["spec"]["template"])
        obj.spec.template = tmpl
        client.update(plural, obj)
        out.write(f"{kind}.apps/{name} rolled back to revision "
                  f"{target.revision}\n")
    elif args.action == "status":
        st = obj.status
        # rollout_status.go: progress is only defined for RollingUpdate
        if obj.spec.update_strategy.type != "RollingUpdate":
            raise SystemExit(
                "error: rollout status is only available for RollingUpdate "
                "strategy type")
        # rollout_status.go gates on status.observedGeneration >=
        # metadata.generation — status counts are stale until the
        # controller has synced the current spec
        if st.observed_generation < obj.metadata.generation:
            out.write(f"Waiting for {kind} spec update to be observed...\n")
            return
        if plural == "daemonsets":
            want = st.desired_number_scheduled
            if st.updated_number_scheduled < want:
                out.write(f"Waiting for daemon set \"{name}\" rollout to "
                          f"finish: {st.updated_number_scheduled} out of "
                          f"{want} new pods have been updated...\n")
            elif st.number_ready < want:
                out.write(f"Waiting for daemon set \"{name}\" rollout to "
                          f"finish: {st.number_ready} of {want} updated "
                          f"pods are available...\n")
            else:
                out.write(f'daemon set "{name}" successfully rolled out\n')
        else:
            want = obj.spec.replicas
            partition = obj.spec.update_strategy.partition
            if partition > 0:
                # rollout_status.go StatefulSetStatusViewer: a
                # partitioned rollout is complete once every ordinal at
                # or above the partition serves the update revision
                if st.updated_replicas < want - partition:
                    out.write(f"Waiting for partitioned roll out to "
                              f"finish: {st.updated_replicas} out of "
                              f"{want - partition} new pods have been "
                              f"updated...\n")
                else:
                    out.write(f"partitioned roll out complete: "
                              f"{st.updated_replicas} new pods have been "
                              f"updated...\n")
            elif st.updated_replicas < want or \
                    st.current_revision != st.update_revision:
                out.write(f"Waiting for statefulset rolling update to "
                          f"complete {st.updated_replicas} pods at revision "
                          f"{st.update_revision}...\n")
            else:
                out.write(f"statefulset rolling update complete "
                          f"{st.updated_replicas} pods at revision "
                          f"{st.update_revision}...\n")
    else:
        raise SystemExit(
            f"error: rollout {args.action!r} not supported for {kind}")


def cmd_rollout(client, args, out):
    from ..controllers.deployment import (HASH_LABEL, REVISION_ANNOTATION,
                                          template_hash)

    plural = _resolve_kind(args.kind)
    if plural in ("daemonsets", "statefulsets"):
        return _rollout_revisioned(client, args, out, plural)
    if plural != "deployments":
        raise SystemExit(
            "error: rollout supports deployments, daemonsets, statefulsets")
    dep, owned = _deployment_and_rss(client, args)
    name = dep.metadata.name
    if args.action == "status":
        # rollout_status.go Status: updated/total/available counts. Gate
        # on the controller having OBSERVED this template first
        # (observedGeneration analog): status counts are stale until an
        # RS for the current template hash exists
        cur_hash = template_hash(dep.spec.template)
        if not any((rs.metadata.labels or {}).get(HASH_LABEL) == cur_hash
                   for rs in owned):
            out.write("Waiting for deployment spec update to be "
                      "observed...\n")
            return
        want = dep.spec.replicas
        st = dep.status
        if st.updated_replicas < want:
            out.write(f"Waiting for rollout to finish: {st.updated_replicas} "
                      f"out of {want} new replicas have been updated...\n")
        elif st.ready_replicas < want:
            out.write(f"Waiting for rollout to finish: {st.ready_replicas} "
                      f"of {want} updated replicas are available...\n")
        else:
            out.write(f'deployment "{name}" successfully rolled out\n')
    elif args.action == "history":
        if getattr(args, "revision", 0):
            target = next(
                (rs for rs in owned if rs.metadata.annotations.get(
                    REVISION_ANNOTATION) == str(args.revision)), None)
            if target is None:
                raise SystemExit(
                    f"error: revision {args.revision} not found")
            from ..api import scheme as _scheme
            out.write(f"deployment.apps/{name} with revision "
                      f"#{args.revision}\nPod Template:\n")
            _print_template(_scheme.encode(target.spec.template), out)
            return
        out.write(f"deployment.apps/{name}\nREVISION\tREPLICASETS\n")
        for rs in sorted(owned, key=lambda r: int(
                r.metadata.annotations.get(REVISION_ANNOTATION, 0))):
            rev = rs.metadata.annotations.get(REVISION_ANNOTATION, "?")
            out.write(f"{rev}\t{rs.metadata.name}\n")
    elif args.action == "undo":
        # rollback.go: resolve the target revision's RS, copy its template
        # (minus the hash label) into the deployment spec
        target = None
        if args.to_revision:
            target = next(
                (rs for rs in owned if rs.metadata.annotations.get(
                    REVISION_ANNOTATION) == str(args.to_revision)), None)
            if target is None:
                raise SystemExit(
                    f"error: revision {args.to_revision} not found")
        else:
            cur_hash = template_hash(dep.spec.template)
            olds = [rs for rs in owned
                    if (rs.metadata.labels or {}).get(HASH_LABEL) != cur_hash]
            if not olds:
                raise SystemExit("error: no rollout history found")
            target = max(olds, key=lambda r: int(
                r.metadata.annotations.get(REVISION_ANNOTATION, 0)))
        import copy

        tmpl = copy.deepcopy(target.spec.template)
        tmpl.metadata.labels = {k: v for k, v in
                                (tmpl.metadata.labels or {}).items()
                                if k != HASH_LABEL}
        dep.spec.template = tmpl
        client.update("deployments", dep)
        rev = target.metadata.annotations.get(REVISION_ANNOTATION, "?")
        out.write(f"deployment.apps/{name} rolled back to revision {rev}\n")
    elif args.action in ("pause", "resume"):
        dep.spec.paused = (args.action == "pause")
        client.update("deployments", dep)
        out.write(f"deployment.apps/{name} {args.action}d\n")
    else:
        raise SystemExit(f"error: unknown rollout action {args.action!r}")


def cmd_expose(client, args, out):
    """expose.go: create a Service selecting the workload's pods."""
    plural = _resolve_kind(args.kind)
    obj = client.get(plural, args.namespace, args.name)
    sel = obj.spec.selector
    if sel is None:
        raise SystemExit(f"error: {args.kind}/{args.name} has no selector")
    if hasattr(sel, "match_labels"):  # LabelSelector -> plain dict
        if sel.match_expressions:
            raise SystemExit("error: cannot expose set-based selectors")
        sel = dict(sel.match_labels)
    svc = api.Service(
        metadata=api.ObjectMeta(name=args.service_name or args.name,
                                namespace=args.namespace),
        spec=api.ServiceSpec(
            selector=sel, type=args.type,
            ports=[api.ServicePort(port=args.port,
                                   target_port=args.target_port or args.port)]))
    client.create("services", svc)
    out.write(f"service/{svc.metadata.name} exposed\n")


def cmd_top(client, args, out):
    """top.go: resource usage from the metrics API (metrics-server's
    PodMetrics objects; node usage aggregates its pods')."""
    from ..api import resources as res

    what = _resolve_kind(args.kind)

    def cpu_mem(m):
        return (m.usage.get(res.CPU, 0), m.usage.get(res.MEMORY, 0))

    def table(rows):
        _write_table(["NAME", "CPU(m)", "MEMORY(Mi)"], rows, out)

    if what == "pods":
        # namespace-scoped, like the real kubectl top pods
        metrics, _ = client.list("podmetrics", args.namespace)
        table([[m.metadata.name, str(cpu_mem(m)[0]),
                str(cpu_mem(m)[1] // (1 << 20))]
               for m in sorted(metrics, key=lambda m: m.metadata.name)])
    elif what == "nodes":
        metrics, _ = client.list("podmetrics", None)
        pods, _ = client.list("pods", None)
        # key by (namespace, name): same-named pods in different
        # namespaces must not collide
        node_of = {(p.metadata.namespace, p.metadata.name): p.spec.node_name
                   for p in pods}
        agg = {}
        for m in metrics:
            node = node_of.get((m.metadata.namespace, m.metadata.name), "")
            if node:
                cpu0, mem0 = agg.get(node, (0, 0))
                cpu, mem = cpu_mem(m)
                agg[node] = (cpu0 + cpu, mem0 + mem)
        rows = []
        for node in sorted(n.metadata.name for n in
                           client.list("nodes", None)[0]):
            cpu, mem = agg.get(node, (0, 0))
            rows.append([node, str(cpu), str(mem // (1 << 20))])
        table(rows)
    else:
        raise SystemExit("error: top supports pods or nodes")


def cmd_explain(client, args, out):
    """explain.go against the dataclass model instead of OpenAPI: field
    names + types of the resource's Python type."""
    import dataclasses
    import typing

    plural = _resolve_kind(args.kind.split(".")[0])
    kind = scheme.kind_for_plural(plural)
    typ = scheme.type_for_kind(kind)
    path = args.kind.split(".")[1:]
    for seg in path:
        hints = typing.get_type_hints(typ)
        if seg not in hints:
            raise SystemExit(f"error: field {seg!r} not found in {kind}")
        t = hints[seg]
        origin = typing.get_origin(t)
        if origin in (list, dict):
            t = typing.get_args(t)[-1]
        elif origin is typing.Union:  # Optional[X]
            t = next(a for a in typing.get_args(t) if a is not type(None))
        typ = t
    out.write(f"KIND: {kind}\nFIELDS ({typ.__name__}):\n")
    if dataclasses.is_dataclass(typ):
        for f in dataclasses.fields(typ):
            out.write(f"  {f.name}\t<{getattr(f.type, '__name__', f.type)}>\n")
    else:
        out.write(f"  <{typ.__name__}> (scalar)\n")


def cmd_taint(client, args, out):
    """taint.go: `kubectl taint nodes <name> key=value:Effect` adds (or
    updates) a taint; a trailing '-' (key:Effect- or key-) removes."""
    if _resolve_kind(args.kind) != "nodes":
        raise SystemExit("error: taint supports nodes")
    node = client.get("nodes", None, args.name)
    taints = list(node.spec.taints)
    for spec in args.taints:
        if spec.endswith("-"):
            body = spec[:-1]
            key, _, effect = body.partition(":")
            key, _, _ = key.partition("=")
            before = len(taints)
            taints = [t for t in taints
                      if not (t.key == key
                              and (not effect or t.effect == effect))]
            if len(taints) == before:
                raise SystemExit(f"error: taint {key!r} not found")
        else:
            kv, sep, effect = spec.rpartition(":")
            if not sep or effect not in (api.NO_SCHEDULE,
                                         api.PREFER_NO_SCHEDULE,
                                         api.NO_EXECUTE):
                raise SystemExit(
                    f"error: taint {spec!r} must be key[=value]:Effect "
                    f"(NoSchedule|PreferNoSchedule|NoExecute)")
            key, _, value = kv.partition("=")
            # replace an existing taint with the same key+effect
            # (reference updates in place rather than duplicating)
            taints = [t for t in taints
                      if not (t.key == key and t.effect == effect)]
            taints.append(api.Taint(key=key, value=value, effect=effect))
    node.spec.taints = taints
    client.update("nodes", node)
    out.write(f"node/{args.name} tainted\n")


def cmd_run(client, args, out):
    """run.go (1.11 semantics): --restart=Always -> Deployment (the
    deprecated-but-default generator), OnFailure -> Job, Never -> Pod."""
    labels = {"run": args.name}
    tmpl = api.PodTemplateSpec(
        metadata=api.ObjectMeta(labels=dict(labels)),
        spec=api.PodSpec(restart_policy=args.restart,
                         containers=[api.Container(name=args.name,
                                                   image=args.image)]))
    meta = api.ObjectMeta(name=args.name, namespace=args.namespace,
                          labels=dict(labels))
    if args.restart == "Always":
        obj = api.Deployment(metadata=meta, spec=api.DeploymentSpec(
            replicas=args.replicas,
            selector=api.LabelSelector(match_labels=dict(labels)),
            template=tmpl))
        client.create("deployments", obj)
        out.write(f"deployment.apps/{args.name} created\n")
    elif args.restart == "OnFailure":
        obj = api.Job(metadata=meta, spec=api.JobSpec(
            selector=api.LabelSelector(match_labels=dict(labels)),
            template=tmpl))
        client.create("jobs", obj)
        out.write(f"job.batch/{args.name} created\n")
    else:  # Never — same template, just not wrapped in a controller
        pod = api.Pod(metadata=meta, spec=tmpl.spec)
        client.create("pods", pod)
        out.write(f"pod/{args.name} created\n")


def cmd_replace(client, args, out):
    """replace.go: full update from the manifest (PUT semantics; the
    live resourceVersion is carried over so the write is a plain update,
    not a CAS failure)."""
    for doc in load_manifests(args.filename):
        obj, kind = _decode_doc(doc)
        plural = scheme.plural_for_kind(kind)
        if scheme.is_namespaced(kind) and args.namespace != "default":
            obj.metadata.namespace = args.namespace
        live = client.get(plural, obj.metadata.namespace, obj.metadata.name)
        obj.metadata.resource_version = live.metadata.resource_version
        obj.metadata.uid = live.metadata.uid
        client.update(plural, obj)
        out.write(f"{plural}/{obj.metadata.name} replaced\n")


def cmd_autoscale(client, args, out):
    """autoscale.go: create an HPA targeting the workload."""
    plural = _resolve_kind(args.kind)
    obj = client.get(plural, args.namespace, args.name)  # must exist
    kind = scheme.kind_for_plural(plural)
    hpa = api.HorizontalPodAutoscaler(
        metadata=api.ObjectMeta(name=args.name, namespace=args.namespace),
        spec=api.HorizontalPodAutoscalerSpec(
            scale_target_ref=api.CrossVersionObjectReference(
                kind=kind, name=obj.metadata.name),
            min_replicas=args.min, max_replicas=args.max,
            target_cpu_utilization_percentage=args.cpu_percent))
    client.create("horizontalpodautoscalers", hpa)
    out.write(f"horizontalpodautoscaler.autoscaling/{args.name} "
              f"autoscaled\n")


def cmd_certificate(client, args, out):
    """certificates.go: approve/deny a CSR by appending the condition
    the signing controller consumes (status subresource write)."""
    csr = client.get("certificatesigningrequests", None, args.name)
    cond = ("Approved", "KubectlApprove") if args.action == "approve" \
        else ("Denied", "KubectlDeny")
    # approve and deny are mutually exclusive: the signer gates on
    # csr.approved only, so a stale Approved alongside a new Denied
    # would still get the CSR signed
    drop = "Denied" if args.action == "approve" else "Approved"
    csr.status.conditions = [c for c in csr.status.conditions
                             if c[0] != drop]
    if cond not in csr.status.conditions:
        csr.status.conditions.append(cond)
    client.update("certificatesigningrequests", csr, sub="status")
    out.write(f"certificatesigningrequest.certificates.k8s.io/{args.name} "
              f"{args.action}d\n")


def cmd_auth(client, args, out):
    """auth/cani.go: POST a SelfSubjectAccessReview and report. Exit
    code 0 = allowed, 1 = denied (like the reference with --quiet off
    it prints yes/no; the exit code contract comes from cani.go
    RunAccessCheck)."""
    if args.action != "can-i":
        raise SystemExit("error: auth supports can-i")
    resource = args.resource
    if args.subresource:
        resource = f"{resource}/{args.subresource}"
    base, _, sub = resource.partition("/")
    plural = _resolve_kind(base)
    # cluster-scoped resources authorize with no namespace (the server's
    # dispatch only sets a namespace from a /namespaces/ path segment) —
    # stamping 'default' here would let a namespaced RoleBinding answer
    # 'yes' for a request that will actually be evaluated cluster-wide
    ns = (args.namespace
          if scheme.is_namespaced(scheme.kind_for_plural(plural)) else None)
    review = api.SelfSubjectAccessReview(
        spec=api.SelfSubjectAccessReviewSpec(
            resource_attributes=api.ResourceAttributes(
                verb=args.auth_verb,
                resource=plural + (f"/{sub}" if sub else ""),
                namespace=ns, name=args.resource_name or None)))
    created = client.create("selfsubjectaccessreviews", review)
    allowed = bool(created.status.allowed)
    out.write("yes\n" if allowed else "no\n")
    return 0 if allowed else 1


def _served_discovery(client):
    """[(groupVersion, APIResourceList doc)] for every groupVersion the
    server actually serves — the RESTMapper discovery walk both
    apiversions.go and apiresources.go perform. Candidate gvs come from
    the shared scheme; each is CONFIRMED over the wire."""
    gvs = ["v1"]
    for k in scheme.all_kinds():
        for gv in scheme.served_versions(k):
            if gv not in gvs:
                gvs.append(gv)
    served = []
    for gv in sorted(gvs):
        path = f"/api/{gv}" if "/" not in gv else f"/apis/{gv}"
        try:
            doc = client.request("GET", path)
        except APIStatusError:
            continue
        if doc.get("resources"):
            served.append((gv, doc))
    return served


def cmd_api_versions(client, args, out):
    """apiversions.go: every served groupVersion, one per line."""
    for gv, _ in _served_discovery(client):
        out.write(gv + "\n")


def cmd_api_resources(client, args, out):
    """apiresources.go: flatten the discovery docs into a table."""
    rows, seen = [], set()
    for gv, doc in _served_discovery(client):
        for r in doc.get("resources", []):
            if r["name"] in seen:
                continue
            seen.add(r["name"])
            rows.append([r["name"], gv, str(r["namespaced"]), r["kind"]])
    rows.sort()
    _write_table(["NAME", "APIVERSION", "NAMESPACED", "KIND"], rows, out)


def cmd_cluster_info(client, args, out):
    """clusterinfo.go: the master URL + cluster-service Services;
    `cluster-info dump` (clusterinfo_dump.go) writes the debugging
    corpus — nodes, events, and per-namespace workload state — as JSON
    to stdout or one file per list under --output-directory."""
    if getattr(args, "action", None) == "dump":
        return _cluster_info_dump(client, args, out)
    out.write(f"Kubernetes master is running at {client.base_url}\n")
    svcs, _ = client.list("services", "kube-system")
    for s in svcs:
        if (s.metadata.labels or {}).get(
                "kubernetes.io/cluster-service") == "true":
            out.write(f"{s.metadata.name} is running at "
                      f"{client.base_url}/api/v1/namespaces/kube-system/"
                      f"services/{s.metadata.name}/proxy\n")


def _cluster_info_dump(client, args, out):
    import os

    def emit(name: str, objs):
        doc = {"kind": "List",
               "items": [scheme.encode_object(o) for o in objs]}
        if args.output_directory:
            os.makedirs(args.output_directory, exist_ok=True)
            with open(os.path.join(args.output_directory,
                                   name.replace("/", "_") + ".json"),
                      "w") as f:
                json.dump(doc, f, indent=2)
        else:
            out.write(f"==== {name} ====\n")
            out.write(json.dumps(doc, indent=2) + "\n")

    emit("nodes", client.list("nodes")[0])
    namespaces = ([n.metadata.name for n in client.list("namespaces")[0]]
                  if args.all_namespaces else [args.namespace])
    for ns in namespaces:
        for plural in ("pods", "services", "replicationcontrollers",
                       "replicasets", "deployments", "daemonsets",
                       "events"):
            try:
                objs, _ = client.list(plural, ns)
            except APIStatusError:
                continue
            emit(f"{ns}/{plural}", objs)


def cmd_completion(client, args, out):
    """Emit a shell completion script (pkg/kubectl/cmd/completion.go).
    Completes verbs and common resource kinds; bash and zsh (zsh wraps
    the bash script via bashcompinit, like the reference)."""
    verbs = " ".join(sorted(VERBS))
    kinds = ("pods nodes services deployments replicasets "
             "replicationcontrollers jobs cronjobs daemonsets "
             "statefulsets namespaces events secrets configmaps")
    bash = f"""# kubectl bash completion
_kubectl_complete() {{
    local cur=${{COMP_WORDS[COMP_CWORD]}}
    if [ $COMP_CWORD -eq 1 ]; then
        COMPREPLY=( $(compgen -W "{verbs}" -- "$cur") )
    else
        COMPREPLY=( $(compgen -W "{kinds}" -- "$cur") )
    fi
}}
complete -F _kubectl_complete kubectl
"""
    if args.shell == "zsh":
        out.write("autoload -Uz bashcompinit && bashcompinit\n" + bash)
    else:
        out.write(bash)


def cmd_options(client, args, out):
    """List the global flags every verb accepts
    (pkg/kubectl/cmd/options.go)."""
    out.write("The following options can be passed to any command:\n\n")
    for flag, descr in [
            ("--server, -s", "API server URL (default $KUBECTL_SERVER)"),
            ("--token", "bearer token for authentication"),
            ("--namespace, -n", "object namespace (default 'default')"),
            ("--ca-cert-data", "cluster CA bundle PEM (or @file)"),
            ("--client-cert-data", "x509 client cert PEM (or @file)"),
            ("--client-key-data", "x509 client key PEM (or @file)")]:
        out.write(f"  {flag}: {descr}\n")


DESIRED_REPLICAS_ANNOTATION = "kubectl.kubernetes.io/desired-replicas"


def cmd_rolling_update(client, args, out):
    """kubectl rolling-update OLD (--image=IMG | -f new-rc.yaml)
    (pkg/kubectl/rolling_updater.go Update + cmd/rollingupdate.go):
    create the next RC, then step replicas one at a time — scale next
    up, wait for its pods to be Ready, scale old down — so capacity
    never drops below the old desired count. Cleanup deletes the old
    RC; the --image path then renames next back to OLD (orphaning the
    pods across the delete/create so they are re-adopted)."""
    import hashlib
    import time as _time

    old = client.get("replicationcontrollers", args.namespace, args.name)
    if args.filename:
        docs = load_manifests(args.filename)
        if len(docs) != 1:
            raise ManifestError("rolling-update takes exactly one "
                                "ReplicationController manifest")
        new, kind = _decode_doc(docs[0])
        if kind != "ReplicationController":
            raise ManifestError(f"rolling-update needs a "
                                f"ReplicationController, got {kind}")
        if new.metadata.name == old.metadata.name:
            raise ManifestError(
                "the new RC must have a different name "
                "(rollingupdate.go validates name != old name)")
        if new.spec.selector == old.spec.selector:
            raise ManifestError(
                "the new RC must have a different selector")
        rename_to = None
    elif args.image:
        # cmd/rollingupdate.go image path: clone the old RC, retag the
        # first container, key both selectors on a deployment hash so
        # old and new pods are distinguishable
        import copy

        new = copy.deepcopy(old)
        new.spec.template.spec.containers[0].image = args.image
        tmpl_hash = hashlib.sha1(
            json.dumps(scheme.encode_object(new)["spec"]["template"],
                       sort_keys=True).encode()).hexdigest()[:10]
        new.metadata = api.ObjectMeta(
            name=f"{old.metadata.name}-{tmpl_hash}",
            namespace=old.metadata.namespace)
        new.spec.selector = dict(old.spec.selector,
                                 deployment=tmpl_hash)
        new.spec.template.metadata.labels = dict(
            new.spec.template.metadata.labels or {},
            deployment=tmpl_hash)
        rename_to = old.metadata.name
    else:
        raise ManifestError("rolling-update needs --image or -f")

    desired = new.spec.replicas or old.spec.replicas
    deadline = _time.monotonic() + args.timeout

    def scale(name: str, replicas: int):
        # retry-on-conflict (rolling_updater.go scaleAndWaitWithScaler's
        # RetryParams): the controller's status writes race ours
        while True:
            rc = client.get("replicationcontrollers", args.namespace, name)
            rc.spec.replicas = replicas
            try:
                client.update("replicationcontrollers", rc)
                return
            except APIStatusError as e:
                if e.code != 409 or _time.monotonic() >= deadline:
                    raise
                _time.sleep(args.poll_interval)

    def wait_ready(name: str, want: int):
        while _time.monotonic() < deadline:
            rc = client.get("replicationcontrollers", args.namespace, name)
            if rc.status.ready_replicas >= want:
                return
            _time.sleep(args.poll_interval)
        raise SystemExit(f"error: timed out waiting for {name} to have "
                         f"{want} ready replicas")

    try:
        new_live = client.get("replicationcontrollers", args.namespace,
                              new.metadata.name)
        scaled_up = new_live.spec.replicas  # resume an interrupted update
        # the annotation preserves the ORIGINAL desired count across
        # interruption: on resume, old has already been partially
        # drained, so its current spec.replicas undercounts
        stamped = (new_live.metadata.annotations or {}).get(
            DESIRED_REPLICAS_ANNOTATION)
        if stamped:
            desired = int(stamped)
    except APIStatusError as e:
        if e.code != 404:
            raise
        new.spec.replicas = 0
        new.metadata.annotations = dict(new.metadata.annotations or {})
        new.metadata.annotations[DESIRED_REPLICAS_ANNOTATION] = str(desired)
        client.create("replicationcontrollers", new)
        scaled_up = 0
        out.write(f"Created {new.metadata.name}\n")
    out.write(f"Scaling up {new.metadata.name} from {scaled_up} to "
              f"{desired}, scaling down {old.metadata.name} from "
              f"{old.spec.replicas} to 0\n")
    remaining_old = old.spec.replicas
    while scaled_up < desired or remaining_old > 0:
        if scaled_up < desired:
            scaled_up += 1
            scale(new.metadata.name, scaled_up)
            wait_ready(new.metadata.name, scaled_up)
        if remaining_old > 0:
            remaining_old -= 1
            scale(old.metadata.name, remaining_old)
    # scaleAndWait: the old RC's pods must actually be GONE before the
    # RC object is deleted — a bare delete would orphan the stragglers
    # on clusters where cascading GC lags (or isn't running)
    while True:
        rc = client.get("replicationcontrollers", args.namespace,
                        old.metadata.name)
        if rc.status.replicas == 0:
            break
        if _time.monotonic() >= deadline:
            raise SystemExit(
                f"error: timed out waiting for {old.metadata.name}'s "
                f"pods to terminate; NOT deleting it (rerun to resume)")
        _time.sleep(args.poll_interval)
    client.delete("replicationcontrollers", args.namespace,
                  old.metadata.name)
    if rename_to:
        # Rename (rolling_updater.go:504): orphan-delete next, recreate
        # under the old name with the SAME selector — the pods survive
        # and the controller re-adopts them
        while True:
            rc = client.get("replicationcontrollers", args.namespace,
                            new.metadata.name)
            rc.metadata.annotations = dict(rc.metadata.annotations or {})
            rc.metadata.annotations["kubernetes.io/orphan-dependents"] = \
                "true"
            try:
                client.update("replicationcontrollers", rc)
                break
            except APIStatusError as e:
                if e.code != 409:
                    raise
                _time.sleep(args.poll_interval)
        client.delete("replicationcontrollers", args.namespace,
                      rc.metadata.name)
        # The reference's orphan finalizer strips dependents' owner
        # references BEFORE the owner object disappears; our annotation
        # route does it asynchronously in the GC, so do it here
        # synchronously — otherwise the recreated RC sees pods still
        # owned by the dead hash-RC, refuses to adopt them, and spawns
        # duplicates (controller_ref adoption requires ref-less pods)
        pods, _ = client.list("pods", args.namespace)
        for p in pods:
            refs = [r for r in (p.metadata.owner_references or [])
                    if not (r.kind == "ReplicationController"
                            and r.name == rc.metadata.name)]
            if len(refs) != len(p.metadata.owner_references or []):
                p.metadata.owner_references = refs
                try:
                    client.update("pods", p)
                except APIStatusError:
                    pass  # deleted/conflicted mid-strip: GC's problem
        renamed = api.ReplicationController(
            metadata=api.ObjectMeta(name=rename_to,
                                    namespace=args.namespace),
            spec=rc.spec)
        client.create("replicationcontrollers", renamed)
        out.write(f"Renamed {rc.metadata.name} to {rename_to}\n")
    out.write(f"replicationcontroller/{rename_to or new.metadata.name} "
              f"rolling updated\n")


def cmd_convert(client, args, out):
    """convert.go: re-render manifests at --output-version through the
    SERVER-SIDE conversion hubs (api/conversion.py) — the same wire
    converters multi-version serving uses, run locally."""
    from ..api import conversion

    for doc in load_manifests(args.filename):
        kind = doc.get("kind")
        if kind is None:
            raise SystemExit("error: manifest document missing kind")
        try:
            hub = scheme.api_version_for(kind)
        except KeyError:
            raise SystemExit(f"error: unknown kind {kind!r}")
        src = doc.get("apiVersion", hub)
        try:
            hub_doc = conversion.to_hub(kind, doc, src, hub)
            out_doc = conversion.from_hub(kind, hub_doc,
                                          args.output_version, hub)
        except KeyError as e:
            raise SystemExit(f"error: {e.args[0]}")
        if args.output == "json":
            out.write(json.dumps(out_doc, indent=2) + "\n")
        else:
            import yaml
            out.write(yaml.safe_dump(out_doc, sort_keys=False) + "---\n")


def cmd_set(client, args, out):
    """pkg/kubectl/cmd/set/: `set image KIND/NAME c=img...` (rollout via
    template change), `set env KIND/NAME K=V... K-` (set_env.go), and
    `set resources KIND/NAME --requests/--limits` (set_resources.go) —
    all patch the pod template's containers, selected by -c (default
    all)."""
    kind_name = args.target
    if "/" not in kind_name:
        raise SystemExit(f"error: set {args.action} needs KIND/NAME")
    kind, _, name = kind_name.partition("/")
    plural = _resolve_kind(kind)
    obj = client.get(plural, args.namespace, name)
    tmpl = (None if plural == "pods"
            else getattr(obj.spec, "template", None))
    if tmpl is None and plural != "pods":
        raise SystemExit(f"error: {kind}/{name} has no pod template")
    containers = (tmpl.spec.containers if tmpl is not None
                  else obj.spec.containers)
    selected = [c for c in containers
                if not args.container or c.name == args.container]
    if not selected:
        raise SystemExit("error: no container matched")
    if args.action == "image":
        if any("=" not in kv for kv in args.images):
            raise SystemExit("error: image updates must be container=image")
        updates = dict(kv.split("=", 1) for kv in args.images)
        changed = False
        for c in selected:
            if c.name in updates or "*" in updates:
                c.image = updates.get(c.name, updates.get("*"))
                changed = True
        if not changed:
            raise SystemExit("error: no container matched")
        client.update(plural, obj)
        out.write(f"{plural}/{name} image updated\n")
        return
    if args.action == "env":
        if not args.images:
            raise SystemExit("error: set env needs at least one "
                             "KEY=VALUE or KEY-")
        for kv in args.images:  # positional K=V / K- items
            if kv.endswith("-") and "=" not in kv:
                for c in selected:
                    c.env.pop(kv[:-1], None)
            elif "=" in kv:
                k, _, v = kv.partition("=")
                for c in selected:
                    c.env = dict(c.env or {}, **{k: v})
            else:
                raise SystemExit(f"error: env needs KEY=VALUE or KEY-, "
                                 f"got {kv!r}")
        client.update(plural, obj)
        out.write(f"{plural}/{name} env updated\n")
        return
    if args.action == "resources":
        from ..api import resources as resq

        def parse_rl(text):
            # canonical container-resource units (api.resource_list):
            # cpu in millicores, everything else in base units/bytes
            outd = {}
            for kv in (text or "").split(","):
                if not kv:
                    continue
                k, eq, v = kv.partition("=")
                if not eq:
                    raise SystemExit(f"error: --requests/--limits need "
                                     f"KEY=VALUE, got {kv!r}")
                try:
                    outd[k] = (resq.milli(v) if k == resq.CPU
                               else resq.value(v))
                except ValueError as e:
                    raise SystemExit(f"error: {e}") from e
            return outd

        reqs, lims = parse_rl(args.requests), parse_rl(args.limits)
        if not reqs and not lims:
            raise SystemExit("error: set resources needs --requests "
                             "and/or --limits")
        for c in selected:
            c.resources.requests.update(reqs)
            c.resources.limits.update(lims)
        client.update(plural, obj)
        out.write(f"{plural}/{name} resource requirements updated\n")
        return
    raise SystemExit(f"error: unknown set action {args.action!r}")


def cmd_wait(client, args, out):
    """wait.go (new in the reference's 1.11 cycle): block until
    --for=delete or --for=condition=<Type>[=<Status>] holds."""
    plural = _resolve_kind(args.kind)
    want = args.wait_for
    if want != "delete" and not want.startswith("condition="):
        raise SystemExit(
            f"error: --for must be 'delete' or 'condition=<Type>"
            f"[=<Status>]', got {want!r}")
    deadline = time.time() + args.timeout
    while True:
        try:
            obj = client.get(plural, args.namespace, args.name)
        except APIStatusError as e:
            if e.code == 404:
                if want == "delete":
                    out.write(f"{plural}/{args.name} condition met\n")
                    return 0
                raise
            raise
        if want != "delete" and want.startswith("condition="):
            spec = want[len("condition="):]
            ctype, _, cstatus = spec.partition("=")
            cstatus = cstatus or "True"
            conds = getattr(obj.status, "conditions", [])
            for c in conds:
                t = getattr(c, "type", None)
                s = getattr(c, "status", None)
                if t is None and isinstance(c, tuple):
                    t, s = c[0], c[1]
                if t == ctype and str(s).startswith(cstatus):
                    out.write(f"{plural}/{args.name} condition met\n")
                    return 0
        if time.time() >= deadline:
            print(f"error: timed out waiting for {want} on "
                  f"{plural}/{args.name}", file=sys.stderr)
            return 1
        time.sleep(min(0.1, args.timeout / 10))


def cmd_proxy(client, args, out):
    """proxy.go: a localhost HTTP server forwarding every request to
    the apiserver with this client's credentials attached — gives
    unauthenticated local tools an authenticated API path. --once
    serves a single request in the background and returns (CI mode)."""
    import http.server
    import threading as _threading

    target = client

    class Handler(http.server.BaseHTTPRequestHandler):
        def _forward(self):
            body = None
            n = int(self.headers.get("Content-Length") or 0)
            if n:
                try:
                    body = json.loads(self.rfile.read(n) or b"{}")
                except json.JSONDecodeError:
                    raw = json.dumps({"kind": "Status", "code": 400,
                                      "reason": "BadRequest",
                                      "message": "body is not JSON"}).encode()
                    self.send_response(400)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(raw)))
                    self.end_headers()
                    self.wfile.write(raw)
                    return
            try:
                raw, ctype = target.request_bytes(
                    self.command, self.path.split("?", 1)[0],
                    body=body,
                    query=(self.path.split("?", 1)[1]
                           if "?" in self.path else ""))
                code = 200
            except APIStatusError as e:
                raw = json.dumps({"kind": "Status", "code": e.code,
                                  "reason": e.reason,
                                  "message": e.message}).encode()
                ctype, code = "application/json", e.code
            self.send_response(code)
            self.send_header("Content-Type", ctype or "application/json")
            self.send_header("Content-Length", str(len(raw)))
            self.end_headers()
            self.wfile.write(raw)

        do_GET = do_POST = do_PUT = do_PATCH = do_DELETE = _forward

        def log_message(self, *a):
            pass

    httpd = http.server.HTTPServer(("127.0.0.1", args.port), Handler)
    out.write(f"Starting to serve on 127.0.0.1:{httpd.server_address[1]}\n")
    out.flush()
    if args.once:
        # NON-daemon: from a real shell the process must stay alive
        # until the one promised request is served (a daemon thread
        # would die with sys.exit before the client connects);
        # in-process callers get control back immediately either way
        _threading.Thread(target=httpd.handle_request).start()
    else:
        try:
            httpd.serve_forever()
        except KeyboardInterrupt:
            pass
    return 0


# -- kind aliases (pkg/kubectl short names) -----------------------------------

_ALIASES = {
    "po": "pods", "pod": "pods",
    "no": "nodes", "node": "nodes",
    "svc": "services", "service": "services",
    "deploy": "deployments", "deployment": "deployments",
    "rs": "replicasets", "replicaset": "replicasets",
    "rc": "replicationcontrollers",
    "sts": "statefulsets", "statefulset": "statefulsets",
    "ds": "daemonsets", "daemonset": "daemonsets",
    "job": "jobs", "cj": "cronjobs", "cronjob": "cronjobs",
    "ns": "namespaces", "namespace": "namespaces",
    "ep": "endpoints",
    "pdb": "poddisruptionbudgets",
    "pv": "persistentvolumes", "pvc": "persistentvolumeclaims",
    "quota": "resourcequotas", "sa": "serviceaccounts",
    "pc": "priorityclasses", "ev": "events", "event": "events",
}


def _resolve_kind(kind: str) -> str:
    plural = _ALIASES.get(kind, kind)
    if scheme.kind_for_plural(plural) is None:
        raise SystemExit(f"error: unknown resource type {kind!r}")
    return plural


# -- entry --------------------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(prog="kubectl")
    ap.add_argument("--server", "-s", default=None,
                    help="API server URL (default $KUBECTL_SERVER)")
    ap.add_argument("--token", default=None)
    ap.add_argument("--namespace", "-n", default="default")
    # kubeconfig's certificate-authority / client-certificate analogs
    # (TLS clusters; PEM data inline or @/path/to/file)
    ap.add_argument("--ca-cert-data", default=None,
                    help="cluster CA bundle PEM (or @file) for https "
                         "servers")
    ap.add_argument("--client-cert-data", default=None,
                    help="x509 client cert PEM (or @file) for mTLS")
    ap.add_argument("--client-key-data", default=None,
                    help="x509 client key PEM (or @file) for mTLS")
    ap.add_argument("--kubeconfig", default=None,
                    help="path to the kubeconfig file "
                         "(default $KUBECONFIG or ~/.kube/config)")
    ap.add_argument("--context", default=None,
                    help="kubeconfig context to use")
    sub = ap.add_subparsers(dest="verb", required=True)

    cfgp = sub.add_parser("config")
    cfgp.add_argument("action",
                      choices=["view", "current-context", "use-context",
                               "get-contexts", "set-cluster",
                               "set-credentials", "set-context",
                               "delete-context"])
    cfgp.add_argument("name", nargs="?")
    cfgp.add_argument("--raw", action="store_true")
    cfgp.add_argument("--server", dest="config_server", default=None)
    cfgp.add_argument("--certificate-authority-data", default=None)
    cfgp.add_argument("--token", dest="config_token", default=None)
    cfgp.add_argument("--cluster", default=None)
    cfgp.add_argument("--user", default=None)
    cfgp.add_argument("--namespace", dest="ctx_namespace", default=None)

    g = sub.add_parser("get")
    g.add_argument("kind")
    g.add_argument("name", nargs="?")
    g.add_argument("--output", "-o", default="table",
                   help="table|wide|yaml|json|jsonpath=...|"
                        "custom-columns=...")
    g.add_argument("--all-namespaces", "-A", action="store_true")
    g.add_argument("--selector", "-l", default=None)
    g.add_argument("--field-selector", default=None)
    g.add_argument("--show-labels", action="store_true")
    g.add_argument("--watch", "-w", action="store_true")
    g.add_argument("--watch-timeout", type=float, default=5.0,
                   help="seconds to stream events before returning "
                        "(real kubectl streams forever)")

    d = sub.add_parser("describe")
    d.add_argument("kind")
    d.add_argument("name")

    c = sub.add_parser("create")
    c.add_argument("gen", nargs="?", default=None,
                   help="generator kind (configmap, secret, namespace, "
                        "serviceaccount, quota, priorityclass, "
                        "deployment, job, service, role, clusterrole, "
                        "rolebinding, clusterrolebinding, "
                        "poddisruptionbudget) — or use -f")
    c.add_argument("name", nargs="?")
    c.add_argument("extra_name", nargs="?")
    c.add_argument("--filename", "-f", default=None)
    c.add_argument("--recursive", "-R", action="store_true")
    c.add_argument("--dry-run", action="store_true")
    c.add_argument("--from-literal", action="append")
    c.add_argument("--from-file", action="append")
    c.add_argument("--type", default="Opaque")
    c.add_argument("--image", default=None)
    c.add_argument("--replicas", type=int, default=1)
    c.add_argument("--value", type=int, default=0)
    c.add_argument("--global-default", action="store_true")
    c.add_argument("--description", default="")
    c.add_argument("--hard", default=None)
    c.add_argument("--tcp", action="append")
    # dest must NOT be "verb" — that is the subparsers' dest, and
    # argparse would overwrite the selected verb with this flag's
    # default (None), breaking every create invocation
    c.add_argument("--verb", dest="rbac_verbs", action="append")
    c.add_argument("--resource", action="append")
    c.add_argument("--role", default=None)
    c.add_argument("--clusterrole", default=None)
    c.add_argument("--serviceaccount", action="append")
    c.add_argument("--user", action="append")
    c.add_argument("--min-available", type=int, default=None)
    c.add_argument("--selector", default=None)

    ap_apply = sub.add_parser("apply")
    ap_apply.add_argument(
        "action", nargs="?", default=None,
        choices=["view-last-applied", "set-last-applied"])
    ap_apply.add_argument("kind", nargs="?")
    ap_apply.add_argument("name", nargs="?")
    ap_apply.add_argument("--filename", "-f", default=None)
    ap_apply.add_argument("--recursive", "-R", action="store_true")
    ap_apply.add_argument("--dry-run", action="store_true")
    ap_apply.add_argument("--prune", action="store_true")
    ap_apply.add_argument("--selector", "-l", default=None)

    dl = sub.add_parser("delete")
    dl.add_argument("kind")
    dl.add_argument("name", nargs="?")
    dl.add_argument("--selector", "-l", default=None)
    dl.add_argument("--field-selector", default=None)
    dl.add_argument("--grace-period", type=int, default=None,
                    dest="grace_period")
    dl.add_argument("--force", action="store_true")
    dl.add_argument("--now", action="store_true")

    sc = sub.add_parser("scale")
    sc.add_argument("kind")
    sc.add_argument("name")
    sc.add_argument("--replicas", type=int, required=True)

    for verb in ("cordon", "uncordon", "drain"):
        c = sub.add_parser(verb)
        c.add_argument("name")

    lb = sub.add_parser("label")
    lb.add_argument("kind")
    lb.add_argument("name")
    lb.add_argument("labels", nargs="+")

    ro = sub.add_parser("rollout")
    ro.add_argument("action",
                    choices=["status", "history", "undo", "pause", "resume"])
    ro.add_argument("kind")
    ro.add_argument("name")
    ro.add_argument("--to-revision", type=int, default=0)
    # history --revision=N: print that revision's pod template detail
    ro.add_argument("--revision", type=int, default=0)

    ex = sub.add_parser("expose")
    ex.add_argument("kind")
    ex.add_argument("name")
    ex.add_argument("--port", type=int, required=True)
    ex.add_argument("--target-port", type=int, default=0)
    ex.add_argument("--name", dest="service_name", default="")
    ex.add_argument("--type", default="ClusterIP")

    lg = sub.add_parser("logs")
    lg.add_argument("name")
    lg.add_argument("--container", "-c", default="")
    lg.add_argument("--tail", type=int, default=None)
    lg.add_argument("--follow", "-f", action="store_true")
    lg.add_argument("--follow-rounds", type=int, default=1,
                    help="long-poll rounds to follow (SPDY stream analog)")
    lg.add_argument("--wait", type=float, default=2.0)
    lg.add_argument("--previous", "-p", action="store_true")

    pl = sub.add_parser("plugin")
    pl.add_argument("plugin_name", nargs="?")
    # REMAINDER: flag-like tokens (--verbose) belong to the PLUGIN
    pl.add_argument("plugin_args", nargs=argparse.REMAINDER)

    ec = sub.add_parser("exec")
    ec.add_argument("name")
    ec.add_argument("--container", "-c", default="")
    ec.add_argument("command", nargs="+",
                    help="command to run (after --)")

    at = sub.add_parser("attach")
    at.add_argument("name")
    at.add_argument("--container", "-c", default="")
    at.add_argument("--follow-rounds", type=int, default=1,
                    help="long-poll rounds to follow (SPDY stream analog)")
    at.add_argument("--wait", type=float, default=2.0,
                    help="seconds each poll waits for new output")

    pf = sub.add_parser("port-forward")
    pf.add_argument("name")
    pf.add_argument("ports", help="LOCAL:REMOTE (or just REMOTE)")
    pf.add_argument("--once", action="store_true",
                    help="serve exactly one connection then exit")
    pf.add_argument("--wait", type=float, default=10.0,
                    help="--once: seconds to wait for the connection")

    pa = sub.add_parser("patch")
    pa.add_argument("kind")
    pa.add_argument("name")
    pa.add_argument("--patch", "-p", required=True,
                    help="JSON merge patch")

    an = sub.add_parser("annotate")
    an.add_argument("kind")
    an.add_argument("name")
    an.add_argument("annotations", nargs="+",
                    help="k=v to set, k- to remove")

    ed = sub.add_parser("edit")
    ed.add_argument("kind")
    ed.add_argument("name")

    cp = sub.add_parser("cp")
    cp.add_argument("src", help="pod:path or local path")
    cp.add_argument("dst", help="local path or pod:path")
    cp.add_argument("--container", "-c", default="")

    df = sub.add_parser("diff")
    df.add_argument("--filename", "-f", required=True)

    xp = sub.add_parser("explain")
    xp.add_argument("kind")

    tp = sub.add_parser("top")
    tp.add_argument("kind")

    tn = sub.add_parser("taint")
    tn.add_argument("kind")
    tn.add_argument("name")
    tn.add_argument("taints", nargs="+",
                    help="key[=value]:Effect to add, key[:Effect]- to remove")

    rn = sub.add_parser("run")
    rn.add_argument("name")
    rn.add_argument("--image", required=True)
    rn.add_argument("--replicas", type=int, default=1)
    rn.add_argument("--restart", choices=["Always", "OnFailure", "Never"],
                    default="Always")

    rp = sub.add_parser("replace")
    rp.add_argument("--filename", "-f", required=True)

    au = sub.add_parser("autoscale")
    au.add_argument("kind")
    au.add_argument("name")
    au.add_argument("--min", type=int, default=1)
    au.add_argument("--max", type=int, required=True)
    au.add_argument("--cpu-percent", type=int, default=80)

    ce = sub.add_parser("certificate")
    ce.add_argument("action", choices=["approve", "deny"])
    ce.add_argument("name")

    at2 = sub.add_parser("auth")
    at2.add_argument("action", choices=["can-i"])
    at2.add_argument("auth_verb", metavar="verb")
    at2.add_argument("resource")
    at2.add_argument("resource_name", nargs="?", default="")
    at2.add_argument("--subresource", default="")

    sub.add_parser("api-versions")
    sub.add_parser("api-resources")
    ci = sub.add_parser("cluster-info")
    ci.add_argument("action", nargs="?", default=None, choices=["dump"])
    ci.add_argument("--output-directory", default=None)
    ci.add_argument("--all-namespaces", "-A", action="store_true")

    ru = sub.add_parser("rolling-update")
    ru.add_argument("name")
    ru.add_argument("--image", default=None)
    ru.add_argument("--filename", "-f", default=None)
    ru.add_argument("--timeout", type=float, default=60.0)
    ru.add_argument("--poll-interval", type=float, default=0.05)

    cp = sub.add_parser("completion")
    cp.add_argument("shell", choices=["bash", "zsh"])

    sub.add_parser("options")

    cv = sub.add_parser("convert")
    cv.add_argument("--filename", "-f", required=True)
    cv.add_argument("--output-version", required=True)
    cv.add_argument("--output", "-o", choices=["yaml", "json"],
                    default="yaml")

    se = sub.add_parser("set")
    se.add_argument("action", choices=["image", "env", "resources"])
    se.add_argument("target", help="KIND/NAME")
    se.add_argument("images", nargs="*",
                    help="image: container=image ('*' for all); "
                         "env: K=V or K-")
    se.add_argument("--container", "-c", default="")
    se.add_argument("--requests", default="")
    se.add_argument("--limits", default="")

    wt = sub.add_parser("wait")
    wt.add_argument("kind")
    wt.add_argument("name")
    wt.add_argument("--for", dest="wait_for", required=True,
                    help="delete | condition=<Type>[=<Status>]")
    wt.add_argument("--timeout", type=float, default=30.0)

    px = sub.add_parser("proxy")
    px.add_argument("--port", type=int, default=0)
    px.add_argument("--once", action="store_true",
                    help="serve exactly one request then exit")

    sub.add_parser("version")
    return ap


VERBS = {"get": cmd_get, "describe": cmd_describe, "create": cmd_create,
         "apply": cmd_apply, "delete": cmd_delete, "scale": cmd_scale,
         "cordon": cmd_cordon, "uncordon": cmd_uncordon, "drain": cmd_drain,
         "label": cmd_label, "version": cmd_version, "rollout": cmd_rollout,
         "expose": cmd_expose, "explain": cmd_explain, "top": cmd_top,
         "logs": cmd_logs, "exec": cmd_exec, "attach": cmd_attach,
         "port-forward": cmd_port_forward, "patch": cmd_patch,
         "annotate": cmd_annotate, "edit": cmd_edit, "cp": cmd_cp,
         "diff": cmd_diff, "taint": cmd_taint, "run": cmd_run,
         "replace": cmd_replace, "autoscale": cmd_autoscale,
         "certificate": cmd_certificate, "auth": cmd_auth,
         "api-versions": cmd_api_versions, "api-resources": cmd_api_resources,
         "cluster-info": cmd_cluster_info, "convert": cmd_convert,
         "set": cmd_set, "wait": cmd_wait, "proxy": cmd_proxy,
         "rolling-update": cmd_rolling_update,
         "completion": cmd_completion, "options": cmd_options,
         "plugin": cmd_plugin}
# "config" is registered below its (later) definition — it is
# dispatched pre-connect in main(), the VERBS entry only feeds
# completion/help


def main(argv: Optional[List[str]] = None, out=None) -> int:
    import os
    out = out or sys.stdout
    args = build_parser().parse_args(argv)
    if args.verb == "config":
        # config verbs edit the kubeconfig FILE — no server connection
        return cmd_config(None, args, out)
    if args.verb == "plugin":
        # purely local: discovery + subprocess, never the apiserver —
        # but the kubeconfig context's namespace still reaches the
        # plugin env (the reference passes the factory-resolved one)
        if args.namespace == "default":
            from . import kubeconfig as kc

            path = args.kubeconfig or kc.default_path()
            if os.path.exists(path):
                try:
                    r = kc.resolve(kc.load(path), context=args.context)
                    if r.get("namespace"):
                        args.namespace = r["namespace"]
                except Exception:
                    pass  # a broken kubeconfig can't block local plugins
        try:
            return cmd_plugin(None, args, out) or 0
        except SystemExit as e:
            print(e, file=sys.stderr)
            return 1
    from ..client.rest import pem_arg

    server = args.server or os.environ.get("KUBECTL_SERVER")
    creds = {"token": args.token,
             "ca_cert_pem": pem_arg(args.ca_cert_data),
             "client_cert_pem": pem_arg(args.client_cert_data),
             "client_key_pem": pem_arg(args.client_key_data)}
    if not server:
        # clientcmd precedence: flags > env > kubeconfig file
        from . import kubeconfig as kc

        path = args.kubeconfig or kc.default_path()
        if os.path.exists(path):
            try:
                r = kc.resolve(kc.load(path), context=args.context)
            except ValueError as e:
                print(f"error: {e}", file=sys.stderr)
                return 1
            server = r["server"]
            creds = {"token": creds["token"] or r["token"],
                     "ca_cert_pem": creds["ca_cert_pem"] or r["ca_pem"],
                     "client_cert_pem": (creds["client_cert_pem"]
                                         or r["client_cert_pem"]),
                     "client_key_pem": (creds["client_key_pem"]
                                        or r["client_key_pem"])}
            if r["namespace"] and args.namespace == "default":
                args.namespace = r["namespace"]
    if not server:
        print("error: --server, $KUBECTL_SERVER, or a kubeconfig "
              "required", file=sys.stderr)
        return 1
    try:
        client = RESTClient(server, **creds)
    except (ValueError, OSError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    return _dispatch(client, args, out)


def cmd_config(client, args, out):
    """kubectl config view / current-context / use-context /
    get-contexts / set-cluster / set-credentials / set-context /
    delete-context — file edits over the kubeconfig
    (pkg/kubectl/cmd/config/)."""
    import os

    from . import kubeconfig as kc

    path = args.kubeconfig or kc.default_path()
    cfg = (kc.load(path) if os.path.exists(path)
           else {"apiVersion": "v1", "kind": "Config", "clusters": [],
                 "users": [], "contexts": [], "current-context": ""})
    action = args.action

    def upsert(entries, name, key, value):
        e = next((x for x in entries if x.get("name") == name), None)
        if e is None:
            entries.append({"name": name, key: value})
        else:
            e.setdefault(key, {}).update(value)

    if action == "view":
        import yaml

        shown = json.loads(json.dumps(cfg))  # deep copy
        if not args.raw:
            for u in shown.get("users", []):
                for k in list(u.get("user", {})):
                    u["user"][k] = "REDACTED"
        out.write(yaml.safe_dump(shown, sort_keys=False))
        return 0
    if action == "current-context":
        cur = cfg.get("current-context")
        if not cur:
            print("error: current-context is not set", file=sys.stderr)
            return 1
        out.write(cur + "\n")
        return 0
    if action == "get-contexts":
        out.write("CURRENT  NAME  CLUSTER  USER  NAMESPACE\n")
        for c in cfg.get("contexts", []):
            mark = "*" if c["name"] == cfg.get("current-context") else ""
            cc = c.get("context", {})
            out.write(f"{mark}  {c['name']}  {cc.get('cluster', '')}  "
                      f"{cc.get('user', '')}  "
                      f"{cc.get('namespace', '')}\n".lstrip())
        return 0
    if action == "use-context":
        if not any(c.get("name") == args.name
                   for c in cfg.get("contexts", [])):
            print(f"error: no context exists with the name: "
                  f"{args.name!r}", file=sys.stderr)
            return 1
        cfg["current-context"] = args.name
    elif action == "set-cluster":
        cluster = {}
        if args.config_server:
            cluster["server"] = args.config_server
        if args.certificate_authority_data:
            from ..client.rest import pem_arg
            import base64

            cluster["certificate-authority-data"] = base64.b64encode(
                pem_arg(args.certificate_authority_data).encode()).decode()
        upsert(cfg["clusters"], args.name, "cluster", cluster)
    elif action == "set-credentials":
        user = {}
        if args.config_token:
            user["token"] = args.config_token
        upsert(cfg["users"], args.name, "user", user)
    elif action == "set-context":
        ctx = {}
        if args.cluster:
            ctx["cluster"] = args.cluster
        if args.user:
            ctx["user"] = args.user
        if args.ctx_namespace:
            ctx["namespace"] = args.ctx_namespace
        upsert(cfg["contexts"], args.name, "context", ctx)
    elif action == "delete-context":
        cfg["contexts"] = [c for c in cfg.get("contexts", [])
                           if c.get("name") != args.name]
        if cfg.get("current-context") == args.name:
            cfg["current-context"] = ""
    else:
        print(f"error: unknown config action {action!r}", file=sys.stderr)
        return 1
    kc.save(path, cfg)
    return 0


VERBS["config"] = cmd_config


def _dispatch(client, args, out) -> int:
    try:
        # discovery: register served CRDs so custom kinds resolve in
        # _resolve_kind / decode (the reference kubectl's RESTMapper
        # discovery against the apiextensions API)
        crds, _ = client.list("customresourcedefinitions")
        for crd in crds:
            scheme.register_dynamic(crd)
    except Exception:
        pass  # pre-CRD servers: discovery is best-effort
    try:
        # a verb may return a process exit code (kubectl exec relays the
        # remote command's); None means success
        rc = VERBS[args.verb](client, args, out)
        return int(rc or 0)
    except APIStatusError as e:
        print(f"Error from server: {e}", file=sys.stderr)
        return 1
    except ManifestError as e:
        # manifest problems (unknown kind, unserved apiVersion): CLI
        # error with exit code 1, matching real kubectl; other
        # ValueErrors are internal bugs and keep their traceback
        print(f"error: {e}", file=sys.stderr)
        return 1
    except OSError as e:
        # local-side failures (cp source missing, destination is a
        # directory, port in use): CLI error, not a traceback
        print(f"error: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
