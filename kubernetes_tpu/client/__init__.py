"""Client runtime: REST client, reflector/informers, workqueue,
leader election, event recording.

Analog of staging/src/k8s.io/client-go: the layer every control-plane
component uses to speak to the apiserver and run level-triggered loops.
"""

from .rest import APIStatusError, RESTClient
from .reflector import Reflector, RemoteStore
from .workqueue import DelayingQueue, ItemExponentialFailureRateLimiter, RateLimitingQueue
from .leaderelection import LeaderElector
from .record import EventRecorder
