"""Rotating client certificates.

Reference: client-go util/certificate/certificate_manager.go (used by
the kubelet through pkg/kubelet/certificate/kubelet.go): the manager
owns the current key+cert, computes a rotation deadline inside the
cert's validity window, and — once past it — generates a fresh key,
submits a CSR under the CURRENT credential, waits for the signed cert,
and atomically swaps. A kubelet that never rotated would fall off the
cluster the moment its bootstrap cert expired.
"""

from __future__ import annotations

import datetime
import secrets
import threading
import time
from typing import Callable, List, Optional, Tuple


class CertificateManager:
    """Owns a client identity and rotates it through the cluster's CSR
    flow. `submit` is the transport seam: (csr_name, csr_pem,
    current_identity) -> signed cert PEM (blocking until the
    approver+signer controllers act), over REST or an in-process
    store."""

    def __init__(self, common_name: str,
                 organizations: Tuple[str, ...],
                 key_pem: str, cert_pem: str,
                 submit: Callable[[str, str, Tuple[str, str]], str],
                 rotation_fraction: float = 0.8,
                 clock: Callable[[], float] = time.time):
        self.common_name = common_name
        self.organizations = tuple(organizations)
        self._key_pem = key_pem
        self._cert_pem = cert_pem
        self._submit = submit
        self.rotation_fraction = rotation_fraction
        self.clock = clock
        self._lock = threading.Lock()
        self._on_rotate: List[Callable[[str, str], None]] = []
        self.rotations = 0
        # failure observability: a signer outage must be visible BEFORE
        # the cert expires and the kubelet falls off the cluster
        self.failed_rotations = 0
        self.last_error: Optional[str] = None
        self._rotating = threading.Event()

    # -- identity --------------------------------------------------------------

    def current(self) -> Tuple[str, str]:
        with self._lock:
            return self._key_pem, self._cert_pem

    def on_rotate(self, fn: Callable[[str, str], None]):
        """Register a (key_pem, cert_pem) callback — consumers rebuild
        their TLS contexts here (the reference's connection-dropping
        CertCallback analog)."""
        self._on_rotate.append(fn)

    # -- rotation decision (certificate_manager.go nextRotationDeadline) -------

    def _validity(self) -> Tuple[float, float]:
        from cryptography import x509

        cert = x509.load_pem_x509_certificate(self._cert_pem.encode())
        nb = cert.not_valid_before_utc.timestamp()
        na = cert.not_valid_after_utc.timestamp()
        return nb, na

    def rotation_deadline(self) -> float:
        """notBefore + fraction * lifetime — past this point every
        maybe_rotate attempts renewal."""
        nb, na = self._validity()
        return nb + self.rotation_fraction * (na - nb)

    def should_rotate(self, now: Optional[float] = None) -> bool:
        now = self.clock() if now is None else now
        return now >= self.rotation_deadline()

    # -- the rotation ----------------------------------------------------------

    def maybe_rotate(self, now: Optional[float] = None) -> bool:
        """Rotate when due. Returns True when a NEW cert was installed;
        a failed submission leaves the current identity untouched (the
        manager retries on the next call, like the reference's
        wait/retry loop)."""
        if not self.should_rotate(now):
            return False
        from ..server import pki

        new_key, csr_pem = pki.make_csr(self.common_name,
                                        self.organizations)
        csr_name = (f"{self.common_name.replace(':', '-')}"
                    f"-rotate-{secrets.token_hex(4)}")
        try:
            new_cert = self._submit(csr_name, csr_pem, self.current())
        except Exception as e:
            self.failed_rotations += 1
            self.last_error = f"{type(e).__name__}: {e}"
            import logging
            logging.getLogger(__name__).warning(
                "certificate rotation for %s failed (attempt %d): %s",
                self.common_name, self.failed_rotations, self.last_error)
            return False
        if not new_cert:
            self.failed_rotations += 1
            self.last_error = "signer returned no certificate"
            return False
        with self._lock:
            self._key_pem, self._cert_pem = new_key, new_cert
            self.rotations += 1
            self.last_error = None
        for fn in list(self._on_rotate):
            fn(new_key, new_cert)
        return True

    def rotate_in_background(self, now: Optional[float] = None) -> bool:
        """Heartbeat-safe entry point: when rotation is due, run it on
        a daemon thread so a slow approver/signer can never stall the
        node heartbeat into NotReady (the reference rotates in its own
        goroutine). At most one rotation attempt runs at a time.
        Returns True when an attempt was started."""
        if not self.should_rotate(now) or self._rotating.is_set():
            return False
        self._rotating.set()

        def attempt():
            try:
                self.maybe_rotate(now)
            finally:
                self._rotating.clear()

        threading.Thread(target=attempt, daemon=True,
                         name="cert-rotation").start()
        return True


def rest_submitter(url: str, ca_cert_pem: str, timeout: float = 15.0):
    """The REST transport for CertificateManager.submit: create the CSR
    under the CURRENT mTLS identity (a live kubelet renews with its own
    cert — no bootstrap token needed, pkg/kubelet/certificate) and poll
    for the signed certificate."""
    from .rest import RESTClient
    from ..api import types as api

    def submit(csr_name: str, csr_pem: str,
               identity: Tuple[str, str]) -> str:
        key_pem, cert_pem = identity
        client = RESTClient(url, client_cert_pem=cert_pem,
                            client_key_pem=key_pem,
                            ca_cert_pem=ca_cert_pem)
        client.create("certificatesigningrequests",
                      api.CertificateSigningRequest(
                          metadata=api.ObjectMeta(name=csr_name,
                                                  namespace=""),
                          spec=api.CertificateSigningRequestSpec(
                              request=csr_pem,
                              usages=["digital signature",
                                      "key encipherment",
                                      "client auth"])))
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            got = client.get("certificatesigningrequests", "", csr_name)
            if got.status.certificate:
                return got.status.certificate
            time.sleep(0.05)
        raise TimeoutError(f"CSR {csr_name} was not signed "
                           f"within {timeout}s")

    return submit
