"""Leader election via a lease-record lock object.

Analog of client-go/tools/leaderelection/leaderelection.go:70: candidates
race to create/renew a LeaseRecord; the holder renews every retry_period,
others acquire when renew_time + lease_duration has expired. Optimistic
concurrency comes from the store's resourceVersion compare-and-swap
(resourcelock's Update on the annotation-carrying object).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from ..api import types as api
from ..runtime.store import Conflict


class LeaderElector:
    def __init__(self, store, identity: str, lock_name: str = "kube-scheduler",
                 lease_duration: float = 15.0, renew_deadline: float = 10.0,
                 retry_period: float = 2.0, clock=time.time,
                 on_started_leading: Optional[Callable[[], None]] = None,
                 on_stopped_leading: Optional[Callable[[], None]] = None):
        self.store = store
        self.identity = identity
        self.lock_name = lock_name
        self.lease_duration = lease_duration
        self.renew_deadline = renew_deadline
        self.retry_period = retry_period
        self.clock = clock
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self.is_leader = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lock record access (resourcelock analog) ------------------------------

    def _get(self) -> Optional[api.LeaseRecord]:
        for ns in ("default", ""):
            rec = self.store.get("leases", ns, self.lock_name)
            if rec is not None:
                return rec
        return None

    def _try_acquire_or_renew(self) -> bool:
        """leaderelection.go:221 tryAcquireOrRenew."""
        now = self.clock()
        rec = self._get()
        if rec is None:
            rec = api.LeaseRecord(
                metadata=api.ObjectMeta(name=self.lock_name),
                holder_identity=self.identity,
                lease_duration_seconds=self.lease_duration,
                acquire_time=now, renew_time=now)
            try:
                self.store.create("leases", rec)
                return True
            except Conflict:
                return False
        if rec.holder_identity != self.identity:
            if now < rec.renew_time + rec.lease_duration_seconds:
                return False  # held by a live leader
            transitions = rec.leader_transitions + 1
            acquire = now
        else:
            transitions = rec.leader_transitions
            acquire = rec.acquire_time
        new = api.LeaseRecord(
            metadata=rec.metadata, holder_identity=self.identity,
            lease_duration_seconds=self.lease_duration,
            acquire_time=acquire, renew_time=now,
            leader_transitions=transitions)
        try:
            self.store.update("leases", new,
                              expect_rv=rec.metadata.resource_version)
            return True
        except (Conflict, KeyError):
            return False

    # -- run loop --------------------------------------------------------------

    def run(self):
        """Block until leadership is acquired, call on_started_leading, then
        renew until renewal fails or stop() (leaderelection.go:148 Run)."""
        while not self._stop.is_set():
            if self._try_acquire_or_renew():
                break
            self._stop.wait(self.retry_period)
        if self._stop.is_set():
            return
        self.is_leader = True
        if self.on_started_leading:
            self.on_started_leading()
        last_renew = self.clock()
        while not self._stop.is_set():
            self._stop.wait(self.retry_period)
            if self._stop.is_set():
                break
            if self._try_acquire_or_renew():
                last_renew = self.clock()
            elif self.clock() - last_renew > self.renew_deadline:
                break  # lost the lease
        self.is_leader = False
        if self.on_stopped_leading:
            self.on_stopped_leading()

    def start(self) -> "LeaderElector":
        self._thread = threading.Thread(target=self.run, daemon=True,
                                        name=f"leaderelection-{self.identity}")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
