"""Leader election via a lease-record lock object.

Analog of client-go/tools/leaderelection/leaderelection.go:70: candidates
race to create/renew a LeaseRecord; the holder renews every retry_period,
others acquire when renew_time + lease_duration has expired. Optimistic
concurrency comes from the store's resourceVersion compare-and-swap
(resourcelock's Update on the annotation-carrying object).

Failover semantics (beyond leaderelection.go, whose Run returns after
one leadership and expects the process to exit — OnStoppedLeading is
documented as the hook to crash from): run() here LOOPS — lose the
lease, fire on_stopped_leading, go back to candidate mode, and fire
on_started_leading again on re-acquisition. That cycle is what lets the
scheduler warm-restart: dormant on loss (informers stay hot), a
recovery pass + resume on re-acquisition, instead of a cold process
restart and a full relist storm.

Renew/acquire attempts are hardened: any store/transport error during
the attempt — including the `lease.renew` chaos fault point — counts as
a failed renewal (the renew_deadline clock keeps running), never as a
crashed elector thread. An apiserver flap shorter than renew_deadline
therefore costs nothing; a longer one demotes the leader cleanly.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

from ..api import types as api
from ..runtime.store import Conflict
from ..utils import faultpoints


class LeaderElector:
    def __init__(self, store, identity: str, lock_name: str = "kube-scheduler",
                 lease_duration: float = 15.0, renew_deadline: float = 10.0,
                 retry_period: float = 2.0, clock=time.time,
                 on_started_leading: Optional[Callable[[], None]] = None,
                 on_stopped_leading: Optional[Callable[[], None]] = None):
        self.store = store
        self.identity = identity
        self.lock_name = lock_name
        self.lease_duration = lease_duration
        self.renew_deadline = renew_deadline
        self.retry_period = retry_period
        self.clock = clock
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self.is_leader = False
        self.leaderships = 0  # acquisitions over this elector's lifetime
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lock record access (resourcelock analog) ------------------------------

    def _get(self) -> Optional[api.LeaseRecord]:
        for ns in ("default", ""):
            rec = self.store.get("leases", ns, self.lock_name)
            if rec is not None:
                return rec
        return None

    def _try_acquire_or_renew(self) -> bool:
        """leaderelection.go:221 tryAcquireOrRenew, hardened: transport
        and store errors are a failed attempt, not a crashed elector —
        the reference gets the same effect from wrapping every lock
        access in error returns that tryAcquireOrRenew maps to false."""
        try:
            # chaos seam: `raise` models the apiserver rejecting/failing
            # the renew round trip, `latency` a slow one that eats into
            # the renew_deadline budget
            faultpoints.fire("lease.renew")
            return self._acquire_or_renew_once()
        except (Conflict, KeyError):
            return False
        except Exception as e:
            logging.getLogger(__name__).warning(
                "lease acquire/renew attempt failed for %s: %s: %s",
                self.identity, type(e).__name__, e)
            return False

    def _acquire_or_renew_once(self) -> bool:
        now = self.clock()
        rec = self._get()
        if rec is None:
            rec = api.LeaseRecord(
                metadata=api.ObjectMeta(name=self.lock_name),
                holder_identity=self.identity,
                lease_duration_seconds=self.lease_duration,
                acquire_time=now, renew_time=now)
            try:
                self.store.create("leases", rec)
                return True
            except Conflict:
                return False
        if rec.holder_identity != self.identity:
            if now < rec.renew_time + rec.lease_duration_seconds:
                return False  # held by a live leader
            transitions = rec.leader_transitions + 1
            acquire = now
        else:
            transitions = rec.leader_transitions
            acquire = rec.acquire_time
        new = api.LeaseRecord(
            metadata=rec.metadata, holder_identity=self.identity,
            lease_duration_seconds=self.lease_duration,
            acquire_time=acquire, renew_time=now,
            leader_transitions=transitions)
        try:
            self.store.update("leases", new,
                              expect_rv=rec.metadata.resource_version)
            return True
        except (Conflict, KeyError):
            return False

    # -- run loop --------------------------------------------------------------

    def run(self):
        """Candidate -> leader -> demoted -> candidate, until stop():
        acquire (blocking), fire on_started_leading, renew every
        retry_period until renewal has failed for renew_deadline, fire
        on_stopped_leading, and go back to acquiring. Each full cycle is
        one warm-restart opportunity for the callbacks' owner."""
        while not self._stop.is_set():
            if not self._acquire():
                return  # stopped while a candidate
            self.is_leader = True
            self.leaderships += 1
            if self.on_started_leading:
                self.on_started_leading()
            self._renew_until_lost()
            self.is_leader = False
            if self.on_stopped_leading:
                self.on_stopped_leading()

    def _acquire(self) -> bool:
        """Block until the lease is acquired; False = stopped first."""
        while not self._stop.is_set():
            if self._try_acquire_or_renew():
                return True
            self._stop.wait(self.retry_period)
        return False

    def _renew_until_lost(self):
        """Renew until stop() or the lease is lost: renewals failing for
        longer than renew_deadline (leaderelection.go:263 renew loop)."""
        last_renew = self.clock()
        while not self._stop.is_set():
            self._stop.wait(self.retry_period)
            if self._stop.is_set():
                return
            if self._try_acquire_or_renew():
                last_renew = self.clock()
            elif self.clock() - last_renew > self.renew_deadline:
                logging.getLogger(__name__).warning(
                    "leader %s lost the %s lease: no successful renew in "
                    "%.1fs (deadline %.1fs)", self.identity, self.lock_name,
                    self.clock() - last_renew, self.renew_deadline)
                return

    def start(self) -> "LeaderElector":
        self._thread = threading.Thread(target=self.run, daemon=True,
                                        name=f"leaderelection-{self.identity}")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
