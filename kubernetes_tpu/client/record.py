"""Event recording.

Analog of client-go/tools/record/event.go:56 EventRecorder: components
emit (reason, message) events about API objects; correlated duplicates
are aggregated by bumping count/lastTimestamp instead of creating new
objects (events_cache.go EventAggregator).
"""

from __future__ import annotations

import time
from typing import Optional

from ..api import scheme
from ..api import types as api
from ..runtime.store import Conflict


class EventRecorder:
    def __init__(self, store, source_component: str, clock=time.time):
        self.store = store
        self.source = source_component
        self.clock = clock

    def event(self, obj, event_type: str, reason: str, message: str):
        """Record an event about obj (Normal or Warning)."""
        kind = scheme.kind_of(obj) or type(obj).__name__
        meta = obj.metadata
        name = f"{meta.name}.{reason.lower()}.{self.source}"
        ns = meta.namespace or "default"
        now = self.clock()
        existing = self.store.get("events", ns, name)
        if existing is not None:
            # same correlation key: bump count, take the latest message
            # (events_cache.go eventObserve)
            existing.count += 1
            existing.message = message
            existing.last_timestamp = now
            try:
                self.store.update("events", existing)
            except (Conflict, KeyError):
                pass
            return
        ev = api.EventObject(
            metadata=api.ObjectMeta(name=name, namespace=ns),
            involved_kind=kind, involved_name=meta.name,
            involved_namespace=meta.namespace,
            reason=reason, message=message, type=event_type,
            source_component=self.source,
            first_timestamp=now, last_timestamp=now)
        try:
            self.store.create("events", ev)
        except Conflict:
            existing = self.store.get("events", ns, name)
            if existing is not None:
                existing.count += 1
                existing.message = message
                existing.last_timestamp = now
                try:
                    self.store.update("events", existing)
                except (Conflict, KeyError):
                    pass
