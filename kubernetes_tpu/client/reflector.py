"""Reflector + remote informer store.

Reflector is the list+watch resync loop (client-go/tools/cache/
reflector.go:49 ListAndWatch): list to seed, then watch from the list's
resourceVersion, relist on 410 Gone. It feeds either plain handlers or a
RemoteStore — an ObjectStore-shaped facade that lets every in-process
component (Scheduler, controllers, kubelets) run unchanged against the
HTTP apiserver: reads hit the local mirror (informer cache), writes go
over REST, and watch callbacks fire from reflector threads (the
sharedProcessor fan-out, shared_informer.go:375).

Stream hardening (the reference's reflector.go backoffManager +
timeoutSeconds jitter, grown here after PR 2's device-path work left
this loop as the last silent failure path):

  * relist errors back off exponentially with +/-50% jitter (the old
    fixed 0.5s sleep hammered a flapping apiserver in lockstep with
    every OTHER reflector in the fleet) and are LOGGED with traceback +
    counted in scheduling_errors_total{stage=reflector} — a reflector
    dying quietly starves the scheduler of events with no signal;
  * a staleness watchdog forces a full relist when the watch stream has
    produced nothing past `stale_after` — a wedged-but-open stream (half
    -closed TCP, a proxy eating frames) otherwise looks identical to a
    quiet cluster forever (`watch_stale_total`);
  * every list+watch cycle is counted (`reflector_relists_total`) and
    chaos-drivable via the `reflector.relist` fault point.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from typing import Callable, Dict, List, Optional

from ..api import scheme
from ..api import types as api
from ..runtime.store import ADDED, DELETED, MODIFIED, Conflict, Event
from ..utils import faultpoints
from ..utils.backoff import exp_step, jittered
from .rest import APIStatusError, RESTClient


class Reflector:
    def __init__(self, client: RESTClient, plural: str,
                 on_event: Callable[[Event], None],
                 relist_backoff: float = 0.5,
                 max_relist_backoff: float = 30.0,
                 stale_after: float = 60.0,
                 watch_timeout: float = 10.0,
                 list_timeout: Optional[float] = None,
                 metrics=None,
                 health=None,
                 clock: Callable[[], float] = time.monotonic,
                 jitter: Callable[[], float] = random.random):
        self.client = client
        self.plural = plural
        self.on_event = on_event
        self.relist_backoff = relist_backoff
        self.max_relist_backoff = max_relist_backoff
        # per-relist budget for the LIST request (None = the client's
        # socket default): a hung LIST during an apiserver outage must
        # fail within the cycle so the backoff ladder and staleness
        # accounting keep moving
        self.list_timeout = list_timeout
        # optional sched.storehealth.StorePathBreaker: relist outcomes
        # feed the consecutive-failure count on the LIST path
        self.health = health
        # watchdog deadline: a stream with no events for this long is
        # declared stale and torn down for a relist. Must exceed the
        # per-stream server timeout (watch_timeout) by a healthy margin
        # or an idle cluster would relist every cycle.
        self.stale_after = stale_after
        self.watch_timeout = watch_timeout
        self.metrics = metrics  # utils.metrics.Metrics (or None)
        self.clock = clock
        self.jitter = jitter
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # the live rung of the relist ladder — exposed (not a run()
        # local) so outage tests can assert the ladder capped at
        # max_relist_backoff and that the first post-heal relist reset it
        self.backoff = relist_backoff
        self.last_sync_rv = 0
        self.synced = threading.Event()  # set after the first list completes
        self.relists = 0       # list+watch cycles entered
        self.stale_relists = 0  # of those, forced by the staleness watchdog
        self._known: Dict[str, object] = {}

    @staticmethod
    def _key(obj) -> str:
        return f"{obj.metadata.namespace}/{obj.metadata.name}"

    def start(self) -> "Reflector":
        self._thread = threading.Thread(target=self.run, daemon=True,
                                        name=f"reflector-{self.plural}")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()

    def _record_error(self, exc: BaseException):
        """A failed list+watch cycle is never silent: traceback to the
        log, stage=reflector into the labelled error series (matching
        the PR 2 bind/wave/extender attribution), and — when a store-
        path breaker is wired — one consecutive-failure tick on the
        LIST path."""
        if self.metrics is not None:
            self.metrics.scheduling_errors.labels(stage="reflector").inc()
            self.metrics.store_errors.labels(op="list").inc()
        if self.health is not None:
            self.health.record_failure()
        logging.getLogger(__name__).error(
            "reflector %s: list+watch failed: %s: %s", self.plural,
            type(exc).__name__, exc, exc_info=exc)

    def _backoff_wait(self, backoff: float) -> float:
        """Sleep a jittered backoff (interruptible by stop()) and return
        the next, doubled backoff (utils/backoff.py — the one shared
        ladder shape). Jitter spans 0.5x-1.5x so a fleet of reflectors
        knocked over by one apiserver flap doesn't relist in lockstep
        forever after."""
        self._stop.wait(jittered(backoff, self.jitter))
        return exp_step(backoff, self.max_relist_backoff)

    def run(self):
        self.backoff = self.relist_backoff
        while not self._stop.is_set():
            try:
                # chaos seam: a `raise` here fails the whole cycle before
                # the list — the repeated-relist-failure scenario the
                # exponential backoff exists for
                faultpoints.fire("reflector.relist")
                self._list_and_watch()
                self.backoff = self.relist_backoff  # clean cycle: reset
            except APIStatusError as e:
                if e.code == 410:
                    # expected expiry: relist immediately, and a clean
                    # list resets the backoff ladder
                    self.backoff = self.relist_backoff
                    continue
                self._record_error(e)
                self.backoff = self._backoff_wait(self.backoff)
            except Exception as e:
                self._record_error(e)
                self.backoff = self._backoff_wait(self.backoff)

    def _list(self):
        faultpoints.fire("store.outage", payload=("list", self.plural))
        if self.list_timeout is not None:
            return self.client.list(self.plural, timeout=self.list_timeout)
        return self.client.list(self.plural)

    def _list_and_watch(self):
        self.relists += 1
        if self.metrics is not None:
            self.metrics.reflector_relists.inc()
        items, rv = self._list()
        if self.health is not None:
            self.health.record_success()  # the store answered a LIST
        # delta replay against the known set (DeltaFIFO Replace semantics,
        # tools/cache/delta_fifo.go Replace: sync adds + implicit deletes)
        new_keys = set()
        for obj in items:
            key = self._key(obj)
            new_keys.add(key)
            old = self._known.get(key)
            if old is None:
                self.on_event(Event(ADDED, self.plural, obj))
            elif old.metadata.resource_version != obj.metadata.resource_version:
                self.on_event(Event(MODIFIED, self.plural, obj, old=old))
            self._known[key] = obj
        for key in list(self._known):
            if key not in new_keys:
                self.on_event(Event(DELETED, self.plural, self._known.pop(key)))
        self.last_sync_rv = rv
        self.synced.set()
        last_progress = self.clock()
        while not self._stop.is_set():
            if self.clock() - last_progress > self.stale_after:
                # staleness watchdog: streams keep opening cleanly but
                # deliver nothing — indistinguishable from an idle
                # cluster except by relisting and comparing. Tear the
                # cycle down; run() re-enters with a fresh list.
                self.stale_relists += 1
                if self.metrics is not None:
                    self.metrics.watch_stale.inc()
                logging.getLogger(__name__).warning(
                    "reflector %s: watch quiet past %.1fs staleness "
                    "deadline; forcing a relist", self.plural,
                    self.stale_after)
                return
            # the stream ends on server timeoutSeconds; re-arm from last rv
            for etype, obj in self.client.watch(
                    self.plural, resource_version=rv,
                    timeout_seconds=self.watch_timeout, stop=self._stop):
                last_progress = self.clock()
                rv = max(rv, obj.metadata.resource_version)
                self.last_sync_rv = rv
                key = self._key(obj)
                if etype == DELETED:
                    self._known.pop(key, None)
                    self.on_event(Event(DELETED, self.plural, obj))
                elif key in self._known:
                    old = self._known[key]
                    self._known[key] = obj
                    if etype == ADDED or \
                            old.metadata.resource_version != obj.metadata.resource_version:
                        self.on_event(Event(MODIFIED, self.plural, obj, old=old))
                else:
                    self._known[key] = obj
                    self.on_event(Event(ADDED, self.plural, obj))


class RemoteStore:
    """ObjectStore facade backed by the HTTP apiserver.

    Components written against runtime.ObjectStore (scheduler, controllers,
    kubelet) run unchanged: list/get serve from reflector-maintained local
    mirrors; create/update/delete/bind go over REST; watch() handlers fire
    from the reflector threads. mirror(kind) must be called (or implied by
    watch()) before reads of that kind."""

    # binds are real HTTP posts and watch events arrive on reflector
    # threads with no store lock held during handler dispatch — safe (and
    # worthwhile) to post binds from the scheduler's worker pool
    async_bind_safe = True

    # per-attempt deadline on the bind POST: a hung bind must surface as
    # a retryable error inside the reconciler's budget, not stall a
    # binder thread for the full 30s default socket timeout
    bind_timeout = 5.0

    # per-relist deadline on the reflector LIST: during an outage the
    # relist must fail fast enough that the backoff ladder (capped at
    # 30s) is what paces recovery, not the socket default stacked on it
    list_timeout = 15.0

    def __init__(self, client: RESTClient, metrics=None,
                 stale_after: float = 60.0):
        self.client = client
        # shared utils.metrics.Metrics registry: reflector relist/stale
        # counters and stage=reflector errors land next to the
        # scheduler's own series on the same /metrics endpoint
        self.metrics = metrics
        self.stale_after = stale_after
        # optional sched.storehealth.StorePathBreaker, assigned by the
        # CLI after the scheduler is built (the scheduler owns the
        # breaker; this store feeds it from the write + LIST paths —
        # bind outcomes are fed by the reconciler seam instead, so one
        # failed POST is never double-counted)
        self.health = None
        self._lock = threading.RLock()
        self._mirrors: Dict[str, Dict[str, object]] = {}
        self._watchers: List[tuple] = []
        self._reflectors: Dict[str, Reflector] = {}

    # -- mirror management -----------------------------------------------------

    def mirror(self, kind: str) -> "RemoteStore":
        with self._lock:
            if kind in self._reflectors:
                return self
            self._mirrors[kind] = {}
            refl = Reflector(self.client, kind, self._on_event,
                             metrics=self.metrics,
                             stale_after=self.stale_after,
                             list_timeout=self.list_timeout,
                             health=self.health)
            self._reflectors[kind] = refl
            refl.start()
        return self

    def set_health(self, breaker) -> None:
        """Wire a StorePathBreaker after construction (the scheduler —
        which owns the breaker — is built against an already-mirroring
        store, so existing reflectors must pick it up too)."""
        with self._lock:
            self.health = breaker
            for refl in self._reflectors.values():
                refl.health = breaker

    def stop(self):
        for refl in self._reflectors.values():
            refl.stop()

    def wait_for_sync(self, timeout: float = 5.0) -> bool:
        """True if every mirror completed its initial list (informer
        HasSynced). rv is not the sentinel — an empty store lists at rv=0."""
        deadline = time.monotonic() + timeout
        ok = True
        for refl in list(self._reflectors.values()):
            left = max(0.0, deadline - time.monotonic())
            ok = refl.synced.wait(left) and ok
        return ok

    def _on_event(self, ev: Event):
        with self._lock:
            objs = self._mirrors.setdefault(ev.kind, {})
            key = f"{ev.obj.metadata.namespace}/{ev.obj.metadata.name}"
            if ev.type == DELETED:
                objs.pop(key, None)
            else:
                objs[key] = ev.obj
            watchers = list(self._watchers)
        for kind, fn in watchers:
            if kind is None or kind == ev.kind:
                fn(ev)

    # -- ObjectStore interface -------------------------------------------------

    def watch(self, kind: Optional[str], fn: Callable[[Event], None]):
        if kind is not None:
            self.mirror(kind)
        with self._lock:
            self._watchers.append((kind, fn))

    def unwatch(self, fn: Callable[[Event], None]):
        with self._lock:
            # equality, not identity: bound methods are recreated per
            # attribute access and only compare equal
            self._watchers = [(k, f) for k, f in self._watchers
                              if f != fn]

    def list(self, kind: str, namespace: Optional[str] = None) -> List[object]:
        self.mirror(kind)
        with self._lock:
            objs = self._mirrors.get(kind, {})
            if namespace is None:
                return list(objs.values())
            prefix = namespace + "/"
            return [o for k, o in objs.items() if k.startswith(prefix)]

    def get(self, kind: str, namespace: str, name: str):
        self.mirror(kind)
        with self._lock:
            return self._mirrors.get(kind, {}).get(f"{namespace}/{name}")

    def count(self, kind: str) -> int:
        return len(self.list(kind))

    @property
    def latest_resource_version(self) -> int:
        with self._lock:
            return max((r.last_sync_rv for r in self._reflectors.values()),
                       default=0)

    def _guard(self, op: str, fn):
        """Run one REST op under store-path accounting: the
        `store.outage` fault point fires first (raise = severed
        transport, latency = a slow apiserver), transport failures
        count into store_errors_total{op} and the breaker's consecutive
        count, and ANY server answer — including a 409/404
        APIStatusError — counts as the store being reachable."""
        faultpoints.fire("store.outage", payload=op)
        try:
            out = fn()
        except APIStatusError:
            if self.health is not None:
                self.health.record_success()
            raise
        except Exception:
            if self.metrics is not None:
                self.metrics.store_errors.labels(op=op).inc()
            if self.health is not None:
                self.health.record_failure()
            raise
        if self.health is not None:
            self.health.record_success()
        return out

    def create(self, kind: str, obj) -> object:
        try:
            return self._guard("create", lambda: self.client.create(kind, obj))
        except APIStatusError as e:
            if e.code == 409:
                raise Conflict(str(e))
            raise

    def update(self, kind: str, obj, expect_rv: Optional[int] = None) -> object:
        import copy
        obj = copy.copy(obj)
        obj.metadata = copy.copy(obj.metadata)
        if expect_rv is not None:
            # carry the CAS revision on the wire object so the server's
            # resourceVersion check enforces it (GuaranteedUpdate contract)
            obj.metadata.resource_version = expect_rv
            try:
                return self.client.update(kind, obj)
            except APIStatusError as e:
                if e.code == 409:
                    raise Conflict(str(e))
                raise
        # expect_rv=None: last-writer-wins like ObjectStore.update, but
        # via refetch-and-retry CAS (NativeObjectStore.update parity) so
        # writes stay properly serialized — a stale mirror rv must not
        # 409 into Conflict-swallowing callers (they'd silently drop the
        # write), and skipping the rv check entirely would let a single
        # round trip clobber unseen concurrent revisions without even
        # ordering them
        for _ in range(16):
            try:
                return self.client.update(kind, obj)
            except APIStatusError as e:
                if e.code != 409:
                    raise
                try:
                    cur = self.client.get(kind, obj.metadata.namespace,
                                          obj.metadata.name)
                except APIStatusError as ge:
                    if ge.code == 404:
                        # deleted between the 409 and the refetch: callers
                        # expect ObjectStore.update's KeyError here
                        raise KeyError(
                            f"{kind} {obj.metadata.name} not found")
                    raise
                obj.metadata.resource_version = \
                    cur.metadata.resource_version
        raise Conflict(f"{kind} {obj.metadata.name}: CAS retries exhausted")

    def delete(self, kind: str, namespace: str, name: str):
        try:
            self._guard("delete",
                        lambda: self.client.delete(kind, namespace, name))
        except APIStatusError as e:
            if e.code == 404:
                raise KeyError(f"{kind} {namespace}/{name} not found")
            raise

    def bind(self, pod: api.Pod, node_name: str):
        # no breaker recording and no store.outage fire here: bind
        # outcomes are fed to the breaker by the scheduler's reconciler
        # seam (per POST attempt), and the fault point fires at the
        # scheduler's bind/truth seams — both cover this path AND the
        # in-process ObjectStore; doubling them here would double-count
        # failures and burn injected `times` budgets twice per attempt
        try:
            self.client.bind(pod.metadata.namespace, pod.metadata.name,
                             node_name, timeout=self.bind_timeout)
        except APIStatusError as e:
            if e.code == 409:
                raise Conflict(str(e))
            if e.code == 404:
                raise KeyError(f"pod {pod.full_name()} not found")
            raise

    def set_pod_condition(self, pod: api.Pod, cond):
        try:
            self.client.patch("pods", pod.metadata.namespace, pod.metadata.name,
                              {"status": {"conditions": [list(cond)]}})
        except APIStatusError:
            pass

    def set_nominated_node(self, pod: api.Pod, node_name: str):
        try:
            self.client.patch("pods", pod.metadata.namespace, pod.metadata.name,
                              {"status": {"nominatedNodeName": node_name}})
        except APIStatusError:
            pass
