"""REST client over the API server.

Analog of client-go's rest.RESTClient + typed clientset verbs
(client-go/rest/client.go, kubernetes/typed/core/v1): List/Get/Create/
Update/Patch/Delete plus the pod binding and eviction subresources, and
a streaming Watch that decodes JSON-lines watch events.
"""

from __future__ import annotations

import json
import socket
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, Iterator, List, Optional, Tuple

from ..api import scheme
from ..utils import faultpoints


def _selector_query(label_selector=None, field_selector=None) -> List[str]:
    """Selector args (dict or raw string) -> query fragments. One
    encoder for list() and delete_collection() — the safe-char set
    keeps set-based syntax (`in (a,b)`, `!key`) readable server-side."""
    from urllib.parse import quote

    def enc(sel):
        if isinstance(sel, str):
            return quote(sel, safe="=,!()")
        return quote(",".join(f"{k}={v}" for k, v in sel.items()),
                     safe="=,")

    q = []
    if label_selector:
        q.append("labelSelector=" + enc(label_selector))
    if field_selector:
        q.append("fieldSelector=" + enc(field_selector))
    return q


def pem_arg(v):
    """CLI PEM argument: literal PEM text, or @/path/to/file."""
    if v and v.startswith("@"):
        with open(v[1:]) as f:
            return f.read()
    return v


class APIStatusError(Exception):
    def __init__(self, code: int, reason: str, message: str):
        super().__init__(f"{code} {reason}: {message}")
        self.code, self.reason, self.message = code, reason, message


class RESTClient:
    def __init__(self, base_url: str, token: Optional[str] = None,
                 user_agent: str = "kubernetes-tpu-client",
                 binary: bool = False,
                 client_cert_pem: Optional[str] = None,
                 client_key_pem: Optional[str] = None,
                 ca_cert_pem: Optional[str] = None,
                 insecure_skip_verify: bool = False):
        """binary=True negotiates the compact binary wire codec for GETs
        (api/binary.py — the reference's
        application/vnd.kubernetes.protobuf role).

        TLS (https base_url): ca_cert_pem is the kubeconfig
        certificate-authority-data analog — the server's chain must
        verify against it. client_cert_pem + client_key_pem form an
        x509 client credential issued by the cluster CA (kubeadm join /
        CSR flow), presented in the TLS handshake (mTLS); the server
        reads the identity from the verified peer chain.
        insecure_skip_verify skips server verification — used only by
        kubeadm join's trust-on-first-use cluster-info fetch."""
        self.base_url = base_url.rstrip("/")
        self.token = token
        self.user_agent = user_agent
        self.binary = binary
        self._ssl_ctx = None
        if self.base_url.startswith("https"):
            if insecure_skip_verify:
                import ssl

                self._ssl_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
                self._ssl_ctx.check_hostname = False
                self._ssl_ctx.verify_mode = ssl.CERT_NONE
                if client_cert_pem and client_key_pem:
                    from ..server.pki import _load_cert_chain

                    _load_cert_chain(self._ssl_ctx, client_cert_pem,
                                     client_key_pem)
            elif ca_cert_pem:
                from ..server import pki

                self._ssl_ctx = pki.client_ssl_context(
                    ca_cert_pem, client_cert_pem, client_key_pem)
            else:
                raise ValueError(
                    "https server requires ca_cert_pem (or, for the "
                    "bootstrap cluster-info fetch, insecure_skip_verify)")
        elif client_cert_pem or client_key_pem:
            # an x509 credential only authenticates through a TLS
            # handshake; silently dropping it over plain http would turn
            # this client into system:anonymous with no indication
            raise ValueError(
                "client_cert_pem/client_key_pem require an https server "
                "(x509 identity comes from the TLS handshake)")

    # -- plumbing --------------------------------------------------------------

    def _path(self, plural: str, namespace: Optional[str], name: Optional[str],
              sub: Optional[str] = None) -> str:
        kind = scheme.kind_for_plural(plural)
        if kind is None:
            # unknown plural (e.g. a CRD this client hasn't discovered):
            # send a core-group request and let the server answer 404 —
            # a URL-building crash would mask the real error
            parts = ["/api/v1"]
            if namespace is not None:
                parts.append(f"namespaces/{namespace}")
            parts.append(plural)
            if name:
                parts.append(name)
            return "/".join(parts)
        ver = scheme.api_version_for(kind)
        prefix = f"/api/{ver}" if "/" not in ver else f"/apis/{ver}"
        parts = [prefix]
        if namespace is not None and scheme.is_namespaced(kind):
            parts.append(f"namespaces/{namespace}")
        parts.append(plural)
        if name:
            parts.append(name)
        if sub:
            parts.append(sub)
        return "/".join(parts)

    def request_bytes(self, method: str, path: str,
                      body: Optional[dict] = None, query: str = "",
                      accept: Optional[str] = None,
                      timeout: Optional[float] = None):
        """Raw round trip -> (body bytes, response Content-Type).
        `timeout` is the per-attempt socket deadline (default 30s) —
        binds pass a tighter one so a hung POST turns into a retryable
        error instead of stalling a binder thread for half a minute."""
        # chaos seam: an armed `rest.request` fault fails (or delays)
        # every control-plane round trip — the apiserver-flap scenario
        # the reflector backoff and bind reconciler exist to absorb.
        # `drop` models the request never reaching the wire.
        if faultpoints.fire("rest.request", payload=(method, path)):
            raise OSError(f"rest.request fault: {method} {path} dropped")
        url = self.base_url + path + (f"?{query}" if query else "")
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Content-Type", "application/json")
        req.add_header("User-Agent", self.user_agent)
        if accept:
            req.add_header("Accept", accept)
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        try:
            with urllib.request.urlopen(req,
                                        timeout=30 if timeout is None
                                        else timeout,
                                        context=self._ssl_ctx) as resp:
                return resp.read(), resp.headers.get("Content-Type", "")
        except urllib.error.HTTPError as e:
            try:
                status = json.loads(e.read())
            except Exception:
                status = {}
            raise APIStatusError(e.code, status.get("reason", e.reason or ""),
                                 status.get("message", ""))

    def request(self, method: str, path: str, body: Optional[dict] = None,
                query: str = "", timeout: Optional[float] = None) -> dict:
        raw, _ = self.request_bytes(method, path, body=body, query=query,
                                    timeout=timeout)
        return json.loads(raw or b"{}")

    # -- verbs -----------------------------------------------------------------

    def list(self, plural: str, namespace: Optional[str] = None,
             label_selector=None, field_selector=None,
             timeout: Optional[float] = None
             ) -> Tuple[List[object], int]:
        """Returns (items, list resourceVersion). Selectors may be
        {key: value} dicts or raw selector STRINGS (set-based
        expressions like "tier in (a,b)" pass through verbatim to the
        server's parser). `timeout` bounds the whole request — callers
        on the leader loop (reflector relists, recovery truth checks)
        pass a budget so one hung LIST can't ride the 30s socket
        default."""
        return self._list_once(plural, namespace,
                               _selector_query(label_selector,
                                               field_selector),
                               timeout=timeout)

    def list_paged(self, plural: str, namespace: Optional[str] = None,
                   page_size: int = 500) -> Tuple[List[object], int]:
        """Chunked list (client-go tools/pager ListPager): walk
        ?limit=N/?continue pages until the server stops returning a
        continue token. Same result as list(), bounded peak payload."""
        kind = scheme.kind_for_plural(plural)
        items: List[object] = []
        cont = None
        while True:
            q = [f"limit={page_size}"]
            if cont:
                q.append(f"continue={cont}")
            path = self._path(plural, namespace, None)
            data = self.request("GET", path, query="&".join(q))
            items.extend(scheme.decode(kind, d)
                         for d in data.get("items", []))
            rv = int(data.get("metadata", {}).get("resourceVersion", "0"))
            cont = data.get("metadata", {}).get("continue")
            if not cont:
                return items, rv

    def _list_once(self, plural, namespace, q, timeout=None):
        path = self._path(plural, namespace, None)
        if self.binary:
            from ..api import binary

            raw, ctype = self.request_bytes("GET", path,
                                            query="&".join(q),
                                            accept=binary.CONTENT_TYPE,
                                            timeout=timeout)
            if ctype.startswith(binary.CONTENT_TYPE):
                return binary.loads_list(raw)
            data = json.loads(raw or b"{}")
        else:
            data = self.request("GET", path, query="&".join(q),
                                timeout=timeout)
        kind = scheme.kind_for_plural(plural)
        items = [scheme.decode(kind, d) for d in data.get("items", [])]
        rv = int(data.get("metadata", {}).get("resourceVersion", "0"))
        return items, rv

    def get(self, plural: str, namespace: Optional[str], name: str,
            timeout: Optional[float] = None):
        path = self._path(plural, namespace, name)
        if self.binary:
            from ..api import binary

            raw, ctype = self.request_bytes("GET", path,
                                            accept=binary.CONTENT_TYPE,
                                            timeout=timeout)
            if ctype.startswith(binary.CONTENT_TYPE):
                return binary.loads(raw)
            return scheme.decode(scheme.kind_for_plural(plural),
                                 json.loads(raw or b"{}"))
        data = self.request("GET", path, timeout=timeout)
        return scheme.decode(scheme.kind_for_plural(plural), data)

    def create(self, plural: str, obj, namespace: Optional[str] = None):
        ns = namespace if namespace is not None else getattr(
            obj.metadata, "namespace", None)
        data = self.request("POST", self._path(plural, ns, None),
                            body=scheme.encode_object(obj))
        return scheme.decode(scheme.kind_for_plural(plural), data)

    def update(self, plural: str, obj, sub: Optional[str] = None):
        path = self._path(plural, obj.metadata.namespace, obj.metadata.name, sub)
        data = self.request("PUT", path, body=scheme.encode_object(obj))
        return scheme.decode(scheme.kind_for_plural(plural), data)

    def update_status(self, plural: str, obj):
        return self.update(plural, obj, sub="status")

    def patch(self, plural: str, namespace: Optional[str], name: str,
              patch: dict):
        data = self.request("PATCH", self._path(plural, namespace, name),
                            body=patch)
        return scheme.decode(scheme.kind_for_plural(plural), data)

    def delete(self, plural: str, namespace: Optional[str], name: str,
               grace_period_seconds: Optional[int] = None):
        q = (f"gracePeriodSeconds={grace_period_seconds}"
             if grace_period_seconds is not None else "")
        self.request("DELETE", self._path(plural, namespace, name),
                     query=q)

    def delete_collection(self, plural: str,
                          namespace: Optional[str] = None,
                          label_selector=None, field_selector=None):
        """Server-side deletecollection (one request deletes every
        match; its own RBAC verb). Selectors as in list()."""
        self.request("DELETE", self._path(plural, namespace, None),
                     query="&".join(_selector_query(label_selector,
                                                    field_selector)))

    def get_scale(self, plural: str, namespace: Optional[str],
                  name: str) -> dict:
        """GET the polymorphic Scale subresource (scale client
        scaleclient.ScalesGetter analog)."""
        return self.request("GET", self._path(plural, namespace, name,
                                              sub="scale"))

    def update_scale(self, plural: str, namespace: Optional[str], name: str,
                     replicas: int) -> dict:
        return self.request(
            "PUT", self._path(plural, namespace, name, sub="scale"),
            body={"kind": "Scale", "apiVersion": "autoscaling/v1",
                  "spec": {"replicas": replicas}})

    def bind(self, namespace: str, pod_name: str, node_name: str,
             timeout: Optional[float] = None):
        """POST pods/<name>/binding (scheduler.go:409 Bind). `timeout`
        bounds the single attempt; retry policy lives in the caller's
        bind reconciler, not here."""
        self.request("POST", self._path("pods", namespace, pod_name, "binding"),
                     body={"kind": "Binding", "apiVersion": "v1",
                           "metadata": {"name": pod_name},
                           "target": {"kind": "Node", "name": node_name}},
                     timeout=timeout)

    def evict(self, namespace: str, pod_name: str):
        self.request("POST", self._path("pods", namespace, pod_name, "eviction"),
                     body={"kind": "Eviction", "apiVersion": "policy/v1beta1"})

    # -- watch -----------------------------------------------------------------

    def watch(self, plural: str, resource_version: Optional[int] = None,
              timeout_seconds: float = 30.0,
              stop: Optional[threading.Event] = None,
              label_selector=None
              ) -> Iterator[Tuple[str, object]]:
        """Yields (event_type, object). Returns when the server closes the
        stream (timeout) or `stop` is set. Raises APIStatusError(410) when
        the resourceVersion is too old — caller relists (reflector.go).
        label_selector filters server-side (transitions translate to
        ADDED/DELETED like the cacher)."""
        # same chaos seam as request_bytes: watch-stream establishment is
        # a REST round trip too (a faulting one exercises the reflector's
        # jittered relist backoff)
        if faultpoints.fire("rest.request", payload=("WATCH", plural)):
            raise OSError(f"rest.request fault: watch {plural} dropped")
        q = f"watch=true&timeoutSeconds={timeout_seconds:g}"
        if resource_version is not None:
            q += f"&resourceVersion={resource_version}"
        for frag in _selector_query(label_selector, None):
            q += "&" + frag
        url = self.base_url + self._path(plural, None, None) + "?" + q
        req = urllib.request.Request(url)
        req.add_header("User-Agent", self.user_agent)
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        kind = scheme.kind_for_plural(plural)
        try:
            resp = urllib.request.urlopen(req, timeout=timeout_seconds + 10,
                                          context=self._ssl_ctx)
        except urllib.error.HTTPError as e:
            try:
                status = json.loads(e.read())
            except Exception:
                status = {}
            raise APIStatusError(e.code, status.get("reason", e.reason or ""),
                                 status.get("message", ""))
        with resp:
            while stop is None or not stop.is_set():
                try:
                    line = resp.readline()
                except (socket.timeout, OSError):
                    return
                if not line:
                    return
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = json.loads(line)
                except json.JSONDecodeError:
                    continue
                yield ev["type"], scheme.decode(kind, ev["object"])
