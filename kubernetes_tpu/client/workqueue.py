"""Work queues: dedup + delaying + rate-limited retry.

Analog of client-go/util/workqueue: Type (queue.go:23 — dedup of dirty/
processing items), DelayingQueue (delaying_queue.go — AddAfter),
RateLimitingQueue (rate_limiting_queue.go — AddRateLimited/Forget) with
the per-item exponential failure limiter (default_rate_limiters.go:39,
5ms..1000s) every controller uses for retries.
"""

from __future__ import annotations

import heapq
import threading
import time
from typing import Dict, List, Optional


class WorkQueue:
    """Dedup queue: an item added while queued is not duplicated; an item
    added while being processed is re-queued when done (workqueue/queue.go)."""

    def __init__(self):
        self._cond = threading.Condition()
        self._queue: List[object] = []
        self._dirty = set()
        self._processing = set()
        self._shutting_down = False

    def add(self, item):
        with self._cond:
            if self._shutting_down or item in self._dirty:
                return
            self._dirty.add(item)
            if item in self._processing:
                return
            self._queue.append(item)
            self._cond.notify()

    def get(self, timeout: Optional[float] = None):
        """Returns item or None on shutdown/timeout. Caller must call done()."""
        with self._cond:
            deadline = None if timeout is None else time.monotonic() + timeout
            while not self._queue and not self._shutting_down:
                left = None if deadline is None else deadline - time.monotonic()
                if left is not None and left <= 0:
                    return None
                self._cond.wait(left)
            if not self._queue:
                return None
            item = self._queue.pop(0)
            self._processing.add(item)
            self._dirty.discard(item)
            return item

    def done(self, item):
        with self._cond:
            self._processing.discard(item)
            if item in self._dirty:
                self._queue.append(item)
                self._cond.notify()

    def shut_down(self):
        with self._cond:
            self._shutting_down = True
            self._cond.notify_all()

    def __len__(self):
        with self._cond:
            return len(self._queue)


class DelayingQueue(WorkQueue):
    """AddAfter support via a waiting heap drained by a background thread
    (delaying_queue.go waitingLoop)."""

    def __init__(self, clock=time.monotonic):
        super().__init__()
        self._clock = clock
        self._heap: List[tuple] = []
        self._heap_cond = threading.Condition()
        self._seq = 0
        self._stop = threading.Event()
        self._waiter = threading.Thread(target=self._waiting_loop,
                                        daemon=True, name="workqueue-delay")
        self._waiter.start()

    def add_after(self, item, delay: float):
        if delay <= 0:
            return self.add(item)
        with self._heap_cond:
            self._seq += 1
            heapq.heappush(self._heap, (self._clock() + delay, self._seq, item))
            self._heap_cond.notify()

    def _waiting_loop(self):
        while not self._stop.is_set():
            with self._heap_cond:
                now = self._clock()
                while self._heap and self._heap[0][0] <= now:
                    _, _, item = heapq.heappop(self._heap)
                    self.add(item)
                wait = (self._heap[0][0] - now) if self._heap else 1.0
                self._heap_cond.wait(min(wait, 1.0))

    def shut_down(self):
        self._stop.set()
        with self._heap_cond:
            self._heap_cond.notify_all()
        super().shut_down()


class ItemExponentialFailureRateLimiter:
    """5ms * 2^failures capped at max_delay (default_rate_limiters.go:39;
    controllers use 5ms..1000s)."""

    def __init__(self, base_delay: float = 0.005, max_delay: float = 1000.0):
        self.base_delay = base_delay
        self.max_delay = max_delay
        self._failures: Dict[object, int] = {}
        self._lock = threading.Lock()

    def when(self, item) -> float:
        with self._lock:
            n = self._failures.get(item, 0)
            self._failures[item] = n + 1
            return min(self.base_delay * (2 ** n), self.max_delay)

    def forget(self, item):
        with self._lock:
            self._failures.pop(item, None)

    def num_requeues(self, item) -> int:
        with self._lock:
            return self._failures.get(item, 0)


class RateLimitingQueue(DelayingQueue):
    def __init__(self, rate_limiter: Optional[ItemExponentialFailureRateLimiter] = None,
                 clock=time.monotonic):
        super().__init__(clock=clock)
        self.rate_limiter = rate_limiter or ItemExponentialFailureRateLimiter()

    def add_rate_limited(self, item):
        self.add_after(item, self.rate_limiter.when(item))

    def forget(self, item):
        self.rate_limiter.forget(item)
