"""Cloud provider layer — pkg/cloudprovider analog."""

from .provider import (CloudProvider, FakeCloud, Instances, LoadBalancer,
                       NodeGroup, NodeGroups, Route, Routes, Zone, Zones,
                       node_from_template)
