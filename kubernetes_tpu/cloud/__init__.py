"""Cloud provider layer — pkg/cloudprovider analog."""

from .provider import (CloudProvider, FakeCloud, Instances, LoadBalancer,
                       Route, Routes, Zone, Zones)
