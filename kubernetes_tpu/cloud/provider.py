"""Cloud provider interface + fake implementation.

Reference: pkg/cloudprovider/cloud.go — the Interface every provider
(aws/azure/gce/...) implements, consumed by the service LB, route and
cloud-node controllers. The reference ships 55k LoC of per-cloud
implementations; here the surface is the interface plus the fake
(pkg/cloudprovider/providers/fake/fake.go), which is what every
reference controller test runs against too. Real TPU-pod deployments
sit behind the same seam: a provider whose Instances are TPU VM workers
and whose Routes program the pod network is a drop-in.
"""

from __future__ import annotations

import copy
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from ..api import types as api
from ..utils import faultpoints

# Stamped by the cloud-node controller from Instances.instance_type;
# the cluster autoscaler infers NodeGroup membership from it.
LABEL_INSTANCE_TYPE = "beta.kubernetes.io/instance-type"
LABEL_HOSTNAME = "kubernetes.io/hostname"


@dataclass
class Route:
    """One pod-network route (cloud.go Route): traffic for dest_cidr goes
    to target_node."""

    name: str
    target_node: str
    dest_cidr: str


@dataclass
class Zone:
    failure_domain: str = ""
    region: str = ""


class LoadBalancer:
    """cloud.go LoadBalancer interface."""

    def get_load_balancer(self, cluster: str, service: api.Service
                          ) -> Tuple[Optional[api.LoadBalancerStatus], bool]:
        raise NotImplementedError

    def ensure_load_balancer(self, cluster: str, service: api.Service,
                             nodes: List[api.Node]) -> api.LoadBalancerStatus:
        raise NotImplementedError

    def update_load_balancer(self, cluster: str, service: api.Service,
                             nodes: List[api.Node]) -> None:
        raise NotImplementedError

    def ensure_load_balancer_deleted(self, cluster: str,
                                     service: api.Service) -> None:
        raise NotImplementedError


class Instances:
    """cloud.go Instances interface."""

    def node_addresses(self, name: str) -> List[api.NodeAddress]:
        raise NotImplementedError

    def instance_id(self, name: str) -> str:
        raise NotImplementedError

    def instance_type(self, name: str) -> str:
        raise NotImplementedError

    def instance_exists_by_provider_id(self, provider_id: str) -> bool:
        raise NotImplementedError


class Zones:
    def get_zone_by_node_name(self, name: str) -> Zone:
        raise NotImplementedError

    def get_zone(self) -> Zone:
        """The zone the caller's resources land in by default
        (cloud.go Zones.GetZone — consumed by the PersistentVolumeLabel
        admission plugin)."""
        raise NotImplementedError


class Routes:
    """cloud.go Routes interface."""

    def list_routes(self, cluster: str) -> List[Route]:
        raise NotImplementedError

    def create_route(self, cluster: str, name_hint: str, route: Route) -> None:
        raise NotImplementedError

    def delete_route(self, cluster: str, route: Route) -> None:
        raise NotImplementedError


@dataclass
class NodeGroup:
    """One elastically sized set of identically shaped machines
    (autoscaler cloudprovider.NodeGroup: MinSize/MaxSize/TargetSize +
    TemplateNodeInfo). `template` is the Node every member boots as —
    allocatable, labels, taints — which is also what the autoscaler
    featurizes into *virtual* snapshot rows for the scale-up what-if.
    Membership of live nodes is inferred from the instance-type label
    the cloud-node controller stamps."""

    name: str
    template: api.Node
    min_size: int = 0
    max_size: int = 10
    target_size: int = 0
    instance_type: str = ""
    price: float = 1.0  # relative per-node cost (cheapest-expansion pick)


def node_from_template(group: NodeGroup, name: str) -> api.Node:
    """Instantiate a member Node from a group's template (autoscaler
    TemplateNodeInfo -> simulated node object): template allocatable /
    labels / taints plus the identity labels a real boot would carry."""
    t = group.template
    labels = dict(t.metadata.labels or {})
    labels[LABEL_INSTANCE_TYPE] = group.instance_type or group.name
    labels[LABEL_HOSTNAME] = name
    alloc = dict(t.status.allocatable)
    return api.Node(
        metadata=api.ObjectMeta(name=name, labels=labels,
                                annotations=dict(t.metadata.annotations or {})),
        spec=api.NodeSpec(taints=copy.deepcopy(t.spec.taints)),
        status=api.NodeStatus(
            capacity=dict(alloc), allocatable=alloc,
            conditions=[api.NodeCondition(api.NODE_READY, api.COND_TRUE)]))


class NodeGroups:
    """Autoscaler-facing sizing interface (autoscaler cloudprovider
    .CloudProvider: NodeGroups()/NodeGroupForNode + per-group
    IncreaseSize/DeleteNodes). Sizes are TARGETS: increase_size returns
    the instance names the cloud is booting; they become Nodes only when
    they register (the joiner seam on the fake)."""

    def groups(self) -> List[NodeGroup]:
        raise NotImplementedError

    def group(self, name: str) -> Optional[NodeGroup]:
        return next((g for g in self.groups() if g.name == name), None)

    def increase_size(self, name: str, delta: int) -> List[str]:
        raise NotImplementedError

    def delete_nodes(self, name: str, node_names: List[str]) -> None:
        raise NotImplementedError

    def template_node(self, name: str) -> api.Node:
        g = self.group(name)
        if g is None:
            raise KeyError(f"node group {name} not found")
        return g.template


class CloudProvider:
    """cloud.go Interface: each accessor returns the sub-interface or None
    when the cloud doesn't support that capability (the Go (iface, bool)
    pair)."""

    provider_name = ""

    def load_balancer(self) -> Optional[LoadBalancer]:
        return None

    def instances(self) -> Optional[Instances]:
        return None

    def zones(self) -> Optional[Zones]:
        return None

    def routes(self) -> Optional[Routes]:
        return None

    def node_groups(self) -> Optional[NodeGroups]:
        return None


# -- fake ----------------------------------------------------------------------


@dataclass
class FakeInstance:
    addresses: List[api.NodeAddress] = field(default_factory=list)
    instance_id: str = ""
    instance_type: str = "fake.small"
    zone: Zone = field(default_factory=Zone)


class FakeCloud(CloudProvider, LoadBalancer, Instances, Zones, Routes,
                NodeGroups):
    """In-memory provider recording every mutation (fake.go FakeCloud),
    used by controller tests and the kubemark-style local stack."""

    provider_name = "fake"

    def __init__(self):
        self._lock = threading.Lock()
        self.default_zone = Zone(failure_domain="z0", region="r0")
        self.instances_by_name: Dict[str, FakeInstance] = {}
        self.balancers: Dict[str, Tuple[api.LoadBalancerStatus, List[str]]] = {}
        self.route_table: Dict[str, Route] = {}
        self.calls: List[str] = []
        self.next_ip = 1
        # monotonic auto-IP counter: `10.1.0.{len+1}` collided with a
        # live instance's address after any delete-then-add sequence
        # (len shrinks back over an issued suffix)
        self._ip_seq = 0
        self.fail_next: Dict[str, Exception] = {}  # call name -> error to raise
        # node groups (autoscaler seam)
        self.groups_by_name: Dict[str, NodeGroup] = {}
        self._instance_group: Dict[str, str] = {}  # instance -> group name
        self._group_seq: Dict[str, int] = {}
        # joiner(group, instance_name): how a booted instance becomes a
        # Node — tests/bench wire this to create the Node object in the
        # store (optionally after a simulated join latency); None means
        # instances boot but never register, which is also a real cloud
        # failure mode the autoscaler must tolerate
        self.joiner: Optional[Callable[[NodeGroup, str], None]] = None

    # test hooks
    def add_instance(self, name: str, internal_ip: str = "",
                     zone: str = "z0", region: str = "r0",
                     instance_type: str = "fake.small"):
        if not internal_ip:
            self._ip_seq += 1
            internal_ip = f"10.1.0.{self._ip_seq}"
        self.instances_by_name[name] = FakeInstance(
            addresses=[api.NodeAddress("InternalIP", internal_ip),
                       api.NodeAddress("Hostname", name)],
            instance_id=f"fake://{name}",
            instance_type=instance_type,
            zone=Zone(failure_domain=zone, region=region))

    def _record(self, call: str):
        self.calls.append(call)
        err = self.fail_next.pop(call, None)
        if err is not None:
            raise err

    # CloudProvider
    def load_balancer(self):
        return self

    def instances(self):
        return self

    def zones(self):
        return self

    def routes(self):
        return self

    # LoadBalancer
    @staticmethod
    def _lb_name(service: api.Service) -> str:
        return f"{service.metadata.namespace}/{service.metadata.name}"

    def get_load_balancer(self, cluster, service):
        with self._lock:
            self._record("get-load-balancer")
            hit = self.balancers.get(self._lb_name(service))
            return (hit[0], True) if hit else (None, False)

    def ensure_load_balancer(self, cluster, service, nodes):
        with self._lock:
            self._record("ensure-load-balancer")
            name = self._lb_name(service)
            if name in self.balancers:
                status = self.balancers[name][0]
            else:
                ip = service.spec.load_balancer_ip or f"203.0.113.{self.next_ip}"
                self.next_ip += 1
                status = api.LoadBalancerStatus(
                    ingress=[api.LoadBalancerIngress(ip=ip)])
            self.balancers[name] = (status, sorted(n.name for n in nodes))
            return status

    def update_load_balancer(self, cluster, service, nodes):
        with self._lock:
            self._record("update-load-balancer")
            name = self._lb_name(service)
            if name in self.balancers:
                self.balancers[name] = (self.balancers[name][0],
                                        sorted(n.name for n in nodes))

    def ensure_load_balancer_deleted(self, cluster, service):
        with self._lock:
            self._record("ensure-load-balancer-deleted")
            self.balancers.pop(self._lb_name(service), None)

    # Instances
    def node_addresses(self, name):
        self._record("node-addresses")
        inst = self.instances_by_name.get(name)
        if inst is None:
            raise KeyError(f"instance {name} not found")
        return list(inst.addresses)

    def instance_id(self, name):
        self._record("instance-id")
        return self.instances_by_name[name].instance_id

    def instance_type(self, name):
        self._record("instance-type")
        return self.instances_by_name[name].instance_type

    def instance_exists_by_provider_id(self, provider_id):
        self._record("instance-exists")
        return any(i.instance_id == provider_id
                   for i in self.instances_by_name.values())

    # Zones
    def get_zone_by_node_name(self, name):
        self._record("get-zone")
        return self.instances_by_name[name].zone

    def get_zone(self):
        self._record("get-zone")
        return self.default_zone

    # NodeGroups
    def node_groups(self):
        return self if self.groups_by_name else None

    def add_node_group(self, name: str, template: api.Node,
                       min_size: int = 0, max_size: int = 10,
                       price: float = 1.0,
                       instance_type: str = "") -> NodeGroup:
        """Register an elastically sized group whose members boot as
        copies of `template`. instance_type defaults to the group name
        (it is the membership key stamped on every member node)."""
        g = NodeGroup(name=name, template=template, min_size=min_size,
                      max_size=max_size, target_size=0,
                      instance_type=instance_type or name, price=price)
        with self._lock:
            self.groups_by_name[name] = g
        return g

    def groups(self) -> List[NodeGroup]:
        with self._lock:
            return list(self.groups_by_name.values())

    def increase_size(self, name: str, delta: int) -> List[str]:
        """Boot `delta` new instances of the group's shape. The chaos
        seam fires BEFORE any mutation so a `cloud.resize` raise models
        a rejected API call: target size and instances are untouched."""
        new: List[Tuple[NodeGroup, str]] = []
        with self._lock:
            self._record("increase-size")
            faultpoints.fire("cloud.resize",
                             payload=("increase_size", name, delta))
            g = self.groups_by_name.get(name)
            if g is None:
                raise KeyError(f"node group {name} not found")
            if delta <= 0:
                raise ValueError(f"increase_size delta must be > 0: {delta}")
            if g.target_size + delta > g.max_size:
                raise ValueError(
                    f"group {name}: size {g.target_size}+{delta} would "
                    f"exceed max {g.max_size}")
            for _ in range(delta):
                seq = self._group_seq.get(name, 0)
                self._group_seq[name] = seq + 1
                iname = f"{name}-{seq}"
                self.add_instance(iname, instance_type=g.instance_type,
                                  zone=self.default_zone.failure_domain,
                                  region=self.default_zone.region)
                self._instance_group[iname] = name
                new.append((g, iname))
            g.target_size += delta
        # join OUTSIDE the cloud lock: the joiner typically creates Node
        # objects, whose informer fan-out must not run under it
        if self.joiner is not None:
            for g, iname in new:
                self.joiner(g, iname)
        return [iname for _, iname in new]

    def delete_nodes(self, name: str, node_names: List[str]) -> None:
        """Tear down specific members (autoscaler DeleteNodes). Refuses
        to shrink below min_size or to touch an instance of another
        group; the chaos seam fires before any mutation."""
        with self._lock:
            self._record("delete-nodes")
            faultpoints.fire("cloud.resize",
                             payload=("delete_nodes", name, tuple(node_names)))
            g = self.groups_by_name.get(name)
            if g is None:
                raise KeyError(f"node group {name} not found")
            if g.target_size - len(node_names) < g.min_size:
                raise ValueError(
                    f"group {name}: deleting {len(node_names)} would drop "
                    f"below min {g.min_size}")
            for n in node_names:
                owner = self._instance_group.get(n)
                inst = self.instances_by_name.get(n)
                member = (owner == name
                          or (owner is None and inst is not None
                              and inst.instance_type == g.instance_type))
                if not member:
                    raise KeyError(f"instance {n} is not a member of {name}")
            for n in node_names:
                self.instances_by_name.pop(n, None)
                self._instance_group.pop(n, None)
            g.target_size -= len(node_names)

    # Routes
    def list_routes(self, cluster):
        with self._lock:
            self._record("list-routes")
            return list(self.route_table.values())

    def create_route(self, cluster, name_hint, route):
        with self._lock:
            self._record("create-route")
            self.route_table[f"{route.target_node}:{route.dest_cidr}"] = route

    def delete_route(self, cluster, route):
        with self._lock:
            self._record("delete-route")
            self.route_table.pop(f"{route.target_node}:{route.dest_cidr}", None)
