"""Cloud provider interface + fake implementation.

Reference: pkg/cloudprovider/cloud.go — the Interface every provider
(aws/azure/gce/...) implements, consumed by the service LB, route and
cloud-node controllers. The reference ships 55k LoC of per-cloud
implementations; here the surface is the interface plus the fake
(pkg/cloudprovider/providers/fake/fake.go), which is what every
reference controller test runs against too. Real TPU-pod deployments
sit behind the same seam: a provider whose Instances are TPU VM workers
and whose Routes program the pod network is a drop-in.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..api import types as api


@dataclass
class Route:
    """One pod-network route (cloud.go Route): traffic for dest_cidr goes
    to target_node."""

    name: str
    target_node: str
    dest_cidr: str


@dataclass
class Zone:
    failure_domain: str = ""
    region: str = ""


class LoadBalancer:
    """cloud.go LoadBalancer interface."""

    def get_load_balancer(self, cluster: str, service: api.Service
                          ) -> Tuple[Optional[api.LoadBalancerStatus], bool]:
        raise NotImplementedError

    def ensure_load_balancer(self, cluster: str, service: api.Service,
                             nodes: List[api.Node]) -> api.LoadBalancerStatus:
        raise NotImplementedError

    def update_load_balancer(self, cluster: str, service: api.Service,
                             nodes: List[api.Node]) -> None:
        raise NotImplementedError

    def ensure_load_balancer_deleted(self, cluster: str,
                                     service: api.Service) -> None:
        raise NotImplementedError


class Instances:
    """cloud.go Instances interface."""

    def node_addresses(self, name: str) -> List[api.NodeAddress]:
        raise NotImplementedError

    def instance_id(self, name: str) -> str:
        raise NotImplementedError

    def instance_type(self, name: str) -> str:
        raise NotImplementedError

    def instance_exists_by_provider_id(self, provider_id: str) -> bool:
        raise NotImplementedError


class Zones:
    def get_zone_by_node_name(self, name: str) -> Zone:
        raise NotImplementedError

    def get_zone(self) -> Zone:
        """The zone the caller's resources land in by default
        (cloud.go Zones.GetZone — consumed by the PersistentVolumeLabel
        admission plugin)."""
        raise NotImplementedError


class Routes:
    """cloud.go Routes interface."""

    def list_routes(self, cluster: str) -> List[Route]:
        raise NotImplementedError

    def create_route(self, cluster: str, name_hint: str, route: Route) -> None:
        raise NotImplementedError

    def delete_route(self, cluster: str, route: Route) -> None:
        raise NotImplementedError


class CloudProvider:
    """cloud.go Interface: each accessor returns the sub-interface or None
    when the cloud doesn't support that capability (the Go (iface, bool)
    pair)."""

    provider_name = ""

    def load_balancer(self) -> Optional[LoadBalancer]:
        return None

    def instances(self) -> Optional[Instances]:
        return None

    def zones(self) -> Optional[Zones]:
        return None

    def routes(self) -> Optional[Routes]:
        return None


# -- fake ----------------------------------------------------------------------


@dataclass
class FakeInstance:
    addresses: List[api.NodeAddress] = field(default_factory=list)
    instance_id: str = ""
    instance_type: str = "fake.small"
    zone: Zone = field(default_factory=Zone)


class FakeCloud(CloudProvider, LoadBalancer, Instances, Zones, Routes):
    """In-memory provider recording every mutation (fake.go FakeCloud),
    used by controller tests and the kubemark-style local stack."""

    provider_name = "fake"

    def __init__(self):
        self._lock = threading.Lock()
        self.default_zone = Zone(failure_domain="z0", region="r0")
        self.instances_by_name: Dict[str, FakeInstance] = {}
        self.balancers: Dict[str, Tuple[api.LoadBalancerStatus, List[str]]] = {}
        self.route_table: Dict[str, Route] = {}
        self.calls: List[str] = []
        self.next_ip = 1
        self.fail_next: Dict[str, Exception] = {}  # call name -> error to raise

    # test hooks
    def add_instance(self, name: str, internal_ip: str = "",
                     zone: str = "z0", region: str = "r0",
                     instance_type: str = "fake.small"):
        self.instances_by_name[name] = FakeInstance(
            addresses=[api.NodeAddress("InternalIP", internal_ip or
                                       f"10.1.0.{len(self.instances_by_name) + 1}"),
                       api.NodeAddress("Hostname", name)],
            instance_id=f"fake://{name}",
            instance_type=instance_type,
            zone=Zone(failure_domain=zone, region=region))

    def _record(self, call: str):
        self.calls.append(call)
        err = self.fail_next.pop(call, None)
        if err is not None:
            raise err

    # CloudProvider
    def load_balancer(self):
        return self

    def instances(self):
        return self

    def zones(self):
        return self

    def routes(self):
        return self

    # LoadBalancer
    @staticmethod
    def _lb_name(service: api.Service) -> str:
        return f"{service.metadata.namespace}/{service.metadata.name}"

    def get_load_balancer(self, cluster, service):
        with self._lock:
            self._record("get-load-balancer")
            hit = self.balancers.get(self._lb_name(service))
            return (hit[0], True) if hit else (None, False)

    def ensure_load_balancer(self, cluster, service, nodes):
        with self._lock:
            self._record("ensure-load-balancer")
            name = self._lb_name(service)
            if name in self.balancers:
                status = self.balancers[name][0]
            else:
                ip = service.spec.load_balancer_ip or f"203.0.113.{self.next_ip}"
                self.next_ip += 1
                status = api.LoadBalancerStatus(
                    ingress=[api.LoadBalancerIngress(ip=ip)])
            self.balancers[name] = (status, sorted(n.name for n in nodes))
            return status

    def update_load_balancer(self, cluster, service, nodes):
        with self._lock:
            self._record("update-load-balancer")
            name = self._lb_name(service)
            if name in self.balancers:
                self.balancers[name] = (self.balancers[name][0],
                                        sorted(n.name for n in nodes))

    def ensure_load_balancer_deleted(self, cluster, service):
        with self._lock:
            self._record("ensure-load-balancer-deleted")
            self.balancers.pop(self._lb_name(service), None)

    # Instances
    def node_addresses(self, name):
        self._record("node-addresses")
        inst = self.instances_by_name.get(name)
        if inst is None:
            raise KeyError(f"instance {name} not found")
        return list(inst.addresses)

    def instance_id(self, name):
        self._record("instance-id")
        return self.instances_by_name[name].instance_id

    def instance_type(self, name):
        self._record("instance-type")
        return self.instances_by_name[name].instance_type

    def instance_exists_by_provider_id(self, provider_id):
        self._record("instance-exists")
        return any(i.instance_id == provider_id
                   for i in self.instances_by_name.values())

    # Zones
    def get_zone_by_node_name(self, name):
        self._record("get-zone")
        return self.instances_by_name[name].zone

    def get_zone(self):
        self._record("get-zone")
        return self.default_zone

    # Routes
    def list_routes(self, cluster):
        with self._lock:
            self._record("list-routes")
            return list(self.route_table.values())

    def create_route(self, cluster, name_hint, route):
        with self._lock:
            self._record("create-route")
            self.route_table[f"{route.target_node}:{route.dest_cidr}"] = route

    def delete_route(self, cluster, route):
        with self._lock:
            self._record("delete-route")
            self.route_table.pop(f"{route.target_node}:{route.dest_cidr}", None)
