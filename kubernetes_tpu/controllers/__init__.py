"""Control loops — the reference's pkg/controller/ layer.

Each controller is an informer+workqueue reconciliation loop
(controller pattern: SharedInformer handlers enqueue keys, workers pop
and sync to desired state; pkg/controller/*). The set mirrors the
kube-controller-manager's roster at the capability level: workloads
(ReplicaSet/RC, Deployment, StatefulSet, DaemonSet, Job, CronJob),
services (Endpoints), node failure detection (NodeLifecycle), disruption
budgets, namespace lifecycle, garbage collection (owner references +
terminated-pod GC), resource quota accounting, service accounts, and
PV/PVC binding.
"""

from .base import Controller, is_pod_active, is_pod_ready, pod_owned_by
from .replicaset import ReplicaSetController, ReplicationControllerController
from .deployment import DeploymentController
from .statefulset import StatefulSetController
from .daemonset import DaemonSetController
from .job import JobController
from .cronjob import CronJobController
from .endpoints import EndpointsController
from .nodelifecycle import NodeLifecycleController
from .disruption import DisruptionController
from .namespace import NamespaceController
from .podgc import PodGCController
from .garbagecollector import GarbageCollector
from .resourcequota import ResourceQuotaController
from .serviceaccount import ServiceAccountController
from .expand import ExpandController
from .volumebinding import PersistentVolumeController
from .attachdetach import AttachDetachController
from .podautoscaler import HorizontalPodAutoscalerController
from .ttl import TTLController
from .certificates import CSRApprovingController, CSRSigningController
from .nodeipam import NodeIpamController
from .route import RouteController
from .service_lb import ServiceLBController
from .cloud_node import CloudNodeController
from .clusterautoscaler import ClusterAutoscaler
from .manager import ControllerManager
