"""Attach/detach controller: reconcile volume attachment with pod placement.

Reference: pkg/controller/volume/attachdetach/attach_detach_controller.go:95
(NewAttachDetachController). Its model, reproduced here per node:

  desired state  = for every scheduled pod on node N, the persistent
                   volumes behind its PVC volumes must be attached to N
                   (desiredStateOfWorld, cache/desired_state_of_world.go)
  actual state   = node.status.volumesAttached
  reconciler     = attach volumes that are desired but absent, detach
                   volumes that are attached but no longer desired
                   (reconciler/reconciler.go:141)

For in-tree volumes the "attach operation" is the control-plane state
transition itself — writing node.status.volumes_attached /
volumes_in_use through the store — the part the scheduler, kubelet
volume manager, and multi-attach protection consume (the reference's
cloud-provider calls live behind the cloud seam). For CSI-backed PVs
the controller additionally crosses the process boundary: the driver's
ControllerPublishVolume runs BEFORE the attachment is recorded and
ControllerUnpublishVolume before it is dropped
(attach_detach_controller.go + csi_attacher.go). A volume attached
elsewhere is not attached again until detached (multi-attach guard for
RWO volumes, reconciler.go:184).
"""

from __future__ import annotations

from typing import List, Optional, Set

from ..api import types as api
from .base import Controller


class AttachDetachController(Controller):
    name = "attachdetach"

    def __init__(self, store):
        super().__init__(store)
        self.informer("pods", enqueue_fn=self._enqueue_pod_node)
        self.informer("nodes")
        self.informer("persistentvolumeclaims",
                      enqueue_fn=lambda o=None, n=None: self._all_nodes())
        from ..volume.csi import CSIPlugin

        self._csi = CSIPlugin(store)

    def _pv(self, name: str):
        return (self.store.get("persistentvolumes", "", name)
                or self.store.get("persistentvolumes", "default", name))

    def _publish(self, pv_name: str, node_name: str) -> bool:
        """Out-of-process attach for CSI PVs; in-tree PVs attach by
        state transition alone. False = driver refused/unreachable
        (leave unattached; the queue retries with backoff)."""
        pv = self._pv(pv_name)
        if pv is None or pv.spec.source_kind != "CSI":
            return True
        from ..volume.csi import CSIError
        from ..volume.plugin import Spec

        try:
            self._csi.new_attacher().attach(Spec(pv=pv), node_name)
            return True
        except CSIError:
            return False

    def _unpublish(self, pv_name: str, node_name: str) -> bool:
        """False = the driver never received the unpublish — the
        attachment must STAY recorded so a later sync retries; dropping
        it would leak the driver's publish entry and permanently block
        the volume's next attach elsewhere."""
        pv = self._pv(pv_name)
        if pv is None or pv.spec.source_kind != "CSI":
            return True
        from ..volume.csi import CSIError

        try:
            self._csi.new_detacher().detach_pv(pv, node_name)
            return True
        except CSIError:
            return False

    def _enqueue_pod_node(self, pod, new=None):
        pod = new if new is not None else pod
        if pod.spec.node_name:
            self.enqueue(f"default/{pod.spec.node_name}")

    def _all_nodes(self):
        for node in self.store.list("nodes"):
            self.enqueue(node)

    def _desired_volumes(self, node_name: str) -> List[str]:
        """PV names required on the node by its scheduled pods."""
        want: List[str] = []
        for pod in self.store.list("pods"):
            if pod.spec.node_name != node_name or not api.is_pod_active(pod):
                continue
            for v in pod.spec.volumes:
                if not v.pvc_name:
                    continue
                pvc = self.store.get("persistentvolumeclaims", pod.namespace,
                                     v.pvc_name)
                if pvc is not None and pvc.spec.volume_name \
                        and pvc.spec.volume_name not in want:
                    want.append(pvc.spec.volume_name)
        return want

    def _attached_elsewhere(self, pv_name: str, node_name: str) -> bool:
        for node in self.store.list("nodes"):
            if node.metadata.name == node_name:
                continue
            if pv_name in node.status.volumes_attached:
                return True
        return False

    def sync(self, key: str):
        _, name = key.split("/", 1)
        node = self.store.get("nodes", "default", name)
        if node is None:
            return
        desired = self._desired_volumes(name)
        attached: List[str] = list(node.status.volumes_attached)
        changed = False
        # detach first: frees RWO volumes for their new node
        blocked = None
        for pv in list(attached):
            if pv not in desired:
                if not self._unpublish(pv, name):
                    blocked = pv  # driver unreachable: retry the detach
                    continue
                attached.remove(pv)
                changed = True
        for pv in desired:
            if pv in attached:
                continue
            if self._attached_elsewhere(pv, name):
                # multi-attach guard: wait for the other node's detach —
                # but DO persist this node's own detaches below first, or
                # two nodes each waiting on the other's stale attachment
                # would livelock (requeued with backoff by the error path)
                blocked = pv
                continue
            if not self._publish(pv, name):
                blocked = pv  # driver refused: retry with backoff
                continue
            attached.append(pv)
            changed = True
        if changed or node.status.volumes_in_use != attached:
            node.status.volumes_attached = attached
            node.status.volumes_in_use = list(attached)
            self.store.update("nodes", node)
        if blocked is not None:
            raise RuntimeError(
                f"volume {blocked} still attached to another node")
