"""Controller base: the informer + workqueue reconcile loop.

The shape every reference controller shares (e.g. pkg/controller/
replicaset/replica_set.go: informer handlers -> workqueue.Add(key) ->
N workers -> syncHandler(key) -> requeue with rate limit on error).
`sync_all()` drains the queue synchronously for deterministic tests —
the analog of driving the loop with a fake clock in unit tests.
"""

from __future__ import annotations

import threading
import time
import traceback
from typing import Callable, List, Optional

from ..api import types as api
from ..client.workqueue import RateLimitingQueue
from ..runtime.informer import SharedInformer
from ..runtime.store import ObjectStore


class Controller:
    name = "controller"

    def __init__(self, store: ObjectStore):
        self.store = store
        self.queue = RateLimitingQueue()
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()
        self.sync_errors = 0

    # -- to override -----------------------------------------------------------

    def sync(self, key: str) -> None:
        """Reconcile one key ('namespace/name'). Raise to retry with backoff."""
        raise NotImplementedError

    def resync(self) -> None:
        """Periodic full relist hook (informer resync period analog)."""

    # -- plumbing --------------------------------------------------------------

    def enqueue(self, obj_or_key):
        if isinstance(obj_or_key, str):
            self.queue.add(obj_or_key)
        else:
            meta = obj_or_key.metadata
            self.queue.add(f"{meta.namespace}/{meta.name}")

    def informer(self, kind: str, enqueue_fn: Optional[Callable] = None,
                 **handlers) -> SharedInformer:
        """Wire an informer whose every event enqueues via enqueue_fn
        (default: the object's own key)."""
        inf = SharedInformer(self.store, kind)
        fn = enqueue_fn or self.enqueue
        if handlers:
            inf.add_event_handler(**handlers)
        else:
            inf.add_event_handler(on_add=fn,
                                  on_update=lambda o, n: fn(n),
                                  on_delete=fn)
        return inf

    def process_one(self, timeout: float = 0.0) -> bool:
        key = self.queue.get(timeout=timeout)
        if key is None:
            return False
        try:
            self.sync(key)
            self.queue.forget(key)
        except Exception:
            self.sync_errors += 1
            self.queue.add_rate_limited(key)
        finally:
            self.queue.done(key)
        return True

    def sync_all(self, max_iters: int = 1000) -> int:
        """Drain the queue synchronously (test/deterministic mode)."""
        n = 0
        while n < max_iters and self.process_one():
            n += 1
        return n

    def run(self, workers: int = 1, resync_period: float = 30.0):
        """Start background workers (controller Run(workers, stopCh)) and
        a periodic resync ticker — controllers whose state can change
        without a watch event (HPA forbidden windows, time-based
        lifecycles) re-enqueue themselves via resync()."""
        def worker():
            while not self._stop.is_set():
                self.process_one(timeout=0.2)

        for i in range(workers):
            t = threading.Thread(target=worker, daemon=True,
                                 name=f"{self.name}-{i}")
            t.start()
            self._threads.append(t)
        if resync_period > 0:
            def resyncer():
                while not self._stop.wait(resync_period):
                    try:
                        self.resync()
                    except Exception:
                        self.sync_errors += 1

            t = threading.Thread(target=resyncer, daemon=True,
                                 name=f"{self.name}-resync")
            t.start()
            self._threads.append(t)

    def stop(self):
        self._stop.set()
        self.queue.shut_down()


# -- shared pod helpers (pkg/controller/controller_utils.go) -------------------


is_pod_active = api.is_pod_active  # canonical definition in api/types.py


def is_pod_ready(pod: api.Pod) -> bool:
    """pod has condition Ready=True (api pod helpers IsPodReady)."""
    for ctype, cstatus in pod.status.conditions:
        if ctype == "Ready":
            return cstatus == "True" or cstatus.startswith("True")
    return False


def pod_owned_by(pod: api.Pod, kind: str, name: str, uid: str = "") -> bool:
    for ref in pod.metadata.owner_references:
        if ref.controller and ref.kind == kind and ref.name == name and \
                (not uid or not ref.uid or ref.uid == uid):
            return True
    return False


def make_pod_from_template(template: api.PodTemplateSpec, owner_kind: str,
                           owner, name: str) -> api.Pod:
    """Instantiate a pod from a template with a controller owner reference
    (controller_utils.go GetPodFromTemplate)."""
    import copy
    spec = copy.deepcopy(template.spec) if template is not None else api.PodSpec()
    labels = dict(template.metadata.labels) if template is not None else {}
    return api.Pod(
        metadata=api.ObjectMeta(
            name=name, namespace=owner.metadata.namespace, labels=labels,
            owner_references=[api.OwnerReference(
                kind=owner_kind, name=owner.metadata.name,
                uid=owner.metadata.uid, controller=True)]),
        spec=spec)
