"""Bootstrap-token machinery: signer + cleaner + the token authenticator
helpers.

Reference: pkg/controller/bootstrap/ — BootstrapSigner (bootstrapsigner
.go) maintains detached JWS signatures over the kube-public cluster-info
ConfigMap, one per active bootstrap token, so a joiner holding only its
token can VERIFY the CA bundle it discovers instead of trusting first
use; TokenCleaner (tokencleaner.go) deletes expired bootstrap-token
Secrets. Tokens are kube-system Secrets of type
bootstrap.kubernetes.io/token with token-id/token-secret/expiration
(the "abcdef.0123456789abcdef" id.secret wire form), exactly the shape
the reference's bootstrap token authenticator consumes
(plugin/pkg/auth/authenticator/token/bootstrap/bootstrap.go).

The signature is HMAC-SHA256 over the ca.crt payload keyed by the
token's secret — the reference uses detached JWS with the same key
material; the HMAC form keeps the verify path dependency-free while
preserving the property that ONLY a real token holder can validate (or
forge) the discovery payload for that token.
"""

from __future__ import annotations

import hashlib
import hmac
import time
from typing import Optional, Tuple

from ..api import types as api
from .base import Controller

TOKEN_SECRET_TYPE = "bootstrap.kubernetes.io/token"
TOKEN_SECRET_PREFIX = "bootstrap-token-"
TOKEN_NAMESPACE = "kube-system"
JWS_KEY_PREFIX = "jws-kubeconfig-"


def new_bootstrap_token() -> Tuple[str, str, str]:
    """(token_id, token_secret, wire form id.secret) — kubeadm's
    GenerateBootstrapToken analog."""
    import secrets

    tid = secrets.token_hex(3)       # 6 hex chars, like abcdef
    tsec = secrets.token_hex(8)      # 16 hex chars
    return tid, tsec, f"{tid}.{tsec}"


def make_token_secret(token_id: str, token_secret: str,
                      ttl_seconds: Optional[float] = None) -> api.Secret:
    data = {"token-id": token_id, "token-secret": token_secret,
            "usage-bootstrap-authentication": "true",
            "usage-bootstrap-signing": "true"}
    if ttl_seconds is not None:
        data["expiration"] = str(time.time() + ttl_seconds)
    return api.Secret(
        metadata=api.ObjectMeta(name=TOKEN_SECRET_PREFIX + token_id,
                                namespace=TOKEN_NAMESPACE),
        type=TOKEN_SECRET_TYPE, data=data)


def parse_expiration(raw: Optional[str]) -> Optional[float]:
    """Expiration as unix seconds. Accepts both this module's numeric
    form and the reference's RFC3339 form ('2026-08-01T00:00:00Z').
    Unparseable values return 0.0 — i.e. ALREADY EXPIRED: a token whose
    expiry cannot be read must fail closed, and it must never crash the
    authenticator/signer/cleaner paths."""
    if raw is None:
        return None
    try:
        return float(raw)
    except ValueError:
        pass
    try:
        from datetime import datetime, timezone

        dt = datetime.fromisoformat(raw.replace("Z", "+00:00"))
        if dt.tzinfo is None:
            dt = dt.replace(tzinfo=timezone.utc)
        return dt.timestamp()
    except ValueError:
        return 0.0


def lookup_token(store, token: str) -> Optional[api.Secret]:
    """Resolve a live, unexpired bootstrap token ('id.secret') to its
    Secret; None if unknown/expired/malformed (bootstrap.go
    AuthenticateToken)."""
    tid, dot, tsec = token.partition(".")
    if not dot or not tid or not tsec:
        return None
    sec = store.get("secrets", TOKEN_NAMESPACE, TOKEN_SECRET_PREFIX + tid)
    if sec is None or sec.type != TOKEN_SECRET_TYPE:
        return None
    if not hmac.compare_digest(
            sec.data.get("token-secret", "").encode(),
            tsec.encode()):  # bytes: non-ASCII input must 401, not 500
        return None
    if sec.data.get("token-id") != tid:
        # reference bootstrap.go validates token-id against the secret
        # name; a mismatched/missing id must not authenticate
        return None
    exp = parse_expiration(sec.data.get("expiration"))
    if exp is not None and time.time() > exp:
        return None
    if sec.data.get("usage-bootstrap-authentication") != "true":
        return None
    return sec


def sign_payload(payload: str, token_secret: str) -> str:
    return hmac.new(token_secret.encode(), payload.encode(),
                    hashlib.sha256).hexdigest()


def compute_signatures(store, ca_pem: str) -> dict:
    """{jws-kubeconfig-<id>: signature} for every live signing-enabled
    bootstrap token — THE policy, shared by the BootstrapSigner
    controller and kubeadm's synchronous pre-signing (two hand-kept
    copies would drift on the expiry filter)."""
    want = {}
    for sec in store.list("secrets", TOKEN_NAMESPACE):
        if sec.type != TOKEN_SECRET_TYPE:
            continue
        if sec.data.get("usage-bootstrap-signing") != "true":
            continue
        exp = parse_expiration(sec.data.get("expiration"))
        if exp is not None and time.time() > exp:
            continue
        tid = sec.data.get("token-id")
        tsec = sec.data.get("token-secret")
        if tid and tsec:
            want[JWS_KEY_PREFIX + tid] = sign_payload(ca_pem, tsec)
    return want


def verify_cluster_info(info: api.ConfigMap, token: str) -> Optional[str]:
    """Authenticated CA discovery: returns the ca.crt iff the ConfigMap
    carries a valid signature under this token (the joiner-side half of
    BootstrapSigner; replaces trust-on-first-use)."""
    tid, _, tsec = token.partition(".")
    ca = info.data.get("ca.crt")
    sig = info.data.get(JWS_KEY_PREFIX + tid)
    if not ca or not sig:
        return None
    if not hmac.compare_digest(sig.encode(),
                               sign_payload(ca, tsec).encode()):
        return None
    return ca


class BootstrapSignerController(Controller):
    """bootstrapsigner.go: keep one signature per signing-enabled token
    on the kube-public cluster-info ConfigMap; drop signatures whose
    token is gone."""

    name = "bootstrapsigner"

    def __init__(self, store):
        super().__init__(store)
        self.informer("secrets",
                      enqueue_fn=lambda o=None, n=None: self.enqueue(
                          "kube-public/cluster-info"))
        self.informer("configmaps")

    def sync(self, key: str):
        if key != "kube-public/cluster-info":
            return
        info = self.store.get("configmaps", "kube-public", "cluster-info")
        if info is None or "ca.crt" not in info.data:
            return
        want = compute_signatures(self.store, info.data["ca.crt"])
        have = {k: v for k, v in info.data.items()
                if k.startswith(JWS_KEY_PREFIX)}
        if have == want:
            return
        info.data = {k: v for k, v in info.data.items()
                     if not k.startswith(JWS_KEY_PREFIX)}
        info.data.update(want)
        self.store.update("configmaps", info)

    def resync(self):
        self.enqueue("kube-public/cluster-info")


class TokenCleanerController(Controller):
    """tokencleaner.go: delete expired bootstrap-token Secrets; their
    holders stop authenticating and their cluster-info signatures are
    dropped by the signer's next pass."""

    name = "tokencleaner"

    def __init__(self, store, clock=time.time):
        super().__init__(store)
        self.clock = clock
        self.informer("secrets")

    def sync(self, key: str):
        ns, name = key.split("/", 1)
        if ns != TOKEN_NAMESPACE or not name.startswith(
                TOKEN_SECRET_PREFIX):
            return
        sec = self.store.get("secrets", ns, name)
        if sec is None or sec.type != TOKEN_SECRET_TYPE:
            return
        exp = parse_expiration(sec.data.get("expiration"))
        if exp is not None and self.clock() > exp:
            try:
                self.store.delete("secrets", ns, name)
            except KeyError:
                pass

    def resync(self):
        for sec in self.store.list("secrets", TOKEN_NAMESPACE):
            if sec.type == TOKEN_SECRET_TYPE:
                self.enqueue(sec)
