"""Certificates controllers: CSR auto-approval and signing.

Reference: pkg/controller/certificates/ — the approver
(approver/sarapprove.go) auto-approves kubelet CSRs whose requestor is
the node itself (self-node client certs), and the signer
(signer/signer.go) issues the certificate for approved CSRs. Real x509
is out of scope for the framework (the reference shells out to a CA
keypair); the control-loop contract — request -> approve/deny ->
signed status.certificate consumable by the requester — is what this
reproduces, with an opaque token standing in for the PEM blob.
"""

from __future__ import annotations

import hashlib

from ..api import types as api
from .base import Controller

KUBELET_USAGES = {"digital signature", "key encipherment", "client auth"}


def is_self_node_csr(csr: api.CertificateSigningRequest) -> bool:
    """approver/sarapprove.go isSelfNodeClientCert: requested by a node
    for its own identity, with exactly the kubelet client usages."""
    if not csr.spec.username.startswith("system:node:"):
        return False
    if "system:nodes" not in csr.spec.groups:
        return False
    return set(csr.spec.usages) == KUBELET_USAGES


class CSRApprovingController(Controller):
    name = "csrapproving"

    def __init__(self, store):
        super().__init__(store)
        self.informer("certificatesigningrequests")

    def sync(self, key: str):
        name = key.split("/", 1)[-1]
        csr = self.store.get("certificatesigningrequests", "default", name) \
            or self.store.get("certificatesigningrequests", "", name)
        if csr is None or csr.approved or csr.denied:
            return
        if is_self_node_csr(csr):
            csr.status.conditions.append(
                ("Approved", "AutoApproved self node client cert"))
            self.store.update("certificatesigningrequests", csr)


class CSRSigningController(Controller):
    name = "csrsigning"

    def __init__(self, store, ca_name: str = "kubernetes-tpu-ca"):
        super().__init__(store)
        self.ca_name = ca_name
        self.informer("certificatesigningrequests")

    def sync(self, key: str):
        name = key.split("/", 1)[-1]
        csr = self.store.get("certificatesigningrequests", "default", name) \
            or self.store.get("certificatesigningrequests", "", name)
        if csr is None or not csr.approved or csr.status.certificate:
            return
        digest = hashlib.sha256(
            f"{self.ca_name}/{csr.spec.username}/{csr.spec.request}"
            .encode()).hexdigest()
        csr.status.certificate = f"cert:{csr.spec.username}:{digest[:32]}"
        self.store.update("certificatesigningrequests", csr)
