"""Certificates controllers: CSR auto-approval and signing.

Reference: pkg/controller/certificates/ — the approver
(approver/sarapprove.go) auto-approves kubelet CSRs whose requestor is
the node itself (self-node client certs), and the signer
(signer/signer.go) issues the certificate for approved CSRs. The signer
is REAL x509: a PEM CSR in spec.request is signed by the cluster CA
(server/pki.py) and the resulting cert is accepted by the apiserver's
x509 authn path — kubeadm join bootstraps kubelet identity through it.
Non-PEM requests (legacy opaque payloads) still get the digest-token
certificate so old callers keep working.
"""

from __future__ import annotations

import hashlib

from ..api import types as api
from .base import Controller

KUBELET_USAGES = {"digital signature", "key encipherment", "client auth"}


def is_self_node_csr(csr: api.CertificateSigningRequest) -> bool:
    """approver/sarapprove.go isSelfNodeClientCert: requested by a node
    for ITS OWN identity, with exactly the kubelet client usages. The
    CSR subject must name the requestor (x509cr.Subject.CommonName ==
    csr.Spec.Username) — without that check any node could mint another
    node's certificate through auto-approval."""
    if not csr.spec.username.startswith("system:node:"):
        return False
    if "system:nodes" not in csr.spec.groups:
        return False
    if set(csr.spec.usages) != KUBELET_USAGES:
        return False
    subj = _pem_subject(csr.spec.request)
    if subj is None:
        # legacy opaque (non-PEM) payloads carry no subject to verify;
        # their digest-token certs never impersonate an x509 identity
        return True
    cn, orgs = subj
    return cn == csr.spec.username and orgs == ["system:nodes"]


def _pem_subject(csr_pem: str):
    """(CN, [O...]) of a PEM CSR, or None if unparseable."""
    try:
        from cryptography import x509
        from cryptography.x509.oid import NameOID

        req = x509.load_pem_x509_csr(csr_pem.encode())
        cn = req.subject.get_attributes_for_oid(NameOID.COMMON_NAME)
        orgs = req.subject.get_attributes_for_oid(
            NameOID.ORGANIZATION_NAME)
        if not cn:
            return None
        return cn[0].value, [o.value for o in orgs]
    except Exception:
        return None


def is_node_bootstrap_csr(csr: api.CertificateSigningRequest) -> bool:
    """approver sarapprove.go isNodeClientCert for the kubeadm join
    flow: a system:bootstrappers requestor asking for a node client
    identity (subject CN system:node:<x>, O [system:nodes]) with the
    kubelet usages."""
    if "system:bootstrappers" not in csr.spec.groups:
        return False
    if set(csr.spec.usages) != KUBELET_USAGES:
        return False
    subj = _pem_subject(csr.spec.request)
    if subj is None:
        return False
    cn, orgs = subj
    return cn.startswith("system:node:") and orgs == ["system:nodes"]


class CSRApprovingController(Controller):
    name = "csrapproving"

    def __init__(self, store):
        super().__init__(store)
        self.informer("certificatesigningrequests")

    def sync(self, key: str):
        name = key.split("/", 1)[-1]
        csr = self.store.get("certificatesigningrequests", "default", name) \
            or self.store.get("certificatesigningrequests", "", name)
        if csr is None or csr.approved or csr.denied:
            return
        if is_self_node_csr(csr):
            csr.status.conditions.append(
                ("Approved", "AutoApproved self node client cert"))
            self.store.update("certificatesigningrequests", csr)
        elif is_node_bootstrap_csr(csr):
            csr.status.conditions.append(
                ("Approved", "AutoApproved node bootstrap client cert"))
            self.store.update("certificatesigningrequests", csr)


class CSRSigningController(Controller):
    name = "csrsigning"

    def __init__(self, store, ca_name: str = "kubernetes-tpu-ca"):
        super().__init__(store)
        self.ca_name = ca_name
        self._ca = None
        self.informer("certificatesigningrequests")

    def _cluster_ca(self):
        if self._ca is None:
            from ..server import pki

            self._ca = pki.ensure_cluster_ca(self.store)
        return self._ca

    def sync(self, key: str):
        name = key.split("/", 1)[-1]
        csr = self.store.get("certificatesigningrequests", "default", name) \
            or self.store.get("certificatesigningrequests", "", name)
        if csr is None or not csr.approved or csr.status.certificate:
            return
        if "BEGIN CERTIFICATE REQUEST" in csr.spec.request:
            # real x509 path (signer.go Sign): issue from the cluster CA
            csr.status.certificate = self._cluster_ca().sign_csr(
                csr.spec.request)
        else:
            digest = hashlib.sha256(
                f"{self.ca_name}/{csr.spec.username}/{csr.spec.request}"
                .encode()).hexdigest()
            csr.status.certificate = f"cert:{csr.spec.username}:{digest[:32]}"
        self.store.update("certificatesigningrequests", csr)
