"""Cloud node controller: initialize nodes from the cloud's view of them.

Reference: pkg/controller/cloud/node_controller.go — a node registers
with the `node.cloudprovider.kubernetes.io/uninitialized` taint
(:71 AddCloudNode path); this controller fills in what only the cloud
knows — addresses (:443), providerID (:391), instance-type and
zone/region labels (:411-437) — then removes the taint so the scheduler
will use the node (:355).
"""

from __future__ import annotations

from ..api import types as api
from ..cloud.provider import LABEL_INSTANCE_TYPE, CloudProvider
from .base import Controller

CLOUD_TAINT = "node.cloudprovider.kubernetes.io/uninitialized"
LABEL_ZONE = "failure-domain.beta.kubernetes.io/zone"
LABEL_REGION = "failure-domain.beta.kubernetes.io/region"


class CloudNodeController(Controller):
    name = "cloud-node"

    def __init__(self, store, cloud: CloudProvider):
        super().__init__(store)
        self.cloud = cloud
        self.informer("nodes",
                      on_add=self.enqueue,
                      on_update=lambda o, n: self.enqueue(n),
                      on_delete=lambda o: None)

    def resync(self):
        for node in self.store.list("nodes"):
            self.enqueue(node)

    def sync(self, key: str):
        _, name = key.split("/", 1)
        node = (self.store.get("nodes", "default", name)
                or self.store.get("nodes", "", name))
        if node is None:
            return
        if not any(t.key == CLOUD_TAINT for t in node.spec.taints):
            return  # already initialized (or not a cloud node)
        instances = self.cloud.instances()
        zones = self.cloud.zones()
        if instances is None:
            return
        # gather every cloud answer BEFORE touching the node: any raise
        # (→ rate-limited retry; registration can out-run the cloud API,
        # :383) must not leave half-initialized state on the live object
        addresses = instances.node_addresses(name)
        provider_id = node.spec.provider_id or instances.instance_id(name)
        itype = instances.instance_type(name)
        zone = zones.get_zone_by_node_name(name) if zones is not None else None
        node.status.addresses = addresses
        node.spec.provider_id = provider_id
        if itype:
            node.metadata.labels[LABEL_INSTANCE_TYPE] = itype
        if zone is not None:
            if zone.failure_domain:
                node.metadata.labels[LABEL_ZONE] = zone.failure_domain
            if zone.region:
                node.metadata.labels[LABEL_REGION] = zone.region
        node.spec.taints = [t for t in node.spec.taints
                            if t.key != CLOUD_TAINT]
        self.store.update("nodes", node)
