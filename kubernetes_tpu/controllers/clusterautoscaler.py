"""Cluster autoscaler: elastic NodeGroups driven by on-device what-ifs.

Reference: kubernetes/autoscaler cluster-autoscaler — RunOnce loops
ScaleUp (estimate which node-group expansion makes the pending pods
feasible) and ScaleDown (find under-utilized nodes whose residents
re-fit elsewhere, then cordon/drain/delete). The reference's
`simulator/` package does both by cloning NodeInfos host-side and
re-running predicates pod by pod; here both what-ifs run on the device
path through `ops/simulate.py` — virtual template rows appended to a
shadow snapshot for scale-up, the gang all-or-nothing plane for the
scale-down joint re-placement proof.

Wiring:
  * feeds off the scheduler's unschedulable map
    (`Scheduler.pending_unschedulable`) and its featurization hook
    (`Scheduler.shadow_featurizer`) so what-if rows encode exactly like
    live ones;
  * NodeGroup membership of live nodes is inferred from the
    `beta.kubernetes.io/instance-type` label the cloud-node controller
    stamps (cloud/provider.py LABEL_INSTANCE_TYPE);
  * respects per-group cooldowns after successful resizes and
    exponential backoff (utils/backoff.py) after cloud failures — a
    `cloud.resize` fault can never double a scale-up: the failed call
    mutated nothing and the group is ineligible until the deadline;
  * emits `TriggeredScaleUp` events on the helped pods and `ScaleDown`
    on removed nodes through client/record.py;
  * scale-down marks the node `spec.unschedulable` (cordon — visible as
    Ready,SchedulingDisabled in `kubectl get nodes`), drains residents
    through the store delete path (their controllers recreate them; the
    refit proof already guaranteed a home), then calls the cloud's
    `delete_nodes` and removes the Node object. A cloud failure after
    the cordon leaves a consistent cluster: the node stays cordoned and
    present (no orphan snapshot rows) and the drain resumes after the
    group's backoff.

Chaos: `autoscaler.simulate` fires before each device what-if,
`cloud.resize` inside the fake cloud's resize calls.

Cost note: a what-if rebuilds the shadow snapshot host-side —
O(nodes + resident pods) re-featurization under the scheduler lock —
so both directions gate it hard: scale-up only when unschedulable pods
AND eligible groups exist, scale-down only after a candidate survives
every cheap filter (group membership, bounds, cooldown, threshold,
replication, PDBs). Passes with nothing to do never take the build
path, and the controller's resync cadence bounds how often the
expensive ones can fire.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Dict, List, Optional, Set, Tuple

from ..api import types as api
from ..client.record import EventRecorder
from ..cloud.provider import LABEL_INSTANCE_TYPE, NodeGroup
from ..ops import encoding as enc
from ..ops import simulate
from ..state.featurize import PodFeaturizer
from ..sched.preemption import _pods_violating_pdb
from ..utils.backoff import PodBackoff
from .base import Controller

LOG = logging.getLogger(__name__)

# Stamped on a node when its drain begins (cordon) and gone only when
# the node is: the durable analog of the reference's
# ToBeDeletedByClusterAutoscaler taint. Without it, a restart between
# cordon and cloud delete would leave the node permanently cordoned —
# the scan's "someone else's cordon: hands off" rule would skip it
# forever (the in-memory _draining set dies with the process).
ANN_SCALE_DOWN = "cluster-autoscaler.kubernetes.io/scale-down-in-progress"


def _replicated(pod: api.Pod) -> bool:
    """Something will recreate this pod after a drain delete (reference
    drain.GetPodsForDeletion: only replicated pods are safely movable —
    a bare pod would be silently destroyed)."""
    return any(ref.controller for ref in pod.metadata.owner_references)


def pick_expansion(options: List[Tuple[NodeGroup, int, int]]
                   ) -> Optional[Tuple[NodeGroup, int]]:
    """Choose one expansion from (group, pods_helped, nodes_needed)
    options: most pods helped first, then cheapest total price, then
    group name for determinism (the reference's `least-waste`/`price`
    expander family collapsed to one rule). Returns (group, nodes)."""
    best = None
    for g, helped, nodes in options:
        if helped <= 0 or nodes <= 0:
            continue
        key = (-helped, g.price * nodes, g.name)
        if best is None or key < best[0]:
            best = (key, g, nodes)
    return None if best is None else (best[1], best[2])


class ClusterAutoscaler(Controller):
    name = "cluster-autoscaler"

    def __init__(self, store, cloud, scheduler, *,
                 utilization_threshold: float = 0.5,
                 scale_up_cooldown: float = 10.0,
                 scale_down_cooldown: float = 60.0,
                 max_virtual_per_group: int = 8,
                 max_pods_per_pass: int = 256,
                 clock: Callable[[], float] = time.monotonic,
                 metrics=None):
        super().__init__(store)
        self.cloud = cloud
        self.scheduler = scheduler
        self.utilization_threshold = utilization_threshold
        self.scale_up_cooldown = scale_up_cooldown
        self.scale_down_cooldown = scale_down_cooldown
        self.max_virtual_per_group = max_virtual_per_group
        self.max_pods_per_pass = max_pods_per_pass
        self.clock = clock
        self.metrics = metrics if metrics is not None else getattr(
            scheduler, "metrics", None)
        self.recorder = EventRecorder(store, "cluster-autoscaler")
        self.backoff = PodBackoff(clock=clock)
        self._cooldown_until: Dict[str, float] = {}  # group -> deadline
        self._retry_at: Dict[str, float] = {}  # group -> backoff deadline
        # nodes we cordoned for removal whose cloud delete hasn't landed
        # yet — picked up again on the next pass regardless of
        # utilization so a mid-drain cloud fault can't strand them
        self._draining: Set[str] = set()
        # introspection for tests/debugging
        self.last_verdict: Optional[simulate.SimulationVerdict] = None
        self.last_scale_up: Optional[Tuple[str, int, List[str]]] = None
        self.last_scale_down: Optional[str] = None

    # -- controller plumbing (periodic RunOnce) --------------------------------

    def resync(self):
        self.enqueue("~/autoscale")

    def sync(self, key: str):
        self.run_once()

    # -- the RunOnce loop ------------------------------------------------------

    def run_once(self) -> Dict[str, int]:
        """One autoscaler pass (reference StaticAutoscaler.RunOnce):
        scale-up first; scale-down only on passes that didn't expand —
        removing capacity while pods are pending would churn."""
        out = {"scaled_up": 0, "scaled_down": 0}
        ng = self.cloud.node_groups() if self.cloud is not None else None
        if ng is None or self.scheduler is None:
            return out
        out["scaled_up"] = self._scale_up(ng)
        if out["scaled_up"] == 0:
            out["scaled_down"] = self._scale_down(ng)
        return out

    def _simulate_backend(self, has_ipa: bool) -> str:
        """What-if execution backend: the numpy host twin while the
        scheduler's device-path breaker is open (a tripped runtime must
        not be dispatched to — the what-if would fail, log, and skip the
        resize every pass), the device otherwise. The twin does not
        carry inter-pod affinity, so has_ipa shadows still attempt the
        device (matching the pre-twin behavior: failure is caught and
        the pass skipped)."""
        from ..sched.breaker import OPEN

        sched = self.scheduler
        if (not has_ipa and sched is not None
                and getattr(sched, "breaker", None) is not None
                and sched.breaker.state == OPEN):
            return "host"
        return "device"

    # -- scale-up --------------------------------------------------------------

    def _eligible_groups(self, ng, now: float) -> List[NodeGroup]:
        out = []
        for g in ng.groups():
            if g.target_size >= g.max_size:
                continue
            if now < self._cooldown_until.get(g.name, 0.0):
                continue
            if now < self._retry_at.get(g.name, 0.0):
                continue
            out.append(g)
        return out

    def _scale_up(self, ng) -> int:
        now = self.clock()
        sched = self.scheduler
        pending = [p for p in sched.pending_unschedulable()
                   if not PodFeaturizer.needs_host_path(p)]
        if not pending:
            return 0
        pending = pending[:self.max_pods_per_pass]
        groups = self._eligible_groups(ng, now)
        if not groups:
            return 0
        try:
            # under the scheduler lock: a consistent cache view and the
            # shared-vocab interning serialized against live waves; the
            # device pass itself runs after release (scratch tensors
            # only — a first-compile must not stall scheduling)
            with sched._mu:
                virtual: List = []
                vgroups: List[NodeGroup] = []
                for g in groups:
                    k = min(g.max_size - g.target_size,
                            self.max_virtual_per_group, len(pending))
                    infos = simulate.virtual_node_infos(g, k)
                    virtual.extend(infos)
                    vgroups.extend([g] * k)
                shadow, n_real = simulate.shadow_snapshot(
                    sched.cache, sched.snapshot, virtual=virtual)
                feat = sched.shadow_featurizer(shadow)
                pb = feat.featurize(pending)
                has_ipa = bool(shadow.has_affinity_terms
                               or pb.ra_has.any() or pb.rn_has.any()
                               or (pb.pa_w != 0).any())
            verdict = simulate.simulate_placements(
                shadow, pb, weights=sched.profile.weights(),
                num_zones=shadow.caps.Z,
                num_label_values=shadow.num_label_values,
                has_ipa=has_ipa,
                backend=self._simulate_backend(has_ipa))
        except Exception as e:
            if self.metrics is not None:
                self.metrics.scheduling_errors.labels(
                    stage="autoscaler").inc()
            LOG.error("scale-up simulation failed: %s: %s",
                      type(e).__name__, e, exc_info=e)
            return 0
        verdict = verdict._replace(n_real=n_real)
        self.last_verdict = verdict
        # demand: pods the scan packed onto virtual rows AND for which
        # no real row is even statically feasible — a pod with a real
        # home (just parked in backoff) must not buy new machines
        helped: Dict[str, List[api.Pod]] = {}
        rows_used: Dict[str, Set[int]] = {}
        for i, pod in enumerate(pending):
            row = int(verdict.chosen[i])
            if row < n_real:
                continue
            if verdict.feasible[i, :n_real].any():
                continue
            g = vgroups[row - n_real]
            helped.setdefault(g.name, []).append(pod)
            rows_used.setdefault(g.name, set()).add(row)
        options = [(g, len(helped.get(g.name, ())),
                    len(rows_used.get(g.name, ())))
                   for g in groups]
        pick = pick_expansion(options)
        if pick is None:
            return 0
        group, need = pick
        try:
            new_names = ng.increase_size(group.name, need)
        except Exception as e:
            # the failed call mutated nothing; the group backs off so a
            # flapping cloud API can't be hammered into a double resize
            self._retry_at[group.name] = now + self.backoff.bump(
                "scaleup:" + group.name)
            if self.metrics is not None:
                self.metrics.scheduling_errors.labels(
                    stage="autoscaler").inc()
            LOG.error("increase_size(%s, %d) failed: %s: %s",
                      group.name, need, type(e).__name__, e)
            return 0
        self.backoff.clear("scaleup:" + group.name)
        self._cooldown_until[group.name] = now + self.scale_up_cooldown
        self.last_scale_up = (group.name, need, new_names)
        if self.metrics is not None:
            self.metrics.autoscaler_scale_ups.inc(need)
        for pod in helped[group.name]:
            self.recorder.event(
                pod, "Normal", "TriggeredScaleUp",
                f"pod triggered scale-up: [{group.name} "
                f"{group.target_size - need}->{group.target_size}]")
        LOG.info("scaled up group %s by %d (pods helped: %d)",
                 group.name, need, len(helped[group.name]))
        return need

    # -- scale-down ------------------------------------------------------------

    def _abort_drain(self, name: str) -> None:
        """Cancel an in-progress scale-down: clear the durable drain
        intent and uncordon so the node returns to service instead of
        sitting cordoned forever and (via the draining-first resume
        rule) shadowing every other candidate. No-op for a node this
        controller never cordoned."""
        self._draining.discard(name)
        node = (self.store.get("nodes", "default", name)
                or self.store.get("nodes", "", name))
        if node is None:
            return
        ann = node.metadata.annotations or {}
        if ANN_SCALE_DOWN not in ann:
            return  # not our cordon (or never cordoned): hands off
        ann.pop(ANN_SCALE_DOWN, None)
        node.spec.unschedulable = False
        self.store.update("nodes", node)
        LOG.info("aborted scale-down of node %s (conditions changed "
                 "since the cordon); node uncordoned", name)

    @staticmethod
    def node_utilization(snapshot, idx: Optional[int]) -> float:
        """max(cpu, memory) requested/allocatable straight from the
        snapshot's resource tensors — no host-cache walk."""
        if idx is None:
            return 0.0
        alloc = snapshot.alloc[idx]
        req = snapshot.requested[idx]
        out = 0.0
        for col in (enc.RES_CPU, enc.RES_MEM):
            if alloc[col] > 0:
                out = max(out, float(req[col]) / float(alloc[col]))
        return out

    def _scale_down(self, ng) -> int:
        now = self.clock()
        sched = self.scheduler
        groups_by_type = {g.instance_type: g for g in ng.groups()}
        pdbs = list(self.store.list("poddisruptionbudgets"))
        cand = None
        sim_args = None
        # under the scheduler lock: the candidate scan over a consistent
        # cache view, the shadow build, and the featurize (shared-vocab
        # interning must serialize with live waves). The device pass
        # itself runs AFTER release — it only touches scratch tensors,
        # and its first-compile-per-shape cost must not stall scheduling.
        with sched._mu:
            live = sched.snapshot
            for name, ni in sched.cache.node_infos.items():
                node = ni.node
                if node is None:
                    continue
                g = groups_by_type.get(
                    (node.metadata.labels or {}).get(LABEL_INSTANCE_TYPE, ""))
                if g is None:
                    continue  # not an autoscaled node
                # drain intent is durable (the node annotation) so a
                # restart mid-drain resumes instead of orphaning a
                # cordoned node behind the hands-off rule below
                draining = (name in self._draining
                            or ANN_SCALE_DOWN in (node.metadata.annotations
                                                  or {}))
                if node.spec.unschedulable and not draining:
                    continue  # someone else's cordon: hands off
                if now < self._retry_at.get(g.name, 0.0):
                    continue
                util = self.node_utilization(live, live.node_index.get(name))
                residents = [p for p in ni.pods
                             if p.metadata.deletion_timestamp is None]
                if not draining:
                    if g.target_size <= g.min_size:
                        continue
                    if now < self._cooldown_until.get(g.name, 0.0):
                        continue  # post-resize settle window
                    if util >= self.utilization_threshold:
                        continue
                    # only replicated pods survive a drain delete (their
                    # controller recreates them) — a bare pod pins the
                    # node (reference drain.GetPodsForDeletion)
                    if any(not _replicated(p) for p in residents):
                        continue
                    # the drain deletes every resident at once: any pod
                    # whose PDB has no disruptions left pins the node
                    violating, _ok = _pods_violating_pdb(residents, pdbs)
                    if violating:
                        continue
                if any(PodFeaturizer.needs_host_path(p) for p in residents):
                    continue  # can't prove the refit on device: keep it
                if cand is None or util < cand[0] or draining:
                    cand = (util, name, g, residents)
                    if draining:
                        break  # finish an interrupted drain first
            if cand is None:
                return 0
            util, name, g, residents = cand
            if residents:
                try:
                    shadow, _ = simulate.shadow_snapshot(
                        sched.cache, live, exclude={name})
                    feat = sched.shadow_featurizer(shadow)
                    free = [simulate.strip_node_name(p) for p in residents]
                    pb = feat.featurize(free)
                    has_ipa = bool(shadow.has_affinity_terms
                                   or pb.ra_has.any() or pb.rn_has.any()
                                   or (pb.pa_w != 0).any())
                    sim_args = (shadow, pb, has_ipa)
                except Exception as e:
                    if self.metrics is not None:
                        self.metrics.scheduling_errors.labels(
                            stage="autoscaler").inc()
                    LOG.error("scale-down featurization failed: %s: %s",
                              type(e).__name__, e, exc_info=e)
                    return 0
        if sim_args is not None:
            # joint re-placement proof on the remaining cluster, outside
            # the scheduler lock (scratch tensors only)
            shadow, pb, has_ipa = sim_args
            try:
                ok, _chosen = simulate.simulate_refit(
                    shadow, pb, len(residents),
                    weights=sched.profile.weights(),
                    num_zones=shadow.caps.Z,
                    num_label_values=shadow.num_label_values,
                    has_ipa=has_ipa,
                    backend=self._simulate_backend(has_ipa))
            except Exception as e:
                if self.metrics is not None:
                    self.metrics.scheduling_errors.labels(
                        stage="autoscaler").inc()
                LOG.error("scale-down simulation failed: %s: %s",
                          type(e).__name__, e, exc_info=e)
                return 0
            if not ok:
                # residents can't all re-fit: the node stays. A RESUMED
                # drain failing this proof (capacity shrank since the
                # cordon) must abort — leaving the annotation would
                # re-select this node every pass (starving other
                # candidates) and hold it cordoned forever.
                self._abort_drain(name)
                return 0
        # a resumed drain must re-check the min floor — the group may
        # have shrunk below it since the cordon (another drain landed)
        if g.target_size - 1 < g.min_size:
            self._abort_drain(name)
            return 0
        # API mutations OUTSIDE the scheduler lock: the informer fan-out
        # of each write re-enters the scheduler's handlers
        node = (self.store.get("nodes", "default", name)
                or self.store.get("nodes", "", name))
        if node is None:
            self._draining.discard(name)
            return 0
        if not node.spec.unschedulable:
            node.spec.unschedulable = True  # cordon: SchedulingDisabled
            node.metadata.annotations[ANN_SCALE_DOWN] = "true"
            self.store.update("nodes", node)
        self._draining.add(name)
        # the refit ran BEFORE the cordon landed: a concurrent wave may
        # have bound new pods to the still-schedulable node in that
        # window. Re-read residents now that the cordon stops further
        # binds — any newcomer was never part of the proof, so the drain
        # aborts (uncordon) rather than orphan it onto a deleted node.
        with sched._mu:
            ni_now = sched.cache.node_infos.get(name)
            now_res = ([p for p in ni_now.pods
                        if p.metadata.deletion_timestamp is None]
                       if ni_now is not None else [])
        proved = {p.uid for p in residents}
        if any(p.uid not in proved for p in now_res):
            self._abort_drain(name)
            return 0
        for p in residents:
            try:
                self.store.delete("pods", p.namespace, p.metadata.name)
            except KeyError:
                pass  # already gone
        try:
            ng.delete_nodes(g.name, [name])
        except Exception as e:
            # consistent failure mode: the node stays cordoned + present
            # (no orphan snapshot rows — the Node object still backs its
            # row) and the drain resumes after the group's backoff
            self._retry_at[g.name] = now + self.backoff.bump(
                "scaledown:" + g.name)
            if self.metrics is not None:
                self.metrics.scheduling_errors.labels(
                    stage="autoscaler").inc()
            LOG.error("delete_nodes(%s, [%s]) failed: %s: %s",
                      g.name, name, type(e).__name__, e)
            return 0
        self.backoff.clear("scaledown:" + g.name)
        try:
            self.store.delete("nodes", node.metadata.namespace, name)
        except KeyError:
            pass
        self._draining.discard(name)
        self._cooldown_until[g.name] = now + self.scale_down_cooldown
        self.last_scale_down = name
        if self.metrics is not None:
            self.metrics.autoscaler_scale_downs.inc()
        self.recorder.event(
            node, "Normal", "ScaleDown",
            f"node removed by cluster autoscaler "
            f"(utilization {util:.2f} < {self.utilization_threshold:.2f})")
        LOG.info("scaled down: removed node %s from group %s "
                 "(utilization %.2f)", name, g.name, util)
        return 1
