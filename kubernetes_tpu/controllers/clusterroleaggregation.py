"""ClusterRole aggregation controller.

Reference: pkg/controller/clusterroleaggregation/clusterroleaggregation_
controller.go — a ClusterRole carrying an aggregationRule owns no rules
of its own: the controller maintains its rules as the union of every
ClusterRole matching the rule's label selectors (how admin/edit/view
pick up CRD-shipped permission fragments). Any labeled-role change
re-enqueues every aggregating role.
"""

from __future__ import annotations

from typing import List

from ..api import types as api
from .base import Controller


def _rule_key(r: api.RBACPolicyRule):
    return (tuple(r.verbs or ()), tuple(r.api_groups or ()),
            tuple(r.resources or ()), tuple(r.resource_names or ()),
            tuple(r.non_resource_urls or ()))


class ClusterRoleAggregationController(Controller):
    name = "clusterroleaggregation"

    def __init__(self, store):
        super().__init__(store)
        self.informer("clusterroles",
                      enqueue_fn=lambda o=None, n=None:
                      self._enqueue_aggregating())

    def _enqueue_aggregating(self):
        for role in self.store.list("clusterroles"):
            if role.aggregation_selectors:
                self.enqueue(role)

    def sync(self, key: str):
        _, name = key.split("/", 1)
        role = self.store.get("clusterroles", "", name)
        if role is None or not role.aggregation_selectors:
            return
        union: List[api.RBACPolicyRule] = []
        seen = set()
        for other in sorted(self.store.list("clusterroles"),
                            key=lambda r: r.metadata.name):
            if other.metadata.name == role.metadata.name:
                continue
            labels = other.metadata.labels or {}
            if not any(sel.to_selector().matches(labels)
                       for sel in role.aggregation_selectors):
                continue
            for r in other.rules:
                k = _rule_key(r)
                if k not in seen:
                    seen.add(k)
                    union.append(r)
        if [_rule_key(r) for r in role.rules] == [_rule_key(r)
                                                 for r in union]:
            return
        role.rules = union
        self.store.update("clusterroles", role)
