"""CronJob: scheduled Job creation.

Reference: pkg/controller/cronjob/cronjob_controller.go (syncOne:224 —
next-schedule computation, concurrencyPolicy Allow/Forbid/Replace,
active-job bookkeeping). Unlike most controllers this one polls (the
reference syncs all cronjobs every 10s, cronjob_controller.go:98); here
``tick(now)`` advances it, and run() wraps tick in a timer loop.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from ..api import types as api
from ..runtime.store import Conflict
from .base import Controller


def cron_matches(schedule: str, t: float) -> bool:
    """Does epoch-time t (minute resolution) match the 5-field cron spec?
    Supports '*', '*/n', 'a', 'a-b', and comma lists."""
    lt = time.gmtime(t)
    fields = schedule.split()
    if len(fields) != 5:
        return False
    vals = (lt.tm_min, lt.tm_hour, lt.tm_mday, lt.tm_mon, lt.tm_wday)
    # cron: 0=Sunday; python: 0=Monday
    vals = vals[:4] + ((lt.tm_wday + 1) % 7,)

    def field_ok(spec: str, v: int) -> bool:
        for part in spec.split(","):
            if part == "*":
                return True
            if part.startswith("*/"):
                if v % int(part[2:]) == 0:
                    return True
            elif "-" in part:
                lo, hi = part.split("-", 1)
                if int(lo) <= v <= int(hi):
                    return True
            elif part.isdigit() and int(part) == v:
                return True
        return False

    return all(field_ok(s, v) for s, v in zip(fields, vals))


class CronJobController(Controller):
    name = "cronjob"

    def __init__(self, store, clock=time.time):
        super().__init__(store)
        self.clock = clock
        self._timer: Optional[threading.Thread] = None

    def sync(self, key: str):
        ns, name = key.split("/", 1)
        cj = self.store.get("cronjobs", ns, name)
        if cj is not None:
            self._sync_one(cj, self.clock())

    def tick(self, now: Optional[float] = None) -> int:
        """Sync every cronjob against `now`. Returns jobs started."""
        now = now if now is not None else self.clock()
        started = 0
        for cj in self.store.list("cronjobs"):
            started += self._sync_one(cj, now)
        return started

    def _sync_one(self, cj: api.CronJob, now: float) -> int:
        # refresh active list from live jobs
        ns = cj.metadata.namespace
        active = []
        for jname in cj.status.active:
            job = self.store.get("jobs", ns, jname)
            if job is not None and not any(
                    c[0] in ("Complete", "Failed") and str(c[1]).startswith("True")
                    for c in job.status.conditions):
                active.append(jname)
        if active != cj.status.active:
            cj.status.active = active
            self._update(cj)
        if cj.spec.suspend:
            return 0
        minute = int(now // 60) * 60
        if cj.status.last_schedule_time is not None and \
                cj.status.last_schedule_time >= minute:
            return 0
        if not cron_matches(cj.spec.schedule, minute):
            return 0
        if active:
            if cj.spec.concurrency_policy == "Forbid":
                return 0
            if cj.spec.concurrency_policy == "Replace":
                for jname in active:
                    try:
                        self.store.delete("jobs", ns, jname)
                    except KeyError:
                        pass
                active = []
        job = api.Job(
            metadata=api.ObjectMeta(
                name=f"{cj.metadata.name}-{int(minute // 60)}",
                namespace=ns,
                labels=dict(cj.spec.job_template_meta.labels or {}),
                owner_references=[api.OwnerReference(
                    kind="CronJob", name=cj.metadata.name,
                    uid=cj.metadata.uid, controller=True)]),
            spec=cj.spec.job_template or api.JobSpec())
        try:
            self.store.create("jobs", job)
        except Conflict:
            return 0
        cj.status.active = active + [job.metadata.name]
        cj.status.last_schedule_time = minute
        self._update(cj)
        return 1

    def _update(self, cj):
        try:
            self.store.update("cronjobs", cj)
        except (Conflict, KeyError):
            pass

    def run(self, workers: int = 1, period: float = 10.0):
        def loop():
            while not self._stop.is_set():
                self.tick()
                self._stop.wait(period)

        self._timer = threading.Thread(target=loop, daemon=True,
                                       name="cronjob-tick")
        self._timer.start()
