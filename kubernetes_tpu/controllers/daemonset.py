"""DaemonSet: one pod per eligible node.

Reference: pkg/controller/daemon/daemon_controller.go (syncDaemonSet:
1075 manage:754 — nodesShouldRunDaemonPod:1206 decides per node via the
scheduler's own GeneralPredicates + taint checks; daemon pods are
created with spec.nodeName pre-set, bypassing the scheduler in 1.11).
"""

from __future__ import annotations

from typing import List

from ..api import types as api
from ..runtime.store import Conflict
from ..plugins import golden
from ..state.node_info import NodeInfo
from .base import (Controller, is_pod_active, is_pod_ready,
                   make_pod_from_template, pod_owned_by)
from .history import REV_LABEL
from .nodelifecycle import TAINT_NOT_READY, TAINT_UNREACHABLE


def add_daemon_tolerations(pod: api.Pod) -> api.Pod:
    """Stamp the not-ready/unreachable NoExecute tolerations on a daemon
    pod (1.11 daemon_controller.go util.AddOrUpdateDaemonPodTolerations):
    a daemon pod exists BECAUSE of its node — evicting it off a failed
    node just respawns it there in a loop, so it tolerates its own
    node's failure taints forever (no tolerationSeconds). Existing
    (key, effect)-matching tolerations are left alone."""
    for key in (TAINT_NOT_READY, TAINT_UNREACHABLE):
        if not any(t.key in ("", key) and t.effect in ("", api.NO_EXECUTE)
                   for t in pod.spec.tolerations):
            pod.spec.tolerations.append(api.Toleration(
                key=key, operator=api.TOLERATION_OP_EXISTS,
                effect=api.NO_EXECUTE))
    return pod


class DaemonSetController(Controller):
    name = "daemonset"

    def __init__(self, store):
        super().__init__(store)
        self.informer("daemonsets")
        self.informer("nodes", enqueue_fn=lambda o: self._all_dirty())
        self.informer("pods",
                      on_add=self._pod_event,
                      on_update=lambda o, n: self._pod_event(n),
                      on_delete=self._pod_event)

    def _all_dirty(self):
        for ds in self.store.list("daemonsets"):
            self.enqueue(ds)

    def _pod_event(self, pod):
        for ref in pod.metadata.owner_references:
            if ref.controller and ref.kind == "DaemonSet":
                self.queue.add(f"{pod.metadata.namespace}/{ref.name}")

    def _should_run(self, ds, node: api.Node) -> bool:
        """nodesShouldRunDaemonPod: simulate the daemon pod on the node —
        GeneralPredicates (incl. resource fit against existing pods),
        taints (daemon pods tolerate memory/disk-pressure implicitly in
        1.11), schedulability (daemon_controller.go:1206)."""
        if node.spec.unschedulable:
            return False
        pod = add_daemon_tolerations(make_pod_from_template(
            ds.spec.template, "DaemonSet", ds, "sim"))
        pod.spec.node_name = node.metadata.name
        ni = NodeInfo(node)
        for existing in self.store.list("pods"):
            if existing.spec.node_name == node.metadata.name and \
                    is_pod_active(existing) and not pod_owned_by(
                        existing, "DaemonSet", ds.metadata.name):
                ni.add_pod(existing)
        ok, _ = golden.general_predicates(pod, ni)
        if not ok:
            return False
        ok, _ = golden.pod_tolerates_node_taints(pod, ni)
        if not ok:
            return False
        ok, _ = golden.check_node_condition(pod, ni)
        return ok

    def sync(self, key: str):
        from . import history

        ns, name = key.split("/", 1)
        ds = self.store.get("daemonsets", ns, name)
        if ds is None:
            return
        # rollout history (daemon/update.go constructHistory): snapshot
        # the current template as a ControllerRevision and reap history
        # beyond the limit; live revisions (a pod still wears the hash)
        # are never reaped. The revision hash is ALSO the staleness
        # label — one content hash drives update decisions and history,
        # like the reference's controller-revision-hash
        revisions = history.list_revisions(self.store, ds, "DaemonSet")
        rev = history.sync_revision(self.store, ds, "DaemonSet",
                                    ds.spec.template, revisions=revisions)
        cur_hash = (rev.metadata.labels or {}).get(REV_LABEL, "")
        nodes = self.store.list("nodes")
        owned: List[api.Pod] = [
            p for p in self.store.list("pods", ns)
            if any(r.controller and r.kind == "DaemonSet" and r.name == name
                   for r in p.metadata.owner_references)]
        history.truncate_history(
            self.store, ds, "DaemonSet",
            live_hashes={(p.metadata.labels or {}).get(REV_LABEL)
                         for p in owned if is_pod_active(p)},
            keep_names={rev.metadata.name},
            revisions=revisions)
        by_node = {}
        for p in owned:
            by_node.setdefault(p.spec.node_name, []).append(p)
        desired = 0
        scheduled = 0
        misscheduled = 0
        updated = 0
        unavailable = 0
        stale_ready: List[api.Pod] = []
        for node in nodes:
            should = self._should_run(ds, node)
            have = [p for p in by_node.pop(node.metadata.name, [])
                    if is_pod_active(p)]
            if should:
                desired += 1
                if have:
                    scheduled += 1
                    # dedupe keeps a CURRENT-hash pod when one exists —
                    # deleting the fresh replacement instead of the
                    # stale duplicate would churn an extra round
                    have.sort(key=lambda p: (p.metadata.labels or {})
                              .get(REV_LABEL) != cur_hash)
                    for extra in have[1:]:
                        self._delete(extra)
                    p = have[0]
                    p_hash = (p.metadata.labels or {}).get(REV_LABEL)
                    if p_hash == cur_hash:
                        updated += 1
                        if not is_pod_ready(p):
                            unavailable += 1
                    elif not is_pod_ready(p):
                        # a stale not-ready pod costs nothing to replace
                        # (update.go rollingUpdate deletes these first)
                        unavailable += 1
                        if ds.spec.update_strategy.type != "OnDelete":
                            self._delete(p)
                    else:
                        stale_ready.append(p)
                else:
                    unavailable += 1
                    pod = add_daemon_tolerations(make_pod_from_template(
                        ds.spec.template, "DaemonSet", ds,
                        f"{name}-{node.metadata.name}"))
                    pod.spec.node_name = node.metadata.name
                    pod.metadata.labels = dict(
                        pod.metadata.labels or {},
                        **{REV_LABEL: cur_hash})
                    try:
                        self.store.create("pods", pod)
                    except Conflict:
                        pass
            else:
                for p in have:
                    misscheduled += 1
                    self._delete(p)
        # RollingUpdate (daemon/update.go): replace READY stale pods
        # only within the maxUnavailable budget; the manage pass above
        # recreates them at the new hash on the next sync
        if ds.spec.update_strategy.type != "OnDelete":
            budget = max(
                0, ds.spec.update_strategy.max_unavailable - unavailable)
            for p in stale_ready[:budget]:
                self._delete(p)
        for orphans in by_node.values():  # pods on deleted nodes
            for p in orphans:
                self._delete(p)
        self._update_status(ds, desired, scheduled, misscheduled, updated)

    def _delete(self, pod):
        try:
            self.store.delete("pods", pod.metadata.namespace, pod.metadata.name)
        except KeyError:
            pass

    def _update_status(self, ds, desired, scheduled, misscheduled,
                       updated=0):
        st = ds.status
        ready = 0
        for p in self.store.list("pods", ds.metadata.namespace):
            if any(r.controller and r.kind == "DaemonSet"
                   and r.name == ds.metadata.name
                   for r in p.metadata.owner_references) and is_pod_ready(p):
                ready += 1
        gen = ds.metadata.generation
        if (st.desired_number_scheduled, st.current_number_scheduled,
                st.number_misscheduled, st.number_ready,
                st.updated_number_scheduled, st.observed_generation) == \
                (desired, scheduled, misscheduled, ready, updated, gen):
            return
        st.desired_number_scheduled = desired
        st.current_number_scheduled = scheduled
        st.number_misscheduled = misscheduled
        st.number_ready = ready
        st.updated_number_scheduled = updated
        st.observed_generation = gen
        try:
            self.store.update("daemonsets", ds)
        except (Conflict, KeyError):
            pass
