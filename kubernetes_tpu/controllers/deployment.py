"""Deployment -> ReplicaSet rollout management.

Reference: pkg/controller/deployment/deployment_controller.go
(syncDeployment:560) + rolling.go (rolloutRolling: scale up the new RS
within maxSurge, scale down olds within maxUnavailable) + sync.go
(getNewReplicaSet: RS per pod-template hash). Recreate strategy scales
olds to zero before creating the new RS (recreate.go).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from ..api import scheme
from ..api import types as api
from ..api.labels import LabelSelector
from ..runtime.store import Conflict
from .base import Controller

HASH_LABEL = "pod-template-hash"
# rollout history bookkeeping (deployment/util/deployment_util.go:36
# RevisionAnnotation): each RS keeps the revision it served; the
# deployment carries the current one; `kubectl rollout undo` resolves a
# revision back to its RS's template
REVISION_ANNOTATION = "deployment.kubernetes.io/revision"


def template_hash(template: api.PodTemplateSpec) -> str:
    """Stable hash of the pod template (util/hash ComputeHash analog)."""
    enc = scheme.encode(template)
    enc.get("metadata", {}).pop("uid", None)
    return scheme.stable_hash(enc, 10)


class DeploymentController(Controller):
    name = "deployment"

    def __init__(self, store):
        super().__init__(store)
        self.informer("deployments")
        self.informer("replicasets",
                      on_add=self._rs_event,
                      on_update=lambda o, n: self._rs_event(n),
                      on_delete=self._rs_event)

    def _rs_event(self, rs):
        for ref in rs.metadata.owner_references:
            if ref.controller and ref.kind == "Deployment":
                self.queue.add(f"{rs.metadata.namespace}/{ref.name}")

    # -- RS management ---------------------------------------------------------

    def _owned_replicasets(self, dep) -> List[api.ReplicaSet]:
        out = []
        for rs in self.store.list("replicasets", dep.metadata.namespace):
            if any(r.controller and r.kind == "Deployment"
                   and r.name == dep.metadata.name
                   for r in rs.metadata.owner_references):
                out.append(rs)
        return out

    def _new_and_old(self, dep) -> Tuple[Optional[api.ReplicaSet],
                                         List[api.ReplicaSet]]:
        h = template_hash(dep.spec.template)
        new, old = None, []
        for rs in self._owned_replicasets(dep):
            if (rs.metadata.labels or {}).get(HASH_LABEL) == h:
                new = rs
            else:
                old.append(rs)
        return new, old

    def _create_new_rs(self, dep) -> api.ReplicaSet:
        import copy
        h = template_hash(dep.spec.template)
        template = copy.deepcopy(dep.spec.template)
        template.metadata.labels = dict(template.metadata.labels or {})
        template.metadata.labels[HASH_LABEL] = h
        base_sel = dep.spec.selector or LabelSelector()
        sel = LabelSelector(
            match_labels={**dict(base_sel.match_labels), HASH_LABEL: h},
            match_expressions=base_sel.match_expressions)
        rs = api.ReplicaSet(
            metadata=api.ObjectMeta(
                name=f"{dep.metadata.name}-{h}",
                namespace=dep.metadata.namespace,
                labels=dict(template.metadata.labels),
                owner_references=[api.OwnerReference(
                    kind="Deployment", name=dep.metadata.name,
                    uid=dep.metadata.uid, controller=True)]),
            spec=api.ReplicaSetSpec(replicas=0, selector=sel,
                                    template=template))
        try:
            return self.store.create("replicasets", rs)
        except Conflict:
            return self.store.get("replicasets", dep.metadata.namespace,
                                  rs.metadata.name)

    def _scale(self, rs: api.ReplicaSet, replicas: int):
        if rs.spec.replicas == replicas:
            return
        rs.spec.replicas = replicas
        self.store.update("replicasets", rs)

    # -- sync ------------------------------------------------------------------

    def sync(self, key: str):
        ns, name = key.split("/", 1)
        dep = self.store.get("deployments", ns, name)
        if dep is None:
            return
        if dep.spec.paused:
            return
        new_rs, old_rss = self._new_and_old(dep)
        if new_rs is None:
            new_rs = self._create_new_rs(dep)
        self._ensure_revision(dep, new_rs, old_rss)
        want = dep.spec.replicas
        if dep.spec.strategy.type == "Recreate":
            # scale olds to zero first; only then bring up the new RS
            if any(rs.spec.replicas > 0 or rs.status.replicas > 0
                   for rs in old_rss):
                for rs in old_rss:
                    self._scale(rs, 0)
                raise RuntimeError("waiting for old replicasets to scale down")
            self._scale(new_rs, want)
        else:
            # pure scale-down (deployment/sync.go scale(): replica-count
            # changes apply before rollout arithmetic — without this, a
            # deployment shrunk by the HPA never scales its new RS down)
            if new_rs.spec.replicas > want:
                self._scale(new_rs, want)
            # RollingUpdate (deployment/rolling.go): total <= want+maxSurge;
            # available >= want-maxUnavailable
            max_surge = dep.spec.strategy.max_surge
            max_unavailable = dep.spec.strategy.max_unavailable
            total = new_rs.spec.replicas + sum(r.spec.replicas for r in old_rss)
            # scale up new within the surge budget
            up_room = want + max_surge - total
            if up_room > 0 and new_rs.spec.replicas < want:
                self._scale(new_rs, min(want, new_rs.spec.replicas + up_room))
            # scale down olds while keeping availability
            ready = new_rs.status.ready_replicas + \
                sum(r.status.ready_replicas for r in old_rss)
            down_room = ready - (want - max_unavailable)
            for rs in sorted(old_rss, key=lambda r: r.spec.replicas,
                             reverse=True):
                if down_room <= 0:
                    break
                step = min(rs.spec.replicas, down_room)
                if step > 0:
                    self._scale(rs, rs.spec.replicas - step)
                    down_room -= step
        self._update_status(dep, new_rs, old_rss)
        if any(rs.spec.replicas > 0 for rs in old_rss) or \
                new_rs.spec.replicas != want:
            raise RuntimeError("rollout in progress")  # requeue to continue

    def _ensure_revision(self, dep, new_rs, old_rss):
        """deployment_util.go:180 SetNewReplicaSetAnnotations: the RS
        serving the current template gets maxOldRevision+1 (an undo that
        re-selects an old RS bumps it to the newest revision); the
        deployment mirrors the current revision."""
        max_old = max([int(rs.metadata.annotations.get(
            REVISION_ANNOTATION, 0)) for rs in old_rss] + [0])
        cur = int(new_rs.metadata.annotations.get(REVISION_ANNOTATION, 0))
        if cur <= max_old:
            new_rs.metadata.annotations[REVISION_ANNOTATION] = str(max_old + 1)
            try:
                self.store.update("replicasets", new_rs)
            except (Conflict, KeyError):
                return
        rev = new_rs.metadata.annotations[REVISION_ANNOTATION]
        if dep.metadata.annotations.get(REVISION_ANNOTATION) != rev:
            dep.metadata.annotations[REVISION_ANNOTATION] = rev
            try:
                self.store.update("deployments", dep)
            except (Conflict, KeyError):
                pass

    def _update_status(self, dep, new_rs, old_rss):
        all_rs = [new_rs] + old_rss
        st = dep.status
        new_st = api.DeploymentStatus(
            replicas=sum(r.status.replicas for r in all_rs),
            updated_replicas=new_rs.status.replicas,
            ready_replicas=sum(r.status.ready_replicas for r in all_rs),
            available_replicas=sum(r.status.ready_replicas for r in all_rs),
            unavailable_replicas=max(
                0, dep.spec.replicas - sum(r.status.ready_replicas
                                           for r in all_rs)),
            observed_generation=dep.metadata.generation)
        if (st.replicas, st.updated_replicas, st.ready_replicas,
                st.observed_generation) == \
                (new_st.replicas, new_st.updated_replicas,
                 new_st.ready_replicas, new_st.observed_generation):
            return
        dep.status = new_st
        try:
            self.store.update("deployments", dep)
        except (Conflict, KeyError):
            pass
