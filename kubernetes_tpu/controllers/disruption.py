"""Disruption controller: PDB status accounting.

Reference: pkg/controller/disruption/disruption.go (trySync:498 —
expectedCount from the pod's controller scale or minAvailable,
currentHealthy from ready pods, disruptionsAllowed = healthy - desired).
The scheduler's preemption consumes status.disruptionsAllowed
(generic_scheduler.go:228 ListPDBs -> filterPodsWithPDBViolation).
"""

from __future__ import annotations

from ..api import types as api
from ..runtime.store import Conflict
from .base import Controller, is_pod_active, is_pod_ready


class DisruptionController(Controller):
    name = "disruption"

    def __init__(self, store):
        super().__init__(store)
        self.informer("poddisruptionbudgets")
        self.informer("pods",
                      on_add=self._pod_event,
                      on_update=self._pod_update,
                      on_delete=self._pod_event)

    def _pod_update(self, old, new):
        # formerly-matching PDBs must recount when labels change
        self._pod_event(old)
        self._pod_event(new)

    def _pod_event(self, pod):
        labels = pod.metadata.labels or {}
        for pdb in self.store.list("poddisruptionbudgets",
                                   pod.metadata.namespace):
            sel = pdb.spec.selector
            if sel is not None and sel.matches(labels):
                self.enqueue(pdb)

    def _expected_count(self, pdb, pods) -> int:
        """expectedCount: from owning workloads' .spec.replicas, falling
        back to matched-pod count (disruption.go getExpectedPodCount)."""
        total = 0
        seen = set()
        for pod in pods:
            ref = next((r for r in pod.metadata.owner_references
                        if r.controller), None)
            if ref is None:
                total += 1
                continue
            key = (ref.kind, ref.name)
            if key in seen:
                continue
            seen.add(key)
            kind_map = {"ReplicaSet": "replicasets",
                        "ReplicationController": "replicationcontrollers",
                        "StatefulSet": "statefulsets",
                        "Deployment": "deployments"}
            plural = kind_map.get(ref.kind)
            owner = self.store.get(plural, pod.metadata.namespace, ref.name) \
                if plural else None
            total += owner.spec.replicas if owner is not None else 1
        return total

    def sync(self, key: str):
        ns, name = key.split("/", 1)
        pdb = self.store.get("poddisruptionbudgets", ns, name)
        if pdb is None:
            return
        sel = pdb.spec.selector
        pods = [p for p in self.store.list("pods", ns)
                if sel is not None and sel.matches(p.metadata.labels or {})
                and is_pod_active(p)]
        healthy = sum(1 for p in pods if is_pod_ready(p))
        expected = self._expected_count(pdb, pods)
        if pdb.spec.min_available is not None:
            desired = pdb.spec.min_available
        elif pdb.spec.max_unavailable is not None:
            desired = max(0, expected - pdb.spec.max_unavailable)
        else:
            desired = expected
        allowed = max(0, healthy - desired)
        st = pdb.status
        if (st.current_healthy, st.desired_healthy, st.expected_pods,
                st.disruptions_allowed) == (healthy, desired, expected, allowed):
            return
        st.current_healthy = healthy
        st.desired_healthy = desired
        st.expected_pods = expected
        st.disruptions_allowed = allowed
        try:
            self.store.update("poddisruptionbudgets", pdb)
        except (Conflict, KeyError):
            pass
