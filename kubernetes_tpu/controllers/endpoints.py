"""Endpoints: service -> ready pod addresses.

Reference: pkg/controller/endpoint/endpoints_controller.go
(syncService:397 — list pods matching the service selector, split by
readiness into addresses / notReadyAddresses, mirror service ports).
kube-proxy consumes the result to build its forwarding rules.
"""

from __future__ import annotations

from ..api import labels as lbl
from ..api import types as api
from ..runtime.store import Conflict
from .base import Controller, is_pod_active, is_pod_ready


def _pod_ip(pod: api.Pod) -> str:
    """The pod's address: status.podIP once the kubelet's network
    plugin assigned one (endpoints_controller.go reads exactly this);
    uid-hash fallback for pods no kubelet serves (pure control-plane
    tests)."""
    if pod.status.pod_ip:
        return pod.status.pod_ip
    from ..kubelet.network import HashIPPlugin

    return HashIPPlugin().setup_pod(pod.metadata.uid)


class EndpointsController(Controller):
    name = "endpoints"

    def __init__(self, store):
        super().__init__(store)
        self.informer("services")
        self.informer("pods",
                      on_add=self._pod_event,
                      on_update=self._pod_update,
                      on_delete=self._pod_event)

    def _pod_event(self, pod: api.Pod):
        labels = pod.metadata.labels or {}
        for svc in self.store.list("services", pod.metadata.namespace):
            if svc.selector and lbl.Selector.from_set(svc.selector).matches(labels):
                self.enqueue(svc)

    def _pod_update(self, old: api.Pod, new: api.Pod):
        # enqueue services matching the OLD labels too, so a relabeled pod
        # is removed from formerly-matching endpoints (reference updatePod)
        self._pod_event(old)
        self._pod_event(new)

    def sync(self, key: str):
        ns, name = key.split("/", 1)
        svc = self.store.get("services", ns, name)
        if svc is None:
            try:
                self.store.delete("endpoints", ns, name)
            except KeyError:
                pass
            return
        if not svc.selector:
            return  # headless/manual endpoints are user-managed
        sel = lbl.Selector.from_set(svc.selector)
        ready, not_ready = [], []
        for pod in self.store.list("pods", ns):
            if not sel.matches(pod.metadata.labels or {}):
                continue
            if not is_pod_active(pod) or not pod.spec.node_name:
                continue
            addr = api.EndpointAddress(
                ip=_pod_ip(pod), node_name=pod.spec.node_name,
                target_pod=pod.full_name())
            (ready if is_pod_ready(pod) else not_ready).append(addr)
        ports = [api.EndpointPort(name=p.name, port=p.target_port or p.port,
                                  protocol=p.protocol)
                 for p in svc.spec.ports] or [api.EndpointPort(port=0)]
        subset = api.EndpointSubset(
            addresses=sorted(ready, key=lambda a: a.ip),
            not_ready_addresses=sorted(not_ready, key=lambda a: a.ip),
            ports=ports)
        existing = self.store.get("endpoints", ns, name)
        if existing is None:
            ep = api.Endpoints(metadata=api.ObjectMeta(name=name, namespace=ns),
                               subsets=[subset])
            try:
                self.store.create("endpoints", ep)
            except Conflict:
                pass
        else:
            if existing.subsets and _subsets_equal(existing.subsets[0], subset):
                return
            existing.subsets = [subset]
            try:
                self.store.update("endpoints", existing)
            except (Conflict, KeyError):
                pass


def _subsets_equal(a: api.EndpointSubset, b: api.EndpointSubset) -> bool:
    key = lambda addrs: [(x.ip, x.node_name) for x in addrs]  # noqa: E731
    return (key(a.addresses) == key(b.addresses)
            and key(a.not_ready_addresses) == key(b.not_ready_addresses)
            and [(p.name, p.port) for p in a.ports] ==
                [(p.name, p.port) for p in b.ports])
