"""Volume expansion controller.

Reference: pkg/controller/volume/expand/ (expand_controller.go +
sync_volume_resize.go): a bound PVC whose spec.requests.storage grew
past status.capacity gets its PV grown (the controller-side expand),
then carries FileSystemResizePending until the node-side filesystem
resize completes — done here by the kubelet's volume housekeeping for
claims mounted by its pods, and immediately by this controller for
unattached claims (the offline-resize path).
"""

from __future__ import annotations

from ..api import resources as res
from ..api import types as api
from ..runtime.store import Conflict
from .base import Controller

RESIZING = "Resizing"
FS_RESIZE_PENDING = "FileSystemResizePending"


def _cond_set(pvc, ctype: str, value: str = "True"):
    pvc.status.conditions = [c for c in pvc.status.conditions
                             if c[0] != ctype] + [(ctype, value)]


def _cond_clear(pvc, *ctypes):
    pvc.status.conditions = [c for c in pvc.status.conditions
                             if c[0] not in ctypes]


def claim_in_use(store, pvc) -> bool:
    """A pod on some node mounts the claim (expand_controller's
    in-use check deciding online vs offline finish)."""
    for p in store.list("pods", pvc.metadata.namespace):
        if not p.spec.node_name or p.status.phase not in (
                "Pending", "Running"):
            continue
        for v in p.spec.volumes:
            if getattr(v, "pvc_name", "") == pvc.metadata.name:
                return True
    return False


def finish_resize(store, pvc):
    """The node-side half (operation_executor MarkVolumeAsResized):
    grant the new size on the claim and clear the pending condition."""
    want = pvc.spec.requests.get(res.STORAGE, 0)
    pvc.status.capacity[res.STORAGE] = want
    _cond_clear(pvc, RESIZING, FS_RESIZE_PENDING)
    try:
        store.update("persistentvolumeclaims", pvc)
    except (Conflict, KeyError):
        pass


class ExpandController(Controller):
    name = "expand"

    def __init__(self, store):
        super().__init__(store)
        self.informer("persistentvolumeclaims")

    def sync(self, key: str):
        ns, name = key.split("/", 1)
        pvc = self.store.get("persistentvolumeclaims", ns, name)
        if pvc is None or not pvc.spec.volume_name:
            return
        want = pvc.spec.requests.get(res.STORAGE, 0)
        pv = self.store.get("persistentvolumes", "",
                            pvc.spec.volume_name) or \
            self.store.get("persistentvolumes", "default",
                           pvc.spec.volume_name)
        if pv is None:
            return
        have = pvc.status.capacity.get(res.STORAGE)
        if have is None:
            # first observation of a bound claim (or a replace wiped
            # status): the granted baseline is what the PV actually
            # provides — stamping spec.requests here would silently
            # complete an expansion that never ran
            pv_cap = pv.spec.capacity.get(res.STORAGE, want)
            if pv_cap >= want and claim_in_use(self.store, pvc):
                # a wiped status can't tell GRANTED from OWED when the
                # PV already holds the new size mid-online-expand: have
                # the node confirm (finish_resize is idempotent) rather
                # than fake completion
                _cond_set(pvc, FS_RESIZE_PENDING)
                pvc.status.phase = "Bound"
                try:
                    self.store.update("persistentvolumeclaims", pvc)
                except (Conflict, KeyError):
                    pass
                return
            have = min(want, pv_cap)
            pvc.status.capacity[res.STORAGE] = have
            pvc.status.phase = "Bound"
            try:
                self.store.update("persistentvolumeclaims", pvc)
            except (Conflict, KeyError):
                return
            # fall through: a growth observed in the same sync proceeds
        if want <= have:
            return
        # controller-side phase is visible on the claim while it runs
        # (expand_controller MarkAsResizing)
        _cond_set(pvc, RESIZING)
        try:
            self.store.update("persistentvolumeclaims", pvc)
        except (Conflict, KeyError):
            return
        # controller-side expand: grow the PV capacity
        # (sync_volume_resize.go ExpandVolume -> UpdatePVSize)
        if pv.spec.capacity.get(res.STORAGE, 0) < want:
            pv.spec.capacity[res.STORAGE] = want
            try:
                self.store.update("persistentvolumes", pv)
            except (Conflict, KeyError):
                return
        if claim_in_use(self.store, pvc):
            # node-side filesystem resize still owed: the claim's
            # kubelet finishes it (MarkForFSResize)
            _cond_set(pvc, FS_RESIZE_PENDING)
            _cond_clear(pvc, RESIZING)
            try:
                self.store.update("persistentvolumeclaims", pvc)
            except (Conflict, KeyError):
                pass
        else:
            # offline expand completes immediately
            finish_resize(self.store, pvc)
