"""Graph-based owner-reference garbage collector.

Reference: pkg/controller/garbagecollector/ — the GraphBuilder watches
every monitored resource and maintains a uid-keyed dependency graph
(graph_builder.go:204 syncMonitors, :560 processGraphChanges); the
collector pops dependents whose owners are gone and deletes them
(garbagecollector.go:404 attemptToDeleteItem), classifying each owner
reference as solid (owner exists with the SAME uid) or dangling
(absent, or a same-named object with a different uid — a recreated
owner must NOT readopt the old dependents).

Mechanics mirrored here:

  * monitors over every registered kind feed add/update/delete into the
    graph; owners referenced before they are observed become VIRTUAL
    nodes that an attempt pass verifies against the store
    (graph_builder.go attemptToDelete enqueue of virtual nodes).
  * deleting an owner enqueues its dependents; each dependent with no
    remaining solid owner is deleted, whose delete event enqueues ITS
    dependents — background cascading deletion through the graph.
  * a dependent with a mix of solid and dangling refs is patched to
    drop only the dangling refs (attemptToDeleteItem's
    "delete owner references" branch).
  * orphaning: this API model has no DeleteOptions, so the reference's
    propagationPolicy=Orphan / "orphan" finalizer flow
    (garbagecollector.go attemptToOrphan) is carried by the
    ORPHAN_ANNOTATION on the owner: when such an owner is deleted, its
    dependents have the owner's references stripped instead of being
    collected.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

from ..api import scheme
from ..api import types as api
from ..runtime.store import ADDED, DELETED, MODIFIED, Event
from .base import Controller

ORPHAN_ANNOTATION = "kubernetes.io/orphan-dependents"

# kinds not worth monitoring: high-churn, never owner-linked
_SKIP_PLURALS = {"events", "podmetrics", "leases"}


@dataclass
class _Node:
    """graph_builder.go `node`: one object (or virtual owner) by uid."""

    uid: str
    plural: str
    namespace: str
    name: str
    owners: List[api.OwnerReference] = field(default_factory=list)
    dependents: Set[str] = field(default_factory=set)
    virtual: bool = False
    orphan: bool = False  # last-observed orphan intent
    # identity keys this node is filed under in _ident_deps (its own
    # uid-less owner references)
    ident_refs: Set[tuple] = field(default_factory=set)


class GarbageCollector(Controller):
    name = "garbagecollector"

    def __init__(self, store):
        super().__init__(store)
        self._glock = threading.Lock()
        self._nodes: Dict[str, _Node] = {}
        self.deleted_total = 0
        self._monitored: Set[str] = set()
        # dependents linked to an owner by (plural, namespace, name)
        # because their reference carries no uid — resolved by identity
        self._ident_deps: Dict[tuple, Set[str]] = {}
        self.sync_monitors()

    # -- monitors (graph_builder.go:204 syncMonitors) --------------------------

    def sync_monitors(self):
        """Monitor every currently-registered kind; called again from
        resync() so CRD-defined kinds gain monitors after registration."""
        for kind in scheme.all_kinds():
            plural = scheme.plural_for_kind(kind)
            if plural in self._monitored or plural in _SKIP_PLURALS:
                continue
            self._monitored.add(plural)
            # raw watch + initial list, NOT a SharedInformer: the graph
            # is the cache; an informer would duplicate every object of
            # every kind into per-kind maps the GC never reads
            self.store.watch(plural, self._on_event)
            for obj in self.store.list(plural):
                self._on_event(Event(ADDED, plural, obj))

    def resync(self):
        self.sync_monitors()

    def _on_event(self, ev: Event):
        if ev.type == DELETED:
            self._observe_delete(ev.kind, ev.obj)
        elif ev.type in (ADDED, MODIFIED):
            self._observe(ev.kind, ev.obj)

    # -- graph maintenance (processGraphChanges) -------------------------------

    def _plural_for(self, kind: str) -> Optional[str]:
        try:
            return scheme.plural_for_kind(kind)
        except KeyError:
            return None

    @staticmethod
    def _safe_namespaced(plural_or_kind: str, by_plural: bool) -> bool:
        """is_namespaced that tolerates unregistered kinds (a CRD may be
        deleted while its leftover instances still emit events)."""
        try:
            kind = (scheme.kind_for_plural(plural_or_kind)
                    if by_plural else plural_or_kind)
            if not kind:
                return True
            return scheme.is_namespaced(kind)
        except KeyError:
            return True


    def _observe(self, plural: str, obj):
        uid = obj.metadata.uid
        verify: List[str] = []
        with self._glock:
            n = self._nodes.get(uid)
            if n is None:
                n = _Node(uid=uid, plural=plural,
                          namespace=obj.metadata.namespace,
                          name=obj.metadata.name)
                self._nodes[uid] = n
            n.plural, n.namespace, n.name = (plural, obj.metadata.namespace,
                                             obj.metadata.name)
            n.virtual = False
            n.orphan = (obj.metadata.annotations or {}).get(
                ORPHAN_ANNOTATION) == "true"
            old_uids = {r.uid for r in n.owners if r.uid}
            n.owners = list(obj.metadata.owner_references or [])
            new_uids = set()
            new_idents = set()
            for ref in n.owners:
                if not ref.uid:
                    # uid-less reference: link by identity so the owner's
                    # eventual delete still enqueues this dependent;
                    # cluster-scoped owners file under "" so the delete
                    # event's lookup matches whatever namespace the
                    # dependent lives in
                    ref_ns = (obj.metadata.namespace
                              if self._safe_namespaced(ref.kind, False)
                              else "")
                    key = (self._plural_for(ref.kind) or ref.kind,
                           ref_ns, ref.name)
                    new_idents.add(key)
                    self._ident_deps.setdefault(key, set()).add(uid)
                    continue
                new_uids.add(ref.uid)
                on = self._nodes.get(ref.uid)
                if on is None:
                    # owner not yet observed: virtual node, verified
                    # against the store by the attempt pass
                    on = _Node(uid=ref.uid,
                               plural=self._plural_for(ref.kind) or "",
                               namespace=obj.metadata.namespace,
                               name=ref.name, virtual=True)
                    self._nodes[ref.uid] = on
                    verify.append(ref.uid)
                on.dependents.add(uid)
            for gone in sorted(old_uids - new_uids):
                o = self._nodes.get(gone)
                if o is not None:
                    o.dependents.discard(uid)
            for key in sorted(n.ident_refs - new_idents):
                deps = self._ident_deps.get(key)
                if deps is not None:
                    deps.discard(uid)
                    if not deps:
                        del self._ident_deps[key]
            n.ident_refs = new_idents
        for vuid in verify:
            self.queue.add(f"attempt:{vuid}")
        if obj.metadata.owner_references:
            self.queue.add(f"attempt:{uid}")

    def _observe_delete(self, plural: str, obj):
        uid = obj.metadata.uid
        with self._glock:
            n = self._nodes.pop(uid, None)
            deps = set(n.dependents) if n else set()
            orphan = n.orphan if n else False
            if n:
                for ref in n.owners:
                    if ref.uid and ref.uid in self._nodes:
                        self._nodes[ref.uid].dependents.discard(uid)
                for key in n.ident_refs:
                    d = self._ident_deps.get(key)
                    if d is not None:
                        d.discard(uid)
                        if not d:
                            del self._ident_deps[key]
            # dependents that referenced this owner by bare identity:
            # kept registered (a recreated same-name owner satisfies a
            # uid-less ref), just re-verified now. Cluster-scoped kinds
            # are filed (and looked up) under "" regardless of the
            # namespace strings either object carries.
            owner_ns = (obj.metadata.namespace
                        if self._safe_namespaced(plural, True) else "")
            deps |= self._ident_deps.get(
                (plural, owner_ns, obj.metadata.name), set())
        for dep in sorted(deps):
            self.queue.add(f"orphan:{dep}:{uid}" if orphan
                           else f"attempt:{dep}")

    # -- collection (attemptToDeleteItem) --------------------------------------

    def _lookup(self, plural: str, namespace: str, name: str):
        obj = self.store.get(plural, namespace, name)
        if obj is None:
            kind = scheme.kind_for_plural(plural)
            if kind is not None and not scheme.is_namespaced(kind):
                obj = self.store.get(plural, "", name) or \
                    self.store.get(plural, "default", name)
        return obj

    def _owner_alive(self, namespace: str, ref: api.OwnerReference) -> bool:
        """Solid owner: exists AND (when both sides carry uids) is the
        same incarnation — a recreated same-name owner is dangling."""
        plural = self._plural_for(ref.kind)
        if plural is None:
            return True  # unmonitorable kind: never collect on its account
        obj = self._lookup(plural, namespace, ref.name)
        if obj is None:
            return False
        return not ref.uid or not obj.metadata.uid or \
            ref.uid == obj.metadata.uid

    def sync(self, key: str):
        verb, _, rest = key.partition(":")
        if verb == "orphan":
            dep_uid, _, owner_uid = rest.partition(":")
            self._orphan_dependent(dep_uid, owner_uid)
            return
        uid = rest
        with self._glock:
            n = self._nodes.get(uid)
            info = (n.plural, n.namespace, n.name, n.virtual) if n else None
        if info is None:
            return
        plural, namespace, name, virtual = info
        if virtual:
            obj = self._lookup(plural, namespace, name) if plural else None
            if obj is not None and obj.metadata.uid == uid:
                # observed late through a different monitor ordering; the
                # informer's own event fills the rest
                with self._glock:
                    if uid in self._nodes:
                        self._nodes[uid].virtual = False
                return
            # the owner never existed (or is a different incarnation):
            # release the virtual node and collect its dependents
            with self._glock:
                n = self._nodes.pop(uid, None)
                deps = sorted(n.dependents) if n else []
            for dep in deps:
                self.queue.add(f"attempt:{dep}")
            return
        obj = self._lookup(plural, namespace, name)
        if obj is None or obj.metadata.uid != uid:
            return  # delete event will prune the graph
        refs = list(obj.metadata.owner_references or [])
        if not refs:
            return
        solid = [r for r in refs
                 if self._owner_alive(obj.metadata.namespace, r)]
        if solid and len(solid) < len(refs):
            # drop only the dangling references (attemptToDeleteItem's
            # patch branch); the object survives on its solid owners
            obj.metadata.owner_references = solid
            self.store.update(plural, obj)
            return
        if not solid:
            try:
                self.store.delete(plural, obj.metadata.namespace,
                                  obj.metadata.name)
                self.deleted_total += 1
            except KeyError:
                pass

    def _orphan_dependent(self, dep_uid: str, owner_uid: str):
        with self._glock:
            n = self._nodes.get(dep_uid)
            info = (n.plural, n.namespace, n.name) if n else None
        if info is None:
            return
        obj = self._lookup(*info)
        if obj is None or obj.metadata.uid != dep_uid:
            return
        kept = [r for r in obj.metadata.owner_references
                if r.uid != owner_uid]
        if len(kept) != len(obj.metadata.owner_references):
            obj.metadata.owner_references = kept
            self.store.update(info[0], obj)

    # -- drive ----------------------------------------------------------------

    def sweep(self) -> int:
        """Drain the attempt queue (cascades re-fill it mid-drain);
        returns objects deleted by this call. The ControllerManager's
        periodic sweeper and tests drive collection through here."""
        before = self.deleted_total
        while self.sync_all():
            pass
        return self.deleted_total - before
