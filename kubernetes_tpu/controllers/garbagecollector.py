"""Owner-reference garbage collector.

Reference: pkg/controller/garbagecollector/garbagecollector.go — the
graph builder watches all kinds, and dependents whose controller owner
is gone are deleted (cascading background deletion; attemptToDelete).
Reduced here to the same invariant without the full uid graph: any
object carrying a controller ownerReference to a non-existent owner is
collected on each sweep.
"""

from __future__ import annotations

from ..api import scheme
from .base import Controller

_KIND_TO_PLURAL = {
    "ReplicaSet": "replicasets", "ReplicationController": "replicationcontrollers",
    "StatefulSet": "statefulsets", "Deployment": "deployments",
    "DaemonSet": "daemonsets", "Job": "jobs", "CronJob": "cronjobs",
    "Service": "services", "Node": "nodes", "Pod": "pods",
}

# dependents worth sweeping (objects that commonly carry owner refs)
_DEPENDENT_KINDS = ["pods", "replicasets", "jobs", "endpoints"]


class GarbageCollector(Controller):
    name = "garbagecollector"

    def sync(self, key: str):
        self.sweep()

    def _owner_exists(self, ns: str, ref) -> bool:
        plural = _KIND_TO_PLURAL.get(ref.kind)
        if plural is None:
            return True  # unknown kind: never collect
        obj = self.store.get(plural, ns, ref.name)
        if obj is None and not scheme.is_namespaced(ref.kind):
            obj = self.store.get(plural, "", ref.name) or \
                self.store.get(plural, "default", ref.name)
        if obj is None:
            return False
        # uid mismatch = recreated owner; the old dependents are orphans
        return not ref.uid or not obj.metadata.uid or ref.uid == obj.metadata.uid

    def sweep(self) -> int:
        deleted = 0
        for kind in _DEPENDENT_KINDS:
            for obj in self.store.list(kind):
                refs = [r for r in obj.metadata.owner_references if r.controller]
                if not refs:
                    continue
                if all(self._owner_exists(obj.metadata.namespace, r)
                       for r in refs):
                    continue
                try:
                    self.store.delete(kind, obj.metadata.namespace,
                                      obj.metadata.name)
                    deleted += 1
                except KeyError:
                    pass
        return deleted
