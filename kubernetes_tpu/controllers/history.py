"""ControllerRevision history management.

The shared bookkeeping DaemonSet and StatefulSet use for rollout
history: each distinct pod template gets an immutable, numbered
ControllerRevision owned by the workload; `kubectl rollout
history/undo` reads them back. Reference:
pkg/controller/history/controller_history.go (NewControllerRevision:
149, ControllerRevisionName:55, FindEqualRevisions:117,
truncateHistory in daemon/update.go:341 and
stateful_set_control.go:264).
"""

from __future__ import annotations

from typing import List, Optional

from ..api import scheme
from ..api import types as api
from ..runtime.store import Conflict

# apps DefaultDaemonSetUniqueLabelKey / StatefulSetRevisionLabel: the
# one label tying pods to the ControllerRevision they were built from
REV_LABEL = "controller-revision-hash"


def revision_data(template) -> dict:
    """Wire-form snapshot of a pod template, shaped like the reference's
    raw patch payload (history.go getPatch: {"spec":{"template":...}})
    so undo can splice it straight back into a workload spec."""
    enc = scheme.encode(template)
    enc.get("metadata", {}).pop("uid", None)
    return {"spec": {"template": enc}}


def revision_hash(data: dict) -> str:
    """Stable content hash naming the revision (HashControllerRevision
    analog — the reference hashes the serialized revision data)."""
    return scheme.stable_hash(data, 10)


def new_revision(owner, owner_kind: str, data: dict,
                 revision: int) -> api.ControllerRevision:
    """NewControllerRevision (controller_history.go:149): named
    <owner>-<hash>, labeled with the owner's selector labels plus the
    revision hash, owned by the workload."""
    h = revision_hash(data)
    labels = dict((owner.spec.selector.match_labels or {})
                  if owner.spec.selector else {})
    labels[REV_LABEL] = h
    return api.ControllerRevision(
        metadata=api.ObjectMeta(
            name=f"{owner.metadata.name}-{h}",
            namespace=owner.metadata.namespace,
            labels=labels,
            owner_references=[api.OwnerReference(
                kind=owner_kind, name=owner.metadata.name,
                uid=owner.metadata.uid, controller=True)]),
        data=data,
        revision=revision)


def list_revisions(store, owner, owner_kind: str) -> List[api.ControllerRevision]:
    """ListControllerRevisions: every revision controller-owned by this
    workload (uid-matched — a recreated same-name owner does not adopt
    its predecessor's history), sorted by revision number."""
    out = []
    for rev in store.list("controllerrevisions", owner.metadata.namespace):
        if any(r.controller and r.uid == owner.metadata.uid
               for r in rev.metadata.owner_references):
            out.append(rev)
    out.sort(key=lambda r: (r.revision, r.metadata.name))
    return out


def sync_revision(store, owner, owner_kind: str,
                  template,
                  revisions: Optional[List] = None) -> api.ControllerRevision:
    """Find-or-create the revision for the workload's CURRENT template
    (constructHistory in daemon/update.go:152 / getStatefulSetRevisions
    in stateful_set_control.go:315): an existing revision with equal
    data is bumped to the head revision number if it fell behind
    (rollback reuses the old snapshot); otherwise a fresh revision is
    created at max+1. Pass `revisions` (from list_revisions) to reuse a
    scan the caller already paid for."""
    data = revision_data(template)
    if revisions is None:
        revisions = list_revisions(store, owner, owner_kind)
    head = revisions[-1].revision if revisions else 0
    equal = [r for r in revisions if r.data == data]
    if equal:
        cur = equal[-1]
        if cur.revision != head or len(equal) > 1:
            # dedupCurHistories: collapse duplicates, advance the kept
            # one so history/undo ordering stays truthful
            for dup in equal[:-1]:
                try:
                    store.delete("controllerrevisions",
                                 dup.metadata.namespace, dup.metadata.name)
                except KeyError:
                    pass
            if cur.revision != head:
                cur.revision = head + 1
                try:
                    store.update("controllerrevisions", cur)
                except (Conflict, KeyError):
                    pass
        return cur
    rev = new_revision(owner, owner_kind, data, head + 1)
    base = rev.metadata.name
    for collision in range(8):
        try:
            store.create("controllerrevisions", rev)
            return rev
        except Conflict:
            existing = store.get("controllerrevisions",
                                 rev.metadata.namespace, rev.metadata.name)
            if existing is not None and any(
                    r.controller and r.uid == owner.metadata.uid
                    for r in existing.metadata.owner_references):
                return existing
            # name held by a FOREIGN owner (e.g. a deleted same-name
            # workload not yet GC'd): never adopt — probe with a
            # collision count like the reference's CreateControllerRevision
            rev.metadata.name = f"{base}-{collision + 1}"
    raise Conflict(f"controllerrevision name space exhausted for {base}")


def truncate_history(store, owner, owner_kind: str,
                     live_hashes: Optional[set] = None,
                     keep_names: Optional[set] = None,
                     revisions: Optional[List] = None) -> int:
    """Delete the oldest non-live revisions beyond
    spec.revisionHistoryLimit (truncateHistory). A revision is live if
    any current pod still carries its hash label, or it is one of the
    current/update revisions (`keep_names`) — live revisions are never
    reaped regardless of age, even at revisionHistoryLimit=0. Pass
    `revisions` to reuse the caller's list_revisions scan."""
    limit = getattr(owner.spec, "revision_history_limit", 10)
    if revisions is None:
        revisions = list_revisions(store, owner, owner_kind)
    live = live_hashes or set()
    keep = keep_names or set()
    candidates = [
        r for r in revisions
        if (r.metadata.labels or {}).get(REV_LABEL)
        not in live and r.metadata.name not in keep]
    excess = len(candidates) - max(0, limit)
    deleted = 0
    for r in candidates[:max(0, excess)]:
        try:
            store.delete("controllerrevisions", r.metadata.namespace,
                         r.metadata.name)
            deleted += 1
        except KeyError:
            pass
    return deleted
