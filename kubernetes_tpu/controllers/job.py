"""Job: run pods to completion.

Reference: pkg/controller/job/job_controller.go (syncJob:436 —
active/succeeded/failed accounting, parallelism-bounded pod creation,
backoffLimit failure condition, Complete condition when succeeded >=
completions).
"""

from __future__ import annotations

import itertools

from ..api import types as api
from ..runtime.store import Conflict
from .base import Controller, make_pod_from_template

_suffix = itertools.count(1)


class JobController(Controller):
    name = "job"

    def __init__(self, store, clock=None):
        super().__init__(store)
        import time
        self.clock = clock or time.time
        self.informer("jobs")
        self.informer("pods",
                      on_add=self._pod_event,
                      on_update=lambda o, n: self._pod_event(n),
                      on_delete=self._pod_event)

    def _pod_event(self, pod):
        for ref in pod.metadata.owner_references:
            if ref.controller and ref.kind == "Job":
                self.queue.add(f"{pod.metadata.namespace}/{ref.name}")

    def sync(self, key: str):
        ns, name = key.split("/", 1)
        job = self.store.get("jobs", ns, name)
        if job is None:
            return
        if any(c[0] in ("Complete", "Failed") and str(c[1]).startswith("True")
               for c in job.status.conditions):
            return  # terminal
        owned = [p for p in self.store.list("pods", ns)
                 if any(r.controller and r.kind == "Job" and r.name == name
                        for r in p.metadata.owner_references)]
        active = [p for p in owned if p.status.phase in
                  ("", "Pending", "Running")
                  and p.metadata.deletion_timestamp is None]
        succeeded = sum(1 for p in owned if p.status.phase == "Succeeded")
        failed = sum(1 for p in owned if p.status.phase == "Failed")
        st = job.status
        changed = (st.active, st.succeeded, st.failed) != \
            (len(active), succeeded, failed)
        st.active, st.succeeded, st.failed = len(active), succeeded, failed
        if st.start_time is None:
            st.start_time = self.clock()
            changed = True
        # job_controller.go pastActiveDeadline: a wall-clock bound on
        # the whole job, failure reason DeadlineExceeded
        if job.spec.active_deadline_seconds is not None:
            remaining = (st.start_time + job.spec.active_deadline_seconds
                         - self.clock())
            if remaining <= 0:
                st.conditions = [("Failed", "True:DeadlineExceeded")]
                for p in active:
                    self._delete(p)
                st.active = 0
                self._update(job)
                return
            # re-enqueue at the deadline (job_controller.go AddAfter):
            # nothing else wakes the sync when the clock runs out
            self.queue.add_after(key, remaining)
        if failed > job.spec.backoff_limit:
            st.conditions = [("Failed", "True:BackoffLimitExceeded")]
            for p in active:
                self._delete(p)
            st.active = 0
            self._update(job)
            return
        if succeeded >= job.spec.completions:
            st.conditions = [("Complete", "True")]
            st.completion_time = self.clock()
            for p in active:
                self._delete(p)
            st.active = 0
            self._update(job)
            return
        # create up to parallelism, bounded by remaining completions
        remaining = job.spec.completions - succeeded
        want_active = min(job.spec.parallelism, remaining)
        for _ in range(want_active - len(active)):
            pod = make_pod_from_template(job.spec.template, "Job", job,
                                         f"{name}-{next(_suffix):05d}")
            pod.spec.restart_policy = "Never"
            try:
                self.store.create("pods", pod)
                st.active += 1
                changed = True
            except Conflict:
                pass
        for p in active[want_active:] if want_active < len(active) else []:
            self._delete(p)
        if changed:
            self._update(job)

    def _delete(self, pod):
        try:
            self.store.delete("pods", pod.metadata.namespace, pod.metadata.name)
        except KeyError:
            pass

    def _update(self, job):
        try:
            self.store.update("jobs", job)
        except (Conflict, KeyError):
            pass
