"""Controller manager: wire and run the full controller roster.

Reference: cmd/kube-controller-manager/app/controllermanager.go
(StartControllers:346 — instantiate every enabled controller against the
shared informer factory, start each with its worker count, optionally
behind leader election).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from ..client.leaderelection import LeaderElector
from .base import Controller
from .cronjob import CronJobController
from .daemonset import DaemonSetController
from .deployment import DeploymentController
from .disruption import DisruptionController
from .endpoints import EndpointsController
from .garbagecollector import GarbageCollector
from .job import JobController
from .namespace import NamespaceController
from .nodelifecycle import NodeLifecycleController
from .podgc import PodGCController
from .replicaset import ReplicaSetController, ReplicationControllerController
from .resourcequota import ResourceQuotaController
from .serviceaccount import ServiceAccountController
from .attachdetach import AttachDetachController
from .certificates import CSRApprovingController, CSRSigningController
from .podautoscaler import HorizontalPodAutoscalerController
from .statefulset import StatefulSetController
from .ttl import TTLController
from .expand import ExpandController
from .volumebinding import PersistentVolumeController
from .bootstrap import BootstrapSignerController, TokenCleanerController
from .clusterroleaggregation import ClusterRoleAggregationController
from .storageprotection import (PVCProtectionController,
                                PVProtectionController)

DEFAULT_CONTROLLERS = [
    ReplicaSetController, ReplicationControllerController,
    DeploymentController, StatefulSetController, DaemonSetController,
    JobController, CronJobController, EndpointsController,
    NodeLifecycleController, DisruptionController, NamespaceController,
    PodGCController, GarbageCollector, ResourceQuotaController,
    ServiceAccountController, PersistentVolumeController,
    AttachDetachController, HorizontalPodAutoscalerController,
    TTLController, CSRApprovingController, CSRSigningController,
    BootstrapSignerController, TokenCleanerController,
    ClusterRoleAggregationController, PVCProtectionController,
    PVProtectionController, ExpandController,
]


def default_controllers() -> List[type]:
    """DEFAULT_CONTROLLERS + server-side loops whose modules import the
    controller base (lazy to break the package import cycle)."""
    from ..server.aggregator import APIServiceAvailabilityController

    return DEFAULT_CONTROLLERS + [APIServiceAvailabilityController]


class ControllerManager:
    def __init__(self, store, controllers: Optional[List[type]] = None,
                 identity: str = "controller-manager",
                 leader_elect: bool = False, cloud=None,
                 cluster_cidr: str = "", metrics_scraper: bool = False,
                 kubelet_client_ctx=None, scheduler=None,
                 node_eviction_rate: Optional[float] = None,
                 secondary_node_eviction_rate: Optional[float] = None,
                 large_cluster_size_threshold: Optional[int] = None,
                 unhealthy_zone_threshold: Optional[float] = None):
        self.store = store
        self.controllers: Dict[str, Controller] = {}
        for cls in (controllers if controllers is not None
                    else default_controllers()):
            c = cls(store)
            self.controllers[c.name] = c
        # eviction storm-control knobs (kube-controller-manager
        # --node-eviction-rate / --secondary-node-eviction-rate /
        # --large-cluster-size-threshold / --unhealthy-zone-threshold)
        nlc = self.controllers.get("nodelifecycle")
        if nlc is not None and hasattr(nlc, "configure"):
            nlc.configure(
                eviction_rate_qps=node_eviction_rate,
                secondary_eviction_rate_qps=secondary_node_eviction_rate,
                large_cluster_threshold=large_cluster_size_threshold,
                unhealthy_zone_threshold=unhealthy_zone_threshold)
        if metrics_scraper:
            # the metrics-server runs OUTSIDE kube-controller-manager in
            # the reference (a separate deployment scraping
            # /stats/summary); opt-in here so embedded clusters can get
            # the full kubelet-stats -> PodMetrics -> HPA/top pipeline
            # from one constructor. TLS kubelets need the apiserver's
            # kubelet-client credential as kubelet_client_ctx.
            from .metricsserver import MetricsServerController
            c = MetricsServerController(store,
                                        ssl_context=kubelet_client_ctx)
            self.controllers[c.name] = c
        # cloud-dependent loops start only when a provider is configured
        # (controllermanager.go gates these on --cloud-provider)
        if cluster_cidr:
            from .nodeipam import NodeIpamController
            c = NodeIpamController(store, cluster_cidr)
            self.controllers[c.name] = c
        if cloud is not None:
            from .cloud_node import CloudNodeController
            from .route import RouteController
            from .service_lb import ServiceLBController
            for c in (ServiceLBController(store, cloud),
                      CloudNodeController(store, cloud)):
                self.controllers[c.name] = c
            if cloud.routes() is not None:
                c = RouteController(store, cloud)
                self.controllers[c.name] = c
            # the cluster autoscaler needs BOTH a sizable cloud (node
            # groups) and the scheduler's simulation hooks — it runs off
            # the live snapshot/queue, so a bare store isn't enough
            # (the reference ships it as a separate binary for the same
            # reason: it is a scheduler-shaped consumer of cluster state)
            if scheduler is not None and cloud.node_groups() is not None:
                from .clusterautoscaler import ClusterAutoscaler
                c = ClusterAutoscaler(store, cloud, scheduler)
                self.controllers[c.name] = c
        self.elector = LeaderElector(
            store, identity, lock_name="kube-controller-manager",
            on_started_leading=self._start_all) if leader_elect else None
        self._gc_timer: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def __getitem__(self, name: str) -> Controller:
        return self.controllers[name]

    # -- synchronous drive (tests / deterministic mode) ------------------------

    def sync_all(self, rounds: int = 3) -> int:
        """Drain every controller queue repeatedly (controllers feed each
        other: deployment -> replicaset -> pods -> endpoints...)."""
        n = 0
        for _ in range(rounds):
            for c in self.controllers.values():
                n += c.sync_all()
            gc = self.controllers.get("garbagecollector")
            if gc is not None:
                gc.sweep()
            podgc = self.controllers.get("podgc")
            if podgc is not None:
                podgc.gc()
            time.sleep(0.02)  # let rate-limited requeues land for next round
        return n

    # -- background mode -------------------------------------------------------

    def start(self, workers: int = 2, sweep_period: float = 20.0):
        if self.elector is not None:
            self.elector.start()
        else:
            self._start_all(workers=workers, sweep_period=sweep_period)
        return self

    def _start_all(self, workers: int = 2, sweep_period: float = 20.0):
        for c in self.controllers.values():
            c.run(workers)

        def sweeper():
            while not self._stop.is_set():
                gc = self.controllers.get("garbagecollector")
                if gc is not None:
                    gc.sweep()
                podgc = self.controllers.get("podgc")
                if podgc is not None:
                    podgc.gc()
                self._stop.wait(sweep_period)

        self._gc_timer = threading.Thread(target=sweeper, daemon=True,
                                          name="gc-sweeper")
        self._gc_timer.start()

    def stop(self):
        self._stop.set()
        if self.elector is not None:
            self.elector.stop()
        for c in self.controllers.values():
            c.stop()
