"""Metrics server: kubelet /stats/summary -> PodMetrics API objects.

Reference: the metrics pipeline the 1.11 tree consumes — kubelets
aggregate cgroup stats into the Summary API (pkg/kubelet/server/stats/
summary.go, apis/stats/v1alpha1) and the out-of-tree metrics-server
scrapes every node's /stats/summary, publishing PodMetrics under
metrics.k8s.io for the HPA's REST metrics client
(pkg/controller/podautoscaler/metrics/) and kubectl top. This
controller is that scraper: per node key, GET the kubelet's summary
and upsert one PodMetrics per pod (usage: cpu millicores, memory
bytes — the units podautoscaler.py and cli/kubectl.py cmd_top read).

Nodes without a published daemon endpoint (no kubelet server) are
skipped. TLS clusters pass the scraper a client SSL context holding
the apiserver's kubelet-client identity, the same credential the
apiserver's exec/log proxy presents.
"""

from __future__ import annotations

import json
import urllib.request

from ..api import resources as res
from ..api import types as api
from .base import Controller


class MetricsServerController(Controller):
    name = "metrics-server"

    def __init__(self, store, ssl_context=None, timeout: float = 5.0):
        super().__init__(store)
        self.ssl_context = ssl_context
        self.timeout = timeout
        self.informer("nodes")
        # metrics follow their pod's lifetime: a deleted pod's
        # PodMetrics goes with it (the GC skips podmetrics — they have
        # no ownerReferences — so this controller owns the cleanup).
        # Event handlers only enqueue; the mutation happens in sync()
        # like every other controller (no store writes during dispatch).
        self.informer("pods", on_add=lambda o: None,
                      on_update=lambda o, n: None,
                      on_delete=lambda p: self.enqueue(
                          f"pod-deleted:{p.metadata.namespace}"
                          f"/{p.metadata.name}"))

    def resync(self):
        for node in self.store.list("nodes"):
            self.enqueue(node)
        # one GLOBAL orphan sweep per resync period (not per node):
        # metrics whose pod vanished while this controller wasn't
        # watching (restart, missed event) go through the same
        # pod-deleted sync path the informer uses
        for pm in self.store.list("podmetrics"):
            ns, pm_name = pm.metadata.namespace, pm.metadata.name
            if self.store.get("pods", ns, pm_name) is None:
                self.enqueue(f"pod-deleted:{ns}/{pm_name}")

    def _scrape(self, host: str, port: int) -> dict:
        scheme_ = "https" if self.ssl_context is not None else "http"
        url = f"{scheme_}://{host}:{port}/stats/summary"
        with urllib.request.urlopen(url, timeout=self.timeout,
                                    context=self.ssl_context) as resp:
            return json.loads(resp.read())

    def sync(self, key: str):
        if key.startswith("pod-deleted:"):
            ns, pod_name = key[len("pod-deleted:"):].split("/", 1)
            if self.store.get("podmetrics", ns, pod_name) is not None:
                self.store.delete("podmetrics", ns, pod_name)
            return
        from ..utils.net import node_daemon_endpoint

        _, name = key.split("/", 1)
        ep = node_daemon_endpoint(self.store, name)
        if ep is None:
            return
        summary = self._scrape(*ep)
        scraped = set()
        for pod_doc in summary.get("pods", []):
            ref = pod_doc.get("podRef", {})
            ns, pod_name = ref.get("namespace", "default"), ref.get("name")
            if not pod_name:
                continue
            scraped.add((ns, pod_name))
            usage = {
                res.CPU: int(pod_doc.get("cpu", {})
                             .get("usageNanoCores", 0)) // 1_000_000,
                res.MEMORY: int(pod_doc.get("memory", {})
                                .get("workingSetBytes", 0)),
            }
            cur = self.store.get("podmetrics", ns, pod_name)
            if cur is None:
                self.store.create("podmetrics", api.PodMetrics(
                    metadata=api.ObjectMeta(name=pod_name, namespace=ns),
                    usage=usage))
            elif cur.usage != usage:
                cur.usage = usage
                self.store.update("podmetrics", cur)
        # No per-node stale sweep: the summary reports EVERY pod bound
        # to the node (stopped containers scrape as zero usage), so
        # `scraped` covers this node's pods; deleted pods are cleaned by
        # the pod-delete informer and the resync orphan sweep. Scanning
        # cluster-wide podmetrics here would cost O(nodes x podmetrics)
        # store reads per resync round at kubemark scale.
        del scraped
