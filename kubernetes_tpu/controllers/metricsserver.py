"""Metrics server: kubelet /stats/summary -> PodMetrics API objects.

Reference: the metrics pipeline the 1.11 tree consumes — kubelets
aggregate cgroup stats into the Summary API (pkg/kubelet/server/stats/
summary.go, apis/stats/v1alpha1) and the out-of-tree metrics-server
scrapes every node's /stats/summary, publishing PodMetrics under
metrics.k8s.io for the HPA's REST metrics client
(pkg/controller/podautoscaler/metrics/) and kubectl top. This
controller is that scraper: per node key, GET the kubelet's summary
and upsert one PodMetrics per pod (usage: cpu millicores, memory
bytes — the units podautoscaler.py and cli/kubectl.py cmd_top read).

Nodes without a published daemon endpoint (no kubelet server) are
skipped. TLS clusters pass the scraper a client SSL context holding
the apiserver's kubelet-client identity, the same credential the
apiserver's exec/log proxy presents.
"""

from __future__ import annotations

import json
import urllib.request

from ..api import resources as res
from ..api import types as api
from .base import Controller


class MetricsServerController(Controller):
    name = "metrics-server"

    def __init__(self, store, ssl_context=None, timeout: float = 5.0):
        super().__init__(store)
        self.ssl_context = ssl_context
        self.timeout = timeout
        self.informer("nodes")
        # metrics follow their pod's lifetime: a deleted pod's
        # PodMetrics goes with it (the GC skips podmetrics — they have
        # no ownerReferences — so this controller owns the cleanup).
        # Event handlers only enqueue; the mutation happens in sync()
        # like every other controller (no store writes during dispatch).
        self.informer("pods", on_add=lambda o: None,
                      on_update=lambda o, n: None,
                      on_delete=lambda p: self.enqueue(
                          f"pod-deleted:{p.metadata.namespace}"
                          f"/{p.metadata.name}"))

    def resync(self):
        for node in self.store.list("nodes"):
            self.enqueue(node)

    def _scrape(self, host: str, port: int) -> dict:
        scheme_ = "https" if self.ssl_context is not None else "http"
        url = f"{scheme_}://{host}:{port}/stats/summary"
        with urllib.request.urlopen(url, timeout=self.timeout,
                                    context=self.ssl_context) as resp:
            return json.loads(resp.read())

    def sync(self, key: str):
        if key.startswith("pod-deleted:"):
            ns, pod_name = key[len("pod-deleted:"):].split("/", 1)
            if self.store.get("podmetrics", ns, pod_name) is not None:
                self.store.delete("podmetrics", ns, pod_name)
            return
        _, name = key.split("/", 1)
        node = (self.store.get("nodes", "default", name)
                or self.store.get("nodes", "", name))
        if node is None or not node.status.kubelet_port:
            return
        host = next((a.address for a in node.status.addresses if a.address),
                    "127.0.0.1")
        summary = self._scrape(host, node.status.kubelet_port)
        scraped = set()
        for pod_doc in summary.get("pods", []):
            ref = pod_doc.get("podRef", {})
            ns, pod_name = ref.get("namespace", "default"), ref.get("name")
            if not pod_name:
                continue
            scraped.add((ns, pod_name))
            usage = {
                res.CPU: int(pod_doc.get("cpu", {})
                             .get("usageNanoCores", 0)) // 1_000_000,
                res.MEMORY: int(pod_doc.get("memory", {})
                                .get("workingSetBytes", 0)),
            }
            cur = self.store.get("podmetrics", ns, pod_name)
            if cur is None:
                self.store.create("podmetrics", api.PodMetrics(
                    metadata=api.ObjectMeta(name=pod_name, namespace=ns),
                    usage=usage))
            elif cur.usage != usage:
                cur.usage = usage
                self.store.update("podmetrics", cur)
        # stale sweep: metrics whose pod is gone, or whose pod is bound
        # to THIS node but absent from this scrape, are dropped (the
        # reference metrics-server reports only currently-scraped pods)
        for pm in self.store.list("podmetrics"):
            ns, pm_name = pm.metadata.namespace, pm.metadata.name
            if (ns, pm_name) in scraped:
                continue
            pod = self.store.get("pods", ns, pm_name)
            if pod is None or pod.spec.node_name == name:
                self.store.delete("podmetrics", ns, pm_name)
