"""Namespace lifecycle: cascading teardown on deletion.

Reference: pkg/controller/namespace/deletion/namespaced_resources_
deleter.go (Delete:77 — once phase=Terminating, delete all namespaced
content, then remove the 'kubernetes' finalizer and the namespace).
Deletion here is modeled by setting status.phase=Terminating (the
apiserver analog of a delete with finalizers pending).
"""

from __future__ import annotations

from ..runtime.store import Conflict
from .base import Controller

# namespaced kinds the deleter sweeps (deletion/namespaced_resources_
# deleter.go discovers these dynamically; the registry is our discovery)
_SWEEP = ["pods", "services", "replicationcontrollers", "replicasets",
          "statefulsets", "deployments", "daemonsets", "jobs", "cronjobs",
          "endpoints", "poddisruptionbudgets", "persistentvolumeclaims",
          "resourcequotas", "serviceaccounts", "secrets", "configmaps",
          "events"]


class NamespaceController(Controller):
    name = "namespace"

    def __init__(self, store):
        super().__init__(store)
        self.informer("namespaces",
                      on_add=self._ns_event,
                      on_update=lambda o, n: self._ns_event(n),
                      on_delete=lambda o: None)

    def _ns_event(self, ns_obj):
        if ns_obj.status.phase == "Terminating":
            self.queue.add(ns_obj.metadata.name)

    def sync(self, key: str):
        name = key.split("/")[-1]
        ns_obj = (self.store.get("namespaces", "", name)
                  or self.store.get("namespaces", "default", name))
        if ns_obj is None or ns_obj.status.phase != "Terminating":
            return
        for kind in _SWEEP:
            for obj in self.store.list(kind, name):
                try:
                    self.store.delete(kind, name, obj.metadata.name)
                except KeyError:
                    pass
        remaining = sum(len(self.store.list(kind, name)) for kind in _SWEEP)
        if remaining:
            raise RuntimeError(f"{remaining} objects remained; requeue")
        # content gone: drop the finalizer and the namespace itself
        ns_obj.spec.finalizers = []
        try:
            self.store.update("namespaces", ns_obj)
            self.store.delete("namespaces", ns_obj.metadata.namespace, name)
        except (Conflict, KeyError):
            pass
