"""Node IPAM controller: allocate pod CIDRs to nodes from the cluster CIDR.

Reference: pkg/controller/nodeipam/ipam/range_allocator.go — carve the
cluster CIDR into fixed-size per-node subnets, assign one to each node's
spec.podCIDR, release on node deletion, and never double-allocate (the
CidrSet bitmap, ipam/cidrset/cidr_set.go).
"""

from __future__ import annotations

import ipaddress
import threading

from .base import Controller


class CidrSet:
    """Bitmap allocator over cluster_cidr split at node_mask_size
    (cidr_set.go:35)."""

    def __init__(self, cluster_cidr: str, node_mask_size: int):
        self.net = ipaddress.ip_network(cluster_cidr)
        if node_mask_size < self.net.prefixlen:
            raise ValueError("node mask must be longer than cluster mask")
        self.node_mask_size = node_mask_size
        self.max_cidrs = 2 ** (node_mask_size - self.net.prefixlen)
        self._used = set()
        self._lock = threading.Lock()

    def _subnet(self, index: int) -> str:
        base = int(self.net.network_address) + (
            index << (self.net.max_prefixlen - self.node_mask_size))
        return f"{ipaddress.ip_address(base)}/{self.node_mask_size}"

    def allocate_next(self) -> str:
        with self._lock:
            for i in range(self.max_cidrs):
                if i not in self._used:
                    self._used.add(i)
                    return self._subnet(i)
            raise RuntimeError("cluster CIDR exhausted")

    def occupy(self, cidr: str) -> None:
        """Mark an existing allocation (controller restart repopulation)."""
        net = ipaddress.ip_network(cidr)
        index = (int(net.network_address) - int(self.net.network_address)) >> (
            self.net.max_prefixlen - self.node_mask_size)
        with self._lock:
            self._used.add(index)

    def release(self, cidr: str) -> None:
        net = ipaddress.ip_network(cidr)
        index = (int(net.network_address) - int(self.net.network_address)) >> (
            self.net.max_prefixlen - self.node_mask_size)
        with self._lock:
            self._used.discard(index)


class NodeIpamController(Controller):
    name = "nodeipam"

    def __init__(self, store, cluster_cidr: str = "10.244.0.0/16",
                 node_mask_size: int = 24):
        super().__init__(store)
        self.cidrs = CidrSet(cluster_cidr, node_mask_size)
        # repopulate from existing allocations before watching
        # (range_allocator.go:96 lists nodes and occupies their CIDRs)
        for node in store.list("nodes"):
            if node.spec.pod_cidr:
                self.cidrs.occupy(node.spec.pod_cidr)
        self.informer("nodes",
                      on_add=self.enqueue,
                      on_update=lambda o, n: self.enqueue(n),
                      on_delete=self._on_delete)

    def _on_delete(self, node):
        if node.spec.pod_cidr:
            self.cidrs.release(node.spec.pod_cidr)

    def resync(self):
        for node in self.store.list("nodes"):
            self.enqueue(node)

    def sync(self, key: str):
        _, name = key.split("/", 1)
        node = (self.store.get("nodes", "default", name)
                or self.store.get("nodes", "", name))
        if node is None or node.spec.pod_cidr:
            return
        node.spec.pod_cidr = self.cidrs.allocate_next()
        try:
            self.store.update("nodes", node)
        except Exception:
            self.cidrs.release(node.spec.pod_cidr)
            node.spec.pod_cidr = ""
            raise
