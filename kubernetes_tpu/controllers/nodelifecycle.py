"""Node lifecycle: the cluster's failure detector.

Reference: pkg/controller/nodelifecycle/node_lifecycle_controller.go —
monitorNodeStatus (:544) watches kubelet heartbeats (NodeStatus
conditions + lastHeartbeatTime); after grace period the node's Ready
condition is set to Unknown, NoExecute taints are applied
(not-ready/unreachable, :473 via the taint manager), and pods are
evicted once their tolerationSeconds expire (scheduler/taint-manager
NoExecuteTaintManager). Recovery removes the taints when heartbeats
resume. This is how the framework achieves elastic recovery: failed
nodes drain automatically and their pods requeue through the scheduler.

Heartbeats arrive as node status updates: kubelet sets
annotation 'heartbeat' = str(epoch seconds) and Ready=True
(the analog of LastHeartbeatTime on NodeCondition).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from ..api import types as api
from ..runtime.store import Conflict
from .base import Controller, is_pod_active

TAINT_NOT_READY = "node.kubernetes.io/not-ready"
TAINT_UNREACHABLE = "node.kubernetes.io/unreachable"
HEARTBEAT_ANNOTATION = "heartbeat"


def _heartbeat(node: api.Node) -> Optional[float]:
    v = (node.metadata.annotations or {}).get(HEARTBEAT_ANNOTATION)
    try:
        return float(v) if v is not None else None
    except ValueError:
        return None


def _ready_status(node: api.Node) -> str:
    for c in node.status.conditions:
        if c.type == api.NODE_READY:
            return c.status
    return api.COND_UNKNOWN


class NodeLifecycleController(Controller):
    name = "nodelifecycle"

    def __init__(self, store, clock=time.time,
                 grace_period: float = 40.0,
                 eviction_wait: float = 300.0):
        super().__init__(store)
        self.clock = clock
        self.grace_period = grace_period
        self.default_eviction_wait = eviction_wait
        self.informer("nodes")
        # taint-expiry bookkeeping: pod key -> (eviction deadline, node)
        self._evict_at: Dict[str, tuple] = {}
        self._timer: Optional[threading.Thread] = None

    # -- monitorNodeStatus -----------------------------------------------------

    def monitor(self, now: Optional[float] = None) -> None:
        """One monitorNodeStatus pass over all nodes + taint-manager sweep."""
        now = now if now is not None else self.clock()
        for node in self.store.list("nodes"):
            self._monitor_node(node, now)
        self._process_evictions(now)

    def sync(self, key: str):
        name = key.split("/", 1)[1]
        node = (self.store.get("nodes", "default", name)
                or self.store.get("nodes", "", name))
        if node is not None:
            self._monitor_node(node, self.clock())

    def _monitor_node(self, node: api.Node, now: float):
        """One pass over one node. All mutations (Ready condition + taint
        swap) land in a single update so a CAS conflict never leaves the
        condition and taint out of sync — the next pass simply retries."""
        hb = _heartbeat(node)
        stale = hb is None or (now - hb) > self.grace_period
        ready = _ready_status(node)
        changed = False
        if stale:
            # kubelet stopped reporting: Ready -> Unknown + unreachable
            # taint (tryUpdateNodeStatus + markNodeForTainting :473)
            if ready != api.COND_UNKNOWN:
                self._set_ready_cond(node, api.COND_UNKNOWN)
                changed = True
            changed |= self._swap_taints(node, add=TAINT_UNREACHABLE,
                                         drop=TAINT_NOT_READY)
        elif ready == api.COND_FALSE:
            changed = self._swap_taints(node, add=TAINT_NOT_READY,
                                        drop=TAINT_UNREACHABLE)
        elif ready == api.COND_TRUE:
            changed = self._swap_taints(node, add=None,
                                        drop=(TAINT_NOT_READY,
                                              TAINT_UNREACHABLE))
        if changed:
            try:
                self.store.update("nodes", node)
            except (Conflict, KeyError):
                return  # stale view; retried on the next pass
        if any(t.effect == api.NO_EXECUTE for t in node.spec.taints):
            self._schedule_evictions(node, now)
        else:
            # cancel pending evictions for this node (scan only the small
            # _evict_at map, not the cluster pod list)
            for key, (_, nname) in list(self._evict_at.items()):
                if nname == node.metadata.name:
                    self._evict_at.pop(key, None)

    @staticmethod
    def _set_ready_cond(node: api.Node, status: str):
        node.status.conditions = [c for c in node.status.conditions
                                  if c.type != api.NODE_READY]
        node.status.conditions.append(api.NodeCondition(api.NODE_READY, status))

    @staticmethod
    def _swap_taints(node: api.Node, add: Optional[str], drop) -> bool:
        """Mutate node.spec.taints in place; True if anything changed
        (taint manager swapUnreachableTaint analog)."""
        drops = (drop,) if isinstance(drop, str) else tuple(drop or ())
        taints = [t for t in node.spec.taints
                  if t.key not in drops and t.key != add]
        if add is not None:
            taints.append(api.Taint(key=add, effect=api.NO_EXECUTE))
        if [t.key for t in taints] == [t.key for t in node.spec.taints]:
            return False
        node.spec.taints = taints
        return True

    # -- NoExecute taint manager (eviction with tolerationSeconds) -------------

    def _schedule_evictions(self, node: api.Node, now: Optional[float] = None):
        now = now if now is not None else self.clock()
        keys = {t.key for t in node.spec.taints
                if t.effect == api.NO_EXECUTE}
        if not keys:
            return
        for pod in self.store.list("pods"):
            if pod.spec.node_name != node.metadata.name or \
                    not is_pod_active(pod):
                continue
            k = pod.full_name()
            wait = self._toleration_wait(pod, keys)
            if wait is None:
                # tolerates forever: never evict
                self._evict_at.pop(k, None)
            else:
                deadline = now + wait
                if k not in self._evict_at or self._evict_at[k][0] > deadline:
                    self._evict_at[k] = (deadline, node.metadata.name)

    def _toleration_wait(self, pod: api.Pod, taint_keys) -> Optional[float]:
        """Min tolerationSeconds across NoExecute taints; None = tolerates
        forever; 0 = evict now (taint manager getMinTolerationTime)."""
        waits = []
        for key in taint_keys:
            taint = api.Taint(key=key, effect=api.NO_EXECUTE)
            matching = [t for t in pod.spec.tolerations if t.tolerates(taint)]
            if not matching:
                waits.append(0.0)
            else:
                secs = [t.toleration_seconds for t in matching]
                if any(s is None for s in secs):
                    continue  # tolerates this taint forever
                waits.append(float(max(0, min(secs))))
        if not waits:
            return None
        return min(waits)

    def _process_evictions(self, now: float):
        for key, (deadline, _nname) in list(self._evict_at.items()):
            if deadline > now:
                continue
            ns, name = key.split("/", 1)
            pod = self.store.get("pods", ns, name)
            self._evict_at.pop(key, None)
            if pod is None or not pod.spec.node_name:
                continue
            node = (self.store.get("nodes", "default", pod.spec.node_name)
                    or self.store.get("nodes", "", pod.spec.node_name))
            if node is None or not any(t.effect == api.NO_EXECUTE
                                       for t in node.spec.taints):
                continue
            try:
                self.store.delete("pods", ns, name)
            except KeyError:
                pass

    # -- background loop -------------------------------------------------------

    def run(self, workers: int = 1, period: float = 5.0):
        super().run(workers)

        def loop():
            while not self._stop.is_set():
                self.monitor()
                self._stop.wait(period)

        self._timer = threading.Thread(target=loop, daemon=True,
                                       name="nodelifecycle-monitor")
        self._timer.start()
