"""Node lifecycle: the cluster's zone-aware failure detector.

Reference: pkg/controller/nodelifecycle/node_lifecycle_controller.go —
monitorNodeStatus (:544) watches kubelet heartbeats (NodeStatus
conditions + lastHeartbeatTime); after grace period the node's Ready
condition is set to Unknown, NoExecute taints are applied
(not-ready/unreachable, :473 via the taint manager), and pods are
evicted once their tolerationSeconds expire (scheduler/taint-manager
NoExecuteTaintManager). Recovery removes the taints when heartbeats
resume.

Correlated failure is where a naive detector destroys a cluster: a rack
switch flap or a control-plane partition makes EVERY node in a failure
domain miss heartbeats at once, and hard-deleting every resident pod in
one monitor pass is the eviction storm the reference's zone machinery
(ComputeZoneState + per-zone RateLimitedTimedQueue) exists to prevent.
This controller implements that machinery:

  * Nodes bucket into failure domains by zone label (GetZoneKey; ids
    interned through the same zone interner the scheduling snapshot
    uses, so the two views agree on domain identity).
  * Each monitor pass computes a per-zone health state — Normal /
    PartialDisruption / FullDisruption — with the ready/not-ready tally
    done as ONE batched reduction over dense condition columns
    (ops/zonehealth.py), on the device path when it is healthy and on
    the host when the circuit breaker (sched/breaker.py) says it isn't.
  * Evictions drain through per-zone token buckets
    (utils/ratelimit.py) instead of firing immediately:
      Normal             -> primary rate (eviction_rate_qps)
      PartialDisruption  -> secondary rate in large zones
                            (> large_cluster_threshold nodes),
                            HALTED (qps 0) in small ones — losing most
                            of a small zone is indistinguishable from
                            losing our link to it
      FullDisruption     -> eviction SUSPENDED entirely: when 100% of a
                            zone stops heartbeating the failure is
                            presumed to be ours (partition), not the
                            nodes'; queued evictions wait until
                            heartbeats resume, at which point recovery
                            clears the taints and cancels them.
    Divergence from the reference, by design: 1.11 only suspends when
    ALL zones are fully disrupted (master-disruption mode) and evicts a
    single dead zone at the primary rate; here suspension is per-zone —
    stricter storm control for the multi-pod TPU workloads this
    scheduler carries (a re-placed 256-chip gang is far more expensive
    than a delayed eviction).

Transitions, evictions, and suspensions are emitted as events
(client/record.py) and exported as node_lifecycle_zone_health
{zone,state} gauges plus eviction / queue-depth series.

Heartbeats arrive as node status updates: kubelet sets annotation
'heartbeat' = str(epoch seconds) and Ready=True (the analog of
LastHeartbeatTime on NodeCondition). The `nodelifecycle.evict` fault
point fires before every pod delete (drop = the eviction API call is
lost and retried next pass).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

import numpy as np

from ..api import types as api
from ..client.record import EventRecorder
from ..ops import zonehealth
from ..runtime.store import Conflict
from ..state.vocab import Interner, VocabSet, bucket_size
from ..utils import faultpoints
from ..utils.metrics import Metrics
from ..utils.ratelimit import TokenBucket
from .base import Controller, is_pod_active

TAINT_NOT_READY = "node.kubernetes.io/not-ready"
TAINT_UNREACHABLE = "node.kubernetes.io/unreachable"
HEARTBEAT_ANNOTATION = "heartbeat"

# per-zone health states (node_lifecycle_controller.go ZoneState)
ZONE_NORMAL = "Normal"
ZONE_PARTIAL = "PartialDisruption"
ZONE_FULL = "FullDisruption"
ZONE_STATES = (ZONE_NORMAL, ZONE_PARTIAL, ZONE_FULL)


def _heartbeat(node: api.Node) -> Optional[float]:
    v = (node.metadata.annotations or {}).get(HEARTBEAT_ANNOTATION)
    try:
        return float(v) if v is not None else None
    except ValueError:
        return None


def _ready_status(node: api.Node) -> str:
    for c in node.status.conditions:
        if c.type == api.NODE_READY:
            return c.status
    return api.COND_UNKNOWN


def zone_display(zone_key: str) -> str:
    """GetZoneKey strings join region/zone with a NUL separator; events
    and metric labels need a printable form."""
    return zone_key.replace(":\x00:", "/").strip("/") or "unzoned"


class _Zone:
    """Synthetic involvedObject for zone-scoped events (a failure domain
    has no API object of its own)."""

    def __init__(self, name: str):
        self.metadata = api.ObjectMeta(name=name, namespace="default")


_Zone.__name__ = "Zone"


class NodeLifecycleController(Controller):
    name = "nodelifecycle"

    def __init__(self, store, clock=time.time,
                 grace_period: float = 40.0,
                 eviction_wait: float = 300.0,
                 eviction_rate_qps: float = 0.1,
                 secondary_eviction_rate_qps: float = 0.01,
                 eviction_burst: float = 10.0,
                 large_cluster_threshold: int = 50,
                 unhealthy_zone_threshold: float = 0.55,
                 vocabs: Optional[VocabSet] = None,
                 breaker=None,
                 metrics: Optional[Metrics] = None):
        super().__init__(store)
        self.clock = clock
        self.grace_period = grace_period
        self.default_eviction_wait = eviction_wait
        # storm-control knobs (kube-controller-manager --node-eviction-rate,
        # --secondary-node-eviction-rate, --large-cluster-size-threshold,
        # --unhealthy-zone-threshold)
        self.eviction_rate_qps = eviction_rate_qps
        self.secondary_eviction_rate_qps = secondary_eviction_rate_qps
        self.eviction_burst = eviction_burst
        self.large_cluster_threshold = large_cluster_threshold
        self.unhealthy_zone_threshold = unhealthy_zone_threshold
        # the zone interner: shared with the scheduling snapshot when a
        # VocabSet is passed, so domain ids agree across components
        self.zones: Interner = vocabs.zones if vocabs is not None \
            else Interner()
        self.breaker = breaker  # device-path circuit breaker (optional)
        self.metrics = metrics if metrics is not None else Metrics()
        self.recorder = EventRecorder(store, "node-controller", clock=clock)
        self.informer("nodes")
        # taint-expiry bookkeeping: pod key -> (eviction deadline, node)
        self._evict_at: Dict[str, tuple] = {}
        # zone key -> state / token bucket / node count, refreshed per pass
        self.zone_states: Dict[str, str] = {}
        self._zone_buckets: Dict[str, TokenBucket] = {}
        self._zone_size: Dict[str, int] = {}
        self._node_zone: Dict[str, str] = {}
        self.evictions = 0  # total pods evicted (cumulative)
        self._timer: Optional[threading.Thread] = None

    def configure(self, *, eviction_rate_qps: Optional[float] = None,
                  secondary_eviction_rate_qps: Optional[float] = None,
                  eviction_burst: Optional[float] = None,
                  large_cluster_threshold: Optional[int] = None,
                  unhealthy_zone_threshold: Optional[float] = None) -> None:
        """Apply controller-manager flag overrides; live buckets re-rate
        on the next state evaluation."""
        if eviction_rate_qps is not None:
            self.eviction_rate_qps = eviction_rate_qps
        if secondary_eviction_rate_qps is not None:
            self.secondary_eviction_rate_qps = secondary_eviction_rate_qps
        if eviction_burst is not None:
            self.eviction_burst = eviction_burst
        if large_cluster_threshold is not None:
            self.large_cluster_threshold = large_cluster_threshold
        if unhealthy_zone_threshold is not None:
            self.unhealthy_zone_threshold = unhealthy_zone_threshold
        for zk, bucket in self._zone_buckets.items():
            bucket.swap_rate(self._zone_qps(
                self.zone_states.get(zk, ZONE_NORMAL),
                self._zone_size.get(zk, 0)))

    # -- monitorNodeStatus -----------------------------------------------------

    def monitor(self, now: Optional[float] = None) -> None:
        """One monitorNodeStatus pass over all nodes: per-node condition
        and taint reconciliation, then the zone disruption computation,
        then the rate-limited taint-manager sweep."""
        now = now if now is not None else self.clock()
        nodes = self.store.list("nodes")
        # one pods-by-node index per pass: a partition keeps whole zones
        # tainted for its entire duration, and per-tainted-node scans of
        # the full pod list would be O(tainted x pods) every 5s
        by_node: Dict[str, list] = {}
        for pod in self.store.list("pods"):
            if pod.spec.node_name and is_pod_active(pod):
                by_node.setdefault(pod.spec.node_name, []).append(pod)
        for node in nodes:
            self._monitor_node(node, now, by_node)
        self._update_zone_states(nodes, now)
        self._process_evictions(now)

    def sync(self, key: str):
        name = key.split("/", 1)[1]
        node = (self.store.get("nodes", "default", name)
                or self.store.get("nodes", "", name))
        if node is not None:
            self._monitor_node(node, self.clock())

    def _monitor_node(self, node: api.Node, now: float,
                      pods_by_node: Optional[Dict[str, list]] = None):
        """One pass over one node. All mutations (Ready condition + taint
        swap) land in a single update so a CAS conflict never leaves the
        condition and taint out of sync — the next pass simply retries."""
        hb = _heartbeat(node)
        stale = hb is None or (now - hb) > self.grace_period
        ready = _ready_status(node)
        changed = False
        if stale:
            # kubelet stopped reporting: Ready -> Unknown + unreachable
            # taint (tryUpdateNodeStatus + markNodeForTainting :473)
            if ready != api.COND_UNKNOWN:
                self._set_ready_cond(node, api.COND_UNKNOWN)
                changed = True
            changed |= self._swap_taints(node, add=TAINT_UNREACHABLE,
                                         drop=TAINT_NOT_READY)
        elif ready == api.COND_FALSE:
            changed = self._swap_taints(node, add=TAINT_NOT_READY,
                                        drop=TAINT_UNREACHABLE)
        elif ready == api.COND_TRUE:
            changed = self._swap_taints(node, add=None,
                                        drop=(TAINT_NOT_READY,
                                              TAINT_UNREACHABLE))
        if changed:
            try:
                self.store.update("nodes", node)
            except (Conflict, KeyError):
                return  # stale view; retried on the next pass
        if any(t.effect == api.NO_EXECUTE for t in node.spec.taints):
            self._schedule_evictions(node, now, pods_by_node)
        else:
            # cancel pending evictions for this node (scan only the small
            # _evict_at map, not the cluster pod list)
            for key, (_, nname) in list(self._evict_at.items()):
                if nname == node.metadata.name:
                    self._evict_at.pop(key, None)

    @staticmethod
    def _set_ready_cond(node: api.Node, status: str):
        node.status.conditions = [c for c in node.status.conditions
                                  if c.type != api.NODE_READY]
        node.status.conditions.append(api.NodeCondition(api.NODE_READY, status))

    @staticmethod
    def _swap_taints(node: api.Node, add: Optional[str], drop) -> bool:
        """Mutate node.spec.taints in place; True if anything changed
        (taint manager swapUnreachableTaint analog). Taints are matched
        by (key, effect): the controller owns only the NoExecute pair —
        a user taint sharing a key under a different effect is never
        clobbered, and an effect-only difference counts as a change."""
        drops = (drop,) if isinstance(drop, str) else tuple(drop or ())
        gone = {(k, api.NO_EXECUTE) for k in drops}
        if add is not None:
            gone.add((add, api.NO_EXECUTE))  # re-added canonically below
        before = [(t.key, t.effect) for t in node.spec.taints]
        taints = [t for t in node.spec.taints
                  if (t.key, t.effect) not in gone]
        if add is not None:
            taints.append(api.Taint(key=add, effect=api.NO_EXECUTE))
        # order-insensitive compare: re-appending an already-present
        # taint must not register as a change every pass
        if sorted((t.key, t.effect) for t in taints) == sorted(before):
            return False
        node.spec.taints = taints
        return True

    # -- zone disruption computation (ComputeZoneState / handleDisruption) ----

    def _zone_qps(self, state: str, size: int) -> float:
        if state == ZONE_NORMAL:
            return self.eviction_rate_qps
        if state == ZONE_PARTIAL:
            # ReducedQPSFunc: secondary rate in large zones, full stop in
            # small ones
            return (self.secondary_eviction_rate_qps
                    if size > self.large_cluster_threshold else 0.0)
        return 0.0  # ZONE_FULL: suspended (enforced again in the sweep)

    def _bucket(self, zone_key: str) -> TokenBucket:
        b = self._zone_buckets.get(zone_key)
        if b is None:
            b = TokenBucket(self.eviction_rate_qps,
                            burst=self.eviction_burst, clock=self.clock)
            self._zone_buckets[zone_key] = b
        return b

    def _update_zone_states(self, nodes: List[api.Node], now: float):
        """Bucket nodes into failure domains and classify each: the
        ready/not-ready tally is one batched reduction over condition
        columns (ops/zonehealth), breaker-gated device path with an
        exact host fallback."""
        n = len(nodes)
        self._node_zone = {}
        if n == 0:
            return
        # dense columns, padded to a power-of-two bucket so the jitted
        # reduction compiles once per cluster-size bucket
        cap = bucket_size(n)
        zone_id = np.zeros((cap,), np.int32)
        bad = np.zeros((cap,), bool)
        valid = np.zeros((cap,), bool)
        seen: Dict[str, int] = {}
        for i, node in enumerate(nodes):
            zk = api.get_zone_key(node)
            zid = self.zones.intern(zk)
            seen[zk] = zid
            self._node_zone[node.metadata.name] = zk
            zone_id[i] = zid
            bad[i] = _ready_status(node) != api.COND_TRUE
            valid[i] = True
        num_zones = bucket_size(self.zones.size)
        totals, badc = zonehealth.zone_tally(zone_id, bad, valid, num_zones,
                                             breaker=self.breaker)
        for zk, zid in seen.items():
            total = int(totals[zid])
            nbad = int(badc[zid])
            if total == 0:
                continue
            if nbad == total:
                state = ZONE_FULL
            elif nbad / total >= self.unhealthy_zone_threshold:
                state = ZONE_PARTIAL
            else:
                state = ZONE_NORMAL
            self._zone_size[zk] = total
            self._set_zone_state(zk, state, total, nbad, now)

    def _set_zone_state(self, zone_key: str, state: str, total: int,
                        nbad: int, now: float):
        old = self.zone_states.get(zone_key)
        # re-rate even without a state transition: a PARTIAL zone whose
        # node count crosses large_cluster_threshold changes qps (halt
        # <-> secondary) while staying PARTIAL
        bucket = self._bucket(zone_key)
        qps = self._zone_qps(state, total)
        if bucket.qps != qps:
            bucket.swap_rate(qps, now)
        if old == state:
            return
        self.zone_states[zone_key] = state
        disp = zone_display(zone_key)
        for s in ZONE_STATES:
            self.metrics.zone_health.labels(zone=disp, state=s).set(
                1.0 if s == state else 0.0)
        zref = _Zone(disp)
        if state == ZONE_FULL:
            # the suspension event the ISSUE's storm-control contract
            # hinges on: 100% failure is presumed OUR failure
            self.metrics.eviction_suspensions.inc()
            self.recorder.event(
                zref, "Warning", "EvictionsSuspended",
                f"zone {disp}: all {total} nodes stopped reporting — "
                f"entering {ZONE_FULL}; pod eviction suspended until "
                f"heartbeats resume")
        elif state == ZONE_PARTIAL:
            qps = self._zone_qps(state, total)
            self.recorder.event(
                zref, "Warning", "ZoneDisruptionEntered",
                f"zone {disp}: {nbad}/{total} nodes unhealthy — entering "
                f"{ZONE_PARTIAL}; eviction rate limited to {qps:g}/s")
        elif old is not None:
            self.recorder.event(
                zref, "Normal", "ZoneDisruptionLeft",
                f"zone {disp}: {total - nbad}/{total} nodes healthy — "
                f"back to {ZONE_NORMAL}")

    # -- NoExecute taint manager (eviction with tolerationSeconds) -------------

    def _schedule_evictions(self, node: api.Node, now: Optional[float] = None,
                            pods_by_node: Optional[Dict[str, list]] = None):
        now = now if now is not None else self.clock()
        keys = {t.key for t in node.spec.taints
                if t.effect == api.NO_EXECUTE}
        if not keys:
            return
        if pods_by_node is not None:  # monitor() pre-indexed the pass
            residents = pods_by_node.get(node.metadata.name, ())
        else:  # single-node sync(): one scan is fine
            residents = [p for p in self.store.list("pods")
                         if p.spec.node_name == node.metadata.name
                         and is_pod_active(p)]
        for pod in residents:
            k = pod.full_name()
            wait = self._toleration_wait(pod, keys)
            if wait is None:
                # tolerates forever: never evict
                self._evict_at.pop(k, None)
            else:
                deadline = now + wait
                if k not in self._evict_at or self._evict_at[k][0] > deadline:
                    self._evict_at[k] = (deadline, node.metadata.name)

    def _toleration_wait(self, pod: api.Pod, taint_keys) -> Optional[float]:
        """Min tolerationSeconds across NoExecute taints; None = tolerates
        forever; 0 = evict now (taint manager getMinTolerationTime)."""
        waits = []
        for key in taint_keys:
            taint = api.Taint(key=key, effect=api.NO_EXECUTE)
            matching = [t for t in pod.spec.tolerations if t.tolerates(taint)]
            if not matching:
                waits.append(0.0)
            else:
                secs = [t.toleration_seconds for t in matching]
                if any(s is None for s in secs):
                    continue  # tolerates this taint forever
                waits.append(float(max(0, min(secs))))
        if not waits:
            return None
        return min(waits)

    def _process_evictions(self, now: float):
        """Drain due evictions through the per-zone rate limiters
        (RateLimitedTimedQueue worker analog): oldest deadline first so
        a token goes to the longest-waiting pod, suspended/empty-bucket
        zones leave entries queued for the next pass."""
        due = sorted((deadline, key, nname)
                     for key, (deadline, nname) in self._evict_at.items()
                     if deadline <= now)
        depth: Dict[str, int] = {}
        for deadline, key, nname in due:
            zone = self._node_zone.get(nname, "")
            state = self.zone_states.get(zone, ZONE_NORMAL)
            disp = zone_display(zone)
            if state == ZONE_FULL:
                # suspended: presumed control-plane-side failure; entry
                # stays queued and is cancelled when heartbeats resume
                depth[disp] = depth.get(disp, 0) + 1
                continue
            ns, name = key.split("/", 1)
            pod = self.store.get("pods", ns, name)
            if pod is None or not pod.spec.node_name:
                self._evict_at.pop(key, None)
                continue
            node = (self.store.get("nodes", "default", pod.spec.node_name)
                    or self.store.get("nodes", "", pod.spec.node_name))
            if node is None or not any(t.effect == api.NO_EXECUTE
                                       for t in node.spec.taints):
                self._evict_at.pop(key, None)
                continue
            if not self._bucket(zone).try_take(now):
                depth[disp] = depth.get(disp, 0) + 1
                continue
            if faultpoints.fire("nodelifecycle.evict",
                                payload=(key, nname)):
                # drop-mode fault: the eviction API call was lost on the
                # wire; the entry stays queued and retries next pass
                depth[disp] = depth.get(disp, 0) + 1
                continue
            self._evict_at.pop(key, None)
            try:
                self.store.delete("pods", ns, name)
            except KeyError:
                continue
            self.evictions += 1
            self.metrics.zone_evictions.labels(zone=disp).inc()
            self.recorder.event(
                pod, "Normal", "NodeControllerEviction",
                f"Marking for deletion Pod {key} from Node {nname}")
        for disp, bucket in list(self._zone_buckets.items()):
            d = zone_display(disp)
            self.metrics.eviction_queue_depth.labels(zone=d).set(
                float(depth.get(d, 0)))

    def queue_depth(self) -> int:
        """Evictions due but held by suspension/rate limits (observability
        + test hook)."""
        now = self.clock()
        return sum(1 for deadline, _ in self._evict_at.values()
                   if deadline <= now)

    # -- background loop -------------------------------------------------------

    def run(self, workers: int = 1, period: float = 5.0):
        super().run(workers)

        def loop():
            while not self._stop.is_set():
                self.monitor()
                self._stop.wait(period)

        self._timer = threading.Thread(target=loop, daemon=True,
                                       name="nodelifecycle-monitor")
        self._timer.start()
