"""Horizontal Pod Autoscaler controller.

Reference: pkg/controller/podautoscaler/horizontal.go:80
(NewHorizontalController) + replica_calculator.go. The control law,
reproduced exactly:

  usageRatio     = currentUtilization / targetUtilization
  desiredReplicas = ceil(usageRatio * currentReadyReplicas)
  no-op when |usageRatio - 1| <= tolerance (0.1, horizontal.go:62)
  clamp to [minReplicas, maxReplicas]

where currentUtilization = sum(pod cpu usage) / sum(pod cpu requests),
request-based, over the target's selected pods (metrics/utilization.go).

Stabilization windows (horizontal.go:409-419 via upscale/downscale
forbidden windows): after a scale event, further scale-UPs are forbidden
for 3 minutes and scale-DOWNs for 5 minutes, measured against
status.lastScaleTime.

The metrics source is pluggable: by default it reads `podmetrics`
objects from the store (metadata.name == pod name, usage["cpu"] in
millicores — what metrics-server publishes); pass `metrics_fn(pod) ->
Optional[int]` to plug anything else in, the seam the reference gets
from its MetricsClient interface (podautoscaler/metrics/interfaces.go).
"""

from __future__ import annotations

import math
import time
from typing import Callable, List, Optional

from ..api import resources as res
from ..api import types as api
from .base import Controller

TOLERANCE = 0.1  # horizontal.go:62 defaultTolerance
UPSCALE_FORBIDDEN_WINDOW = 3 * 60.0  # horizontal.go upscaleForbiddenWindow
DOWNSCALE_FORBIDDEN_WINDOW = 5 * 60.0

# scalable target kinds -> store plural: THE scale mapping, shared with
# the apiserver's /scale subresource (api/scale.py)
from ..api import scale as scaleapi  # noqa: E402
from ..api.scale import BUILTIN_SCALE_KINDS as SCALE_KINDS  # noqa: E402


class HorizontalPodAutoscalerController(Controller):
    name = "horizontalpodautoscaler"

    def __init__(self, store, metrics_fn: Optional[Callable] = None,
                 clock: Callable[[], float] = time.time):
        super().__init__(store)
        self.clock = clock
        self.metrics_fn = metrics_fn or self._store_metrics
        self.informer("horizontalpodautoscalers")
        # a metrics publish re-evaluates only the HPAs whose target
        # selects that pod — enqueueing every HPA per metric would cost
        # O(pods x HPAs) syncs per publish cycle. The periodic resync
        # (base.run()'s ticker; the reference polls every 30s,
        # horizontal.go:144) covers deferred decisions like forbidden
        # windows and custom metrics_fn sources with no store events.
        self.informer("podmetrics", enqueue_fn=self._enqueue_for_metric)

    def resync(self):
        for hpa in self.store.list("horizontalpodautoscalers"):
            self.enqueue(hpa)

    def _enqueue_for_metric(self, m, new=None):
        # a pod can only be selected by a same-namespace target, and the
        # rate-limiting queue dedups keys, so namespace-scoped enqueue
        # coalesces a publish cycle to at most one sync per local HPA —
        # resolving the exact target per metric would cost more than the
        # syncs it saves
        m = new if new is not None else m
        for hpa in self.store.list("horizontalpodautoscalers",
                                   m.metadata.namespace):
            self.enqueue(hpa)

    # -- metrics source ---------------------------------------------------------

    def _store_metrics(self, pod: api.Pod) -> Optional[int]:
        m = self.store.get("podmetrics", pod.namespace, pod.metadata.name)
        if m is None:
            return None
        return m.usage.get(res.CPU)

    # -- target plumbing --------------------------------------------------------

    def _get_target(self, hpa: api.HorizontalPodAutoscaler):
        """Resolve scaleTargetRef through the shared scale mapping —
        built-in workloads AND custom kinds whose CRD declares
        subresources.scale (the reference HPA goes through the
        polymorphic scale client for exactly this reason,
        horizontal.go scaleForResourceMappings). Returns
        (plural, target, mapping)."""

        ref = hpa.spec.scale_target_ref
        plural = SCALE_KINDS.get(ref.kind)
        if plural is None:
            crd = scaleapi.crd_for_kind(self.store, ref.kind)
            if crd is None or crd.spec.subresources is None or \
                    crd.spec.subresources.scale is None:
                return None, None, None
            plural = crd.spec.names.plural
        target = self.store.get(plural, hpa.metadata.namespace, ref.name)
        if target is None:
            return plural, None, None
        return plural, target, scaleapi.mapping_for(self.store, plural,
                                                    target)

    def _selected_pods(self, target, mapping=None) -> List[api.Pod]:
        if isinstance(target, api.CustomObject):
            # custom targets select pods through the Scale selector
            # string (status.selector from labelSelectorPath)
            from ..api.labels import Selector

            sel_str = (mapping[2] if mapping else "") or ""
            if not sel_str:
                return []
            try:
                s = Selector.parse(sel_str)
            except ValueError:
                return []
            return [p for p in self.store.list("pods",
                                               target.metadata.namespace)
                    if api.is_pod_active(p)
                    and s.matches(p.metadata.labels or {})]
        sel = target.spec.selector
        if sel is None:
            match = target.spec.template.metadata.labels \
                if target.spec.template else {}
            fits = lambda p: all(  # noqa: E731
                (p.metadata.labels or {}).get(k) == v
                for k, v in match.items())
        elif isinstance(sel, dict):
            fits = lambda p: all(  # noqa: E731
                (p.metadata.labels or {}).get(k) == v for k, v in sel.items())
        else:
            s = sel.to_selector()
            fits = lambda p: s.matches(p.metadata.labels or {})  # noqa: E731
        return [p for p in self.store.list("pods", target.metadata.namespace)
                if api.is_pod_active(p) and fits(p)]

    # -- the control loop -------------------------------------------------------

    def sync(self, key: str):
        ns, name = key.split("/", 1)
        hpa = self.store.get("horizontalpodautoscalers", ns, name)
        if hpa is None:
            return

        plural, target, mapping = self._get_target(hpa)
        if target is None or mapping is None:
            return
        pods = self._selected_pods(target, mapping)
        current = scaleapi.get_spec_replicas(target, mapping[0])
        desired, utilization = self._desired_replicas(hpa, pods, current)
        before = (hpa.status.current_replicas,
                  hpa.status.current_cpu_utilization_percentage,
                  hpa.status.desired_replicas)
        hpa.status.current_replicas = current
        hpa.status.current_cpu_utilization_percentage = utilization
        scaled = False
        if desired is not None and desired != current \
                and self._scale_allowed(hpa, desired > current):
            scaleapi.set_spec_replicas(target, mapping[0], desired)
            self.store.update(plural, target)
            hpa.status.desired_replicas = desired
            hpa.status.last_scale_time = self.clock()
            scaled = True
        else:
            hpa.status.desired_replicas = current
        after = (hpa.status.current_replicas,
                 hpa.status.current_cpu_utilization_percentage,
                 hpa.status.desired_replicas)
        # update only on a real change: an unconditional write would
        # self-enqueue via the HPA informer and spin the workqueue
        if scaled or after != before:
            self.store.update("horizontalpodautoscalers", hpa)

    def _desired_replicas(self, hpa, pods, current):
        """replica_calculator.go:59 GetResourceReplicas: request-weighted
        utilization over pods with metrics. Pods without a sample are
        rebalanced conservatively (replica_calculator.go:338): counted at
        0 usage when the measured ratio says scale UP, and at 100% of
        request when it says scale DOWN — if that flips the direction,
        no scale. The [min, max] clamp applies UNCONDITIONALLY
        (horizontal.go normalizeDesiredReplicas): even an on-target or
        metrics-less HPA enforces its bounds."""
        def clamp(n):
            return max(hpa.spec.min_replicas, min(hpa.spec.max_replicas, n))

        total_request = 0
        total_usage = 0
        missing_request = 0
        sampled = 0
        eligible = 0  # pods with a CPU request: the replica multiplier
        # counts only these — a request-less pod can't contribute to
        # utilization, so extrapolating the ratio over it over-scales
        for p in pods:
            request = sum(c.resources.requests.get(res.CPU, 0)
                          for c in p.spec.containers)
            if request <= 0:
                continue
            eligible += 1
            usage = self.metrics_fn(p)
            if usage is None:
                missing_request += request
                continue
            total_request += request
            total_usage += usage
            sampled += 1
        if sampled == 0 or total_request == 0:
            bounded = clamp(current)
            return (None, None) if bounded == current else (bounded, None)
        utilization = int(round(100.0 * total_usage / total_request))
        target = max(1, hpa.spec.target_cpu_utilization_percentage)
        ratio = utilization / target
        if abs(ratio - 1.0) <= TOLERANCE:
            desired = clamp(current)
            return ((None, utilization) if desired == current
                    else (desired, utilization))
        if missing_request > 0:
            if ratio > 1.0:
                usage2, request2 = total_usage, total_request + missing_request
            else:
                usage2 = total_usage + missing_request
                request2 = total_request + missing_request
            ratio2 = (100.0 * usage2 / request2) / target
            if (ratio2 > 1.0) != (ratio > 1.0) \
                    or abs(ratio2 - 1.0) <= TOLERANCE:
                desired = clamp(current)
                return ((None, utilization) if desired == current
                        else (desired, utilization))
            ratio = ratio2
        desired = clamp(math.ceil(ratio * max(eligible, 1)))
        return (None, utilization) if desired == current \
            else (desired, utilization)

    def _scale_allowed(self, hpa, up: bool) -> bool:
        last = hpa.status.last_scale_time
        if last is None:
            return True
        window = UPSCALE_FORBIDDEN_WINDOW if up else DOWNSCALE_FORBIDDEN_WINDOW
        return self.clock() - last >= window
