"""Pod garbage collection.

Reference: pkg/controller/podgc/gc_controller.go (gc:75 —
gcTerminated: keep at most `terminated_threshold` Succeeded/Failed pods;
gcOrphaned: delete pods bound to nodes that no longer exist;
gcUnscheduledTerminating: terminating pods never scheduled).
"""

from __future__ import annotations

from .base import Controller


class PodGCController(Controller):
    name = "podgc"

    def __init__(self, store, terminated_threshold: int = 100):
        super().__init__(store)
        self.terminated_threshold = terminated_threshold

    def sync(self, key: str):
        self.gc()

    def gc(self) -> int:
        deleted = 0
        pods = self.store.list("pods")
        node_names = {n.metadata.name for n in self.store.list("nodes")}
        # terminated beyond threshold, oldest (lowest rv) first
        terminated = sorted(
            (p for p in pods if p.status.phase in ("Succeeded", "Failed")),
            key=lambda p: p.metadata.resource_version)
        excess = len(terminated) - self.terminated_threshold
        for p in terminated[:max(0, excess)]:
            deleted += self._delete(p)
        for p in pods:
            if p.spec.node_name and p.spec.node_name not in node_names:
                deleted += self._delete(p)  # orphaned by node deletion
            elif p.metadata.deletion_timestamp is not None and \
                    not p.spec.node_name:
                deleted += self._delete(p)  # terminating, never scheduled
        return deleted

    def _delete(self, pod) -> int:
        try:
            self.store.delete("pods", pod.metadata.namespace,
                              pod.metadata.name)
            return 1
        except KeyError:
            return 0
