"""ReplicaSet / ReplicationController reconciliation.

Reference: pkg/controller/replicaset/replica_set.go (syncReplicaSet:562
manageReplicas:459) and pkg/controller/replication/ (same logic over the
RC shape). Diff desired vs. actual matching pods: create missing with
owner refs, delete surplus preferring not-ready/pending victims
(controller_utils.go ActivePods sort), then update status.
"""

from __future__ import annotations

import itertools
from typing import List, Optional

from ..api import labels as lbl
from ..api import types as api
from ..runtime.store import Conflict
from .base import (Controller, is_pod_active, is_pod_ready,
                   make_pod_from_template, pod_owned_by)

_suffix = itertools.count(1)


def _victim_order(pod: api.Pod):
    """Deletion preference: pending before running, not-ready before ready
    (controller_utils.go ActivePods Less)."""
    return (pod.status.phase == "Running",  # False sorts first
            is_pod_ready(pod))


class _WorkloadSyncer(Controller):
    """Shared RS/RC sync over an adapter (kind, selector_fn)."""

    kind = "replicasets"
    owner_kind = "ReplicaSet"

    def __init__(self, store):
        super().__init__(store)
        self.informer(self.kind)
        # pod events enqueue the owning workload (replica_set.go addPod)
        self.pod_informer = self.informer(
            "pods",
            on_add=self._pod_event, on_update=lambda o, n: self._pod_event(n),
            on_delete=self._pod_event)

    def _pod_event(self, pod: api.Pod):
        for ref in pod.metadata.owner_references:
            if ref.controller and ref.kind == self.owner_kind:
                self.queue.add(f"{pod.metadata.namespace}/{ref.name}")

    def _selector(self, obj) -> Optional[lbl.Selector]:
        raise NotImplementedError

    def _template(self, obj) -> Optional[api.PodTemplateSpec]:
        return obj.spec.template

    def _replicas(self, obj) -> int:
        return obj.spec.replicas

    def _matching_pods(self, obj) -> List[api.Pod]:
        sel = self._selector(obj)
        out = []
        for pod in self.store.list("pods", obj.metadata.namespace):
            if not is_pod_active(pod):
                continue
            owned = pod_owned_by(pod, self.owner_kind, obj.metadata.name,
                                 obj.metadata.uid)
            if owned or (sel is not None and not pod.metadata.owner_references
                         and sel.matches(pod.metadata.labels or {})):
                out.append(pod)
        return out

    def sync(self, key: str):
        ns, name = key.split("/", 1)
        obj = self.store.get(self.kind, ns, name)
        if obj is None:
            return  # deleted; pods are cleaned by the garbage collector
        pods = self._matching_pods(obj)
        want = self._replicas(obj)
        diff = want - len(pods)
        if diff > 0:
            template = self._template(obj)
            for _ in range(diff):
                pod = make_pod_from_template(
                    template, self.owner_kind, obj,
                    f"{name}-{next(_suffix):05d}")
                try:
                    self.store.create("pods", pod)
                except Conflict:
                    pass
        elif diff < 0:
            victims = sorted(pods, key=_victim_order)[:-diff]
            for pod in victims:
                try:
                    self.store.delete("pods", pod.metadata.namespace,
                                      pod.metadata.name)
                except KeyError:
                    pass
        self._update_status(obj, pods if diff <= 0 else
                            self._matching_pods(obj))

    def _update_status(self, obj, pods: List[api.Pod]):
        ready = sum(1 for p in pods if is_pod_ready(p))
        st = obj.status
        if (st.replicas, st.ready_replicas) == (len(pods), ready):
            return
        st.replicas = len(pods)
        st.ready_replicas = ready
        if hasattr(st, "available_replicas"):
            st.available_replicas = ready
        try:
            self.store.update(self.kind, obj)
        except (Conflict, KeyError):
            raise  # retry via rate-limited requeue


class ReplicaSetController(_WorkloadSyncer):
    name = "replicaset"
    kind = "replicasets"
    owner_kind = "ReplicaSet"

    def _selector(self, obj):
        return obj.spec.selector.to_selector() if obj.spec.selector else None


class ReplicationControllerController(_WorkloadSyncer):
    name = "replicationcontroller"
    kind = "replicationcontrollers"
    owner_kind = "ReplicationController"

    def _selector(self, obj):
        if obj.spec.selector:
            return lbl.Selector.from_set(obj.spec.selector)
        return None
