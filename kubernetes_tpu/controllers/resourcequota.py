"""ResourceQuota accounting.

Reference: pkg/controller/resourcequota/resource_quota_controller.go
(syncResourceQuota:253 — recompute status.used via the evaluators in
pkg/quota/evaluator/core; admission enforces against status).
"""

from __future__ import annotations

from ..api import resources as res
from ..api import types as api
from ..runtime.store import Conflict
from .base import Controller, is_pod_active


class ResourceQuotaController(Controller):
    name = "resourcequota"

    def __init__(self, store):
        super().__init__(store)
        self.informer("resourcequotas")
        self.informer("pods", enqueue_fn=self._pod_event)

    def _pod_event(self, pod):
        for q in self.store.list("resourcequotas", pod.metadata.namespace):
            self.enqueue(q)

    def sync(self, key: str):
        ns, name = key.split("/", 1)
        quota = self.store.get("resourcequotas", ns, name)
        if quota is None:
            return
        pods = [p for p in self.store.list("pods", ns) if is_pod_active(p)]
        used = {"pods": len(pods)}
        cpu = mem = 0
        for p in pods:
            req = api.get_resource_request(p)
            cpu += req.get(res.CPU, 0)
            mem += req.get(res.MEMORY, 0)
        used["requests.cpu"] = cpu
        used["requests.memory"] = mem
        used["services"] = len(self.store.list("services", ns))
        used["persistentvolumeclaims"] = len(
            self.store.list("persistentvolumeclaims", ns))
        # only track what hard constrains (quota core evaluator Matches)
        used = {k: v for k, v in used.items()
                if k in quota.spec.hard or
                (k == "requests.cpu" and "cpu" in quota.spec.hard) or
                (k == "requests.memory" and "memory" in quota.spec.hard)}
        if quota.status.used == used and quota.status.hard == quota.spec.hard:
            return
        quota.status.hard = dict(quota.spec.hard)
        quota.status.used = used
        try:
            self.store.update("resourcequotas", quota)
        except (Conflict, KeyError):
            pass
