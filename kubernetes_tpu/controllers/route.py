"""Route controller: program the cloud pod-network route table.

Reference: pkg/controller/route/route_controller.go:103 reconcile —
every node with a podCIDR gets a cloud route (dest=podCIDR →
target=node); routes whose node or CIDR no longer matches are deleted;
once a node's route exists its NetworkUnavailable condition is cleared
(:186 updateNetworkingCondition) so the scheduler's node predicates
admit it.
"""

from __future__ import annotations

from ..api import types as api
from ..cloud.provider import CloudProvider, Route
from .base import Controller


def set_node_condition(node: api.Node, ctype: str, status: str,
                       reason: str = "") -> bool:
    """Upsert one status condition; True if anything changed."""
    for cond in node.status.conditions:
        if cond.type == ctype:
            if cond.status == status:
                return False
            cond.status = status
            cond.reason = reason
            return True
    node.status.conditions.append(api.NodeCondition(ctype, status, reason))
    return True


class RouteController(Controller):
    name = "route"

    def __init__(self, store, cloud: CloudProvider, cluster_name: str = "tpu"):
        super().__init__(store)
        routes = cloud.routes()
        if routes is None:
            raise ValueError("cloud provider does not support routes")
        self.routes = routes
        self.cluster_name = cluster_name
        # any node event re-runs the whole reconcile: the route table is
        # global state, per-key sync would race against deletions
        self.informer("nodes", enqueue_fn=lambda *_: self.enqueue("all/all"))
        self.enqueue("all/all")

    def resync(self):
        self.enqueue("all/all")

    def sync(self, key: str):
        self.reconcile()

    def reconcile(self):
        nodes = self.store.list("nodes")
        want = {(n.name, n.spec.pod_cidr) for n in nodes if n.spec.pod_cidr}
        have = {(r.target_node, r.dest_cidr): r
                for r in self.routes.list_routes(self.cluster_name)}
        # routed reflects what the CLOUD actually holds after this pass,
        # not what we intended: a failed create must leave its node
        # NetworkUnavailable=True so the scheduler's node predicates keep
        # pods off it (ref updateNetworkingCondition on the create error
        # path, route_controller.go:186)
        routed = {t for t, c in want if (t, c) in have}
        errors = 0
        # sorted: create/delete order must not follow set hash order —
        # a mid-pass failure would otherwise leave a different prefix of
        # routes materialized run-to-run
        for target, cidr in sorted(want - set(have)):
            try:
                self.routes.create_route(
                    self.cluster_name, f"{target}-{cidr}",
                    Route(name=f"{target}-{cidr}", target_node=target,
                          dest_cidr=cidr))
                routed.add(target)
            except Exception:
                errors += 1
        for stale in sorted(set(have) - want):
            try:
                self.routes.delete_route(self.cluster_name, have[stale])
            except Exception:
                errors += 1
        for node in nodes:
            if not node.spec.pod_cidr:
                continue  # ipam hasn't run; ref skips such nodes too
            reachable = node.name in routed
            changed = set_node_condition(
                node, api.NODE_NETWORK_UNAVAILABLE,
                api.COND_FALSE if reachable else api.COND_TRUE,
                reason="RouteCreated" if reachable else "NoRouteCreated")
            if changed:
                self.store.update("nodes", node)
        if errors:
            raise RuntimeError(f"{errors} route operation(s) failed")
