"""Service LB controller: keep cloud load balancers in sync with Services.

Reference: pkg/controller/service/service_controller.go — watches
Services and Nodes; for every type=LoadBalancer service it calls the
cloud's EnsureLoadBalancer with the current ready-node set and writes
the returned ingress into status.loadBalancer (:306 syncLoadBalancer);
when the type changes away or the service is deleted it tears the LB
down (:263); node-set changes fan out UpdateLoadBalancer to all LB
services (:640 nodeSyncLoop).
"""

from __future__ import annotations

import threading
from typing import Dict, List

from ..api import types as api
from ..cloud.provider import CloudProvider
from .base import Controller


def _lb_ready_nodes(nodes: List[api.Node]) -> List[api.Node]:
    """service_controller.go:615 getNodeConditionPredicate: schedulable,
    Ready nodes back the LB."""
    out = []
    for n in nodes:
        if n.spec.unschedulable:
            continue
        ready = any(c.type == api.NODE_READY and c.status == api.COND_TRUE
                    for c in n.status.conditions)
        if ready:
            out.append(n)
    return out


class ServiceLBController(Controller):
    name = "service-lb"

    def __init__(self, store, cloud: CloudProvider, cluster_name: str = "tpu"):
        super().__init__(store)
        lb = cloud.load_balancer()
        if lb is None:
            raise ValueError("cloud provider does not support load balancers")
        self.lb = lb
        self.cluster_name = cluster_name
        self._mu = threading.Lock()
        # services whose LB we ensured, by key — needed to tear down after
        # the object is gone (the ref keeps this in its serviceCache).
        # Seeded from persisted status so a restarted/failed-over instance
        # still tears down LBs it didn't create itself; a service deleted
        # while no instance was running is only reclaimed by a finalizer,
        # which the v1.11-era reference doesn't use either.
        self._ensured: Dict[str, api.Service] = {
            f"{s.metadata.namespace}/{s.metadata.name}": s
            for s in store.list("services")
            if s.status.load_balancer.ingress}
        self._last_nodes: List[str] = []
        self.informer("services",
                      on_add=self.enqueue,
                      on_update=lambda o, n: self.enqueue(n),
                      on_delete=self.enqueue)
        self.informer("nodes", enqueue_fn=lambda *_: self._node_sync())

    def _node_sync(self):
        """Node churn: if the ready-node set changed, re-enqueue every LB
        service (nodeSyncLoop)."""
        names = sorted(n.name for n in
                       _lb_ready_nodes(self.store.list("nodes")))
        with self._mu:
            if names == self._last_nodes:
                return
            self._last_nodes = names
            keys = list(self._ensured)
        for key in keys:
            self.enqueue(key)

    def resync(self):
        for svc in self.store.list("services"):
            self.enqueue(svc)

    def sync(self, key: str):
        ns, name = key.split("/", 1)
        svc = self.store.get("services", ns, name)
        wants_lb = svc is not None and svc.spec.type == "LoadBalancer"
        with self._mu:
            had = key in self._ensured
            cached = self._ensured.get(key)
        if not wants_lb:
            if had:
                # deleted or type changed away: tear down (:263)
                self.lb.ensure_load_balancer_deleted(
                    self.cluster_name, cached if svc is None else svc)
                with self._mu:
                    self._ensured.pop(key, None)
                if svc is not None and svc.status.load_balancer.ingress:
                    svc.status.load_balancer = api.LoadBalancerStatus()
                    self.store.update("services", svc)
            return
        nodes = _lb_ready_nodes(self.store.list("nodes"))
        status = self.lb.ensure_load_balancer(self.cluster_name, svc, nodes)
        with self._mu:
            self._ensured[key] = svc
        ips = [(i.ip, i.hostname) for i in status.ingress]
        cur = [(i.ip, i.hostname) for i in svc.status.load_balancer.ingress]
        if ips != cur:
            svc.status.load_balancer = status
            self.store.update("services", svc)
