"""ServiceAccount controller: ensure 'default' SA per namespace.

Reference: pkg/controller/serviceaccount/serviceaccounts_controller.go
(syncNamespace:178 — every active namespace gets the default
ServiceAccount; the tokens controller pairs each SA with a token
Secret, tokens_controller.go).
"""

from __future__ import annotations

from ..api import types as api
from ..runtime.store import Conflict
from .base import Controller


class ServiceAccountController(Controller):
    name = "serviceaccount"

    def __init__(self, store):
        super().__init__(store)
        self._ca = None
        self.informer("namespaces",
                      enqueue_fn=lambda o: self.queue.add(
                          f"ns:{o.metadata.name}"))
        # tokens controller half (tokens_controller.go): every SA gets a
        # signed token Secret, including user-created SAs
        self.informer("serviceaccounts",
                      enqueue_fn=lambda o: self.queue.add(
                          f"sa:{o.metadata.namespace}/{o.metadata.name}"))

    def _sa_key(self) -> str:
        if self._ca is None:
            from ..server import pki

            self._ca = pki.ensure_cluster_ca(self.store)
        return self._ca.sa_signing_key

    def _ensure_token(self, sa: api.ServiceAccount):
        """Mint a real SA JWT (pkg/serviceaccount/jwt.go) bound to the
        SA's uid and the Secret's name; the apiserver's authenticator
        verifies both liveness conditions."""
        from ..server import serviceaccount as sat

        secret_name = f"{sa.metadata.name}-token"
        ns = sa.metadata.namespace
        existing = self.store.get("secrets", ns, secret_name)
        if existing is not None:
            # a recreated SA (new uid) invalidates the old token — the
            # authenticator rejects the uid mismatch — so the Secret
            # must be re-minted, not kept (tokens_controller.go deletes
            # secrets of deleted SAs; this covers the recreate race too)
            claims = sat.claims_of(existing.data.get("token", ""))
            if claims is None or claims.get(
                    "kubernetes.io/serviceaccount/service-account.uid") \
                    != sa.metadata.uid:
                try:
                    self.store.delete("secrets", ns, secret_name)
                except KeyError:
                    pass
                existing = None
        if existing is None:
            token = sat.mint(self._sa_key(), ns, sa.metadata.name,
                             sa.metadata.uid, secret_name)
            try:
                self.store.create("secrets", api.Secret(
                    metadata=api.ObjectMeta(name=secret_name, namespace=ns),
                    type="kubernetes.io/service-account-token",
                    data={"token": token}))
            except Conflict:
                pass
        if secret_name not in sa.secrets:
            sa.secrets.append(secret_name)
            try:
                self.store.update("serviceaccounts", sa)
            except Conflict:
                pass

    def sync(self, key: str):
        kind, _, rest = key.partition(":")
        if kind == "sa":
            ns, _, name = rest.partition("/")
            sa = self.store.get("serviceaccounts", ns, name)
            if sa is not None:
                self._ensure_token(sa)
            return
        # namespace event: ensure the default SA exists
        name = rest or key  # bare keys tolerated (tests enqueue names)
        ns_obj = (self.store.get("namespaces", "", name)
                  or self.store.get("namespaces", "default", name))
        if ns_obj is None or ns_obj.status.phase != "Active":
            return
        if self.store.get("serviceaccounts", name, "default") is not None:
            return
        sa = api.ServiceAccount(
            metadata=api.ObjectMeta(name="default", namespace=name))
        try:
            self.store.create("serviceaccounts", sa)
        except Conflict:
            return
        self._ensure_token(sa)
