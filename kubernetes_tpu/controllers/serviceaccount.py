"""ServiceAccount controller: ensure 'default' SA per namespace.

Reference: pkg/controller/serviceaccount/serviceaccounts_controller.go
(syncNamespace:178 — every active namespace gets the default
ServiceAccount; the tokens controller pairs each SA with a token
Secret, tokens_controller.go).
"""

from __future__ import annotations

from ..api import types as api
from ..runtime.store import Conflict
from .base import Controller


class ServiceAccountController(Controller):
    name = "serviceaccount"

    def __init__(self, store):
        super().__init__(store)
        self.informer("namespaces")

    def sync(self, key: str):
        name = key.split("/")[-1]
        ns_obj = (self.store.get("namespaces", "", name)
                  or self.store.get("namespaces", "default", name))
        if ns_obj is None or ns_obj.status.phase != "Active":
            return
        if self.store.get("serviceaccounts", name, "default") is not None:
            return
        token = api.Secret(
            metadata=api.ObjectMeta(name="default-token", namespace=name),
            type="kubernetes.io/service-account-token",
            data={"token": f"sa-{name}-default"})
        sa = api.ServiceAccount(
            metadata=api.ObjectMeta(name="default", namespace=name),
            secrets=[token.metadata.name])
        try:
            self.store.create("secrets", token)
        except Conflict:
            pass
        try:
            self.store.create("serviceaccounts", sa)
        except Conflict:
            pass
