"""StatefulSet: ordered pods with stable identities.

Reference: pkg/controller/statefulset/stateful_set_control.go
(UpdateStatefulSet: ordinal-ordered create/scale; OrderedReady waits for
predecessor readiness before creating the next replica; Parallel does
not). Pod names are <set>-<ordinal> — the stable network identity.
"""

from __future__ import annotations

from typing import Dict, Optional

from ..api import types as api
from ..runtime.store import Conflict
from .base import (Controller, is_pod_active, is_pod_ready,
                   make_pod_from_template)


class StatefulSetController(Controller):
    name = "statefulset"

    def __init__(self, store):
        super().__init__(store)
        self.informer("statefulsets")
        self.informer("pods",
                      on_add=self._pod_event,
                      on_update=lambda o, n: self._pod_event(n),
                      on_delete=self._pod_event)

    def _pod_event(self, pod):
        for ref in pod.metadata.owner_references:
            if ref.controller and ref.kind == "StatefulSet":
                self.queue.add(f"{pod.metadata.namespace}/{ref.name}")

    def _pods_by_ordinal(self, ss) -> Dict[int, api.Pod]:
        out: Dict[int, api.Pod] = {}
        prefix = ss.metadata.name + "-"
        for pod in self.store.list("pods", ss.metadata.namespace):
            if not pod.metadata.name.startswith(prefix):
                continue
            if not any(r.controller and r.kind == "StatefulSet"
                       and r.name == ss.metadata.name
                       for r in pod.metadata.owner_references):
                continue
            suffix = pod.metadata.name[len(prefix):]
            if suffix.isdigit():
                out[int(suffix)] = pod
        return out

    def sync(self, key: str):
        ns, name = key.split("/", 1)
        ss = self.store.get("statefulsets", ns, name)
        if ss is None:
            return
        pods = self._pods_by_ordinal(ss)
        want = ss.spec.replicas
        ordered = ss.spec.pod_management_policy != "Parallel"
        # create missing ordinals in order; under OrderedReady stop at the
        # first not-ready predecessor (stateful_set_control.go:433)
        for i in range(want):
            pod = pods.get(i)
            if pod is None:
                new = make_pod_from_template(ss.spec.template, "StatefulSet",
                                             ss, f"{name}-{i}")
                new.metadata.labels["statefulset.kubernetes.io/pod-name"] = \
                    new.metadata.name
                self._ensure_claims(ss, new, i)
                try:
                    self.store.create("pods", new)
                except Conflict:
                    pass
                if ordered:
                    raise RuntimeError(f"waiting for ordinal {i}")
            elif ordered and not (is_pod_active(pod) and is_pod_ready(pod)):
                # predecessor not ready: halt rollout here
                break
        # scale down from the top ordinal (reverse order)
        for i in sorted((o for o in pods if o >= want), reverse=True):
            pod = pods[i]
            try:
                self.store.delete("pods", pod.metadata.namespace,
                                  pod.metadata.name)
            except KeyError:
                pass
            if ordered:
                raise RuntimeError(f"scaling down ordinal {i}")
        self._update_status(ss, pods)

    def _ensure_claims(self, ss, pod: api.Pod, ordinal: int):
        """volumeClaimTemplates (stateful_set_utils.go updateStorage +
        stateful_pod_control.go createPersistentVolumeClaims): mint the
        per-ordinal PVC `<template>-<set>-<ordinal>` if absent and mount
        it into the pod under the template's name. Claims survive
        scale-down/delete (the reference never reaps them)."""
        import copy

        for tmpl in ss.spec.volume_claim_templates:
            claim_name = f"{tmpl.metadata.name}-{ss.metadata.name}-{ordinal}"
            if self.store.get("persistentvolumeclaims",
                              ss.metadata.namespace, claim_name) is None:
                pvc = api.PersistentVolumeClaim(
                    metadata=api.ObjectMeta(
                        name=claim_name,
                        namespace=ss.metadata.namespace,
                        labels=dict(pod.metadata.labels or {})),
                    spec=copy.deepcopy(tmpl.spec))
                try:
                    self.store.create("persistentvolumeclaims", pvc)
                except Conflict:
                    pass
            # updateStorage semantics: a template volume of the SAME
            # name is REPLACED by the claim mount (not duplicated —
            # duplicate names fail pod validation)
            pod.spec.volumes = [v for v in pod.spec.volumes
                                if v.name != tmpl.metadata.name] + [
                api.Volume(name=tmpl.metadata.name, pvc_name=claim_name)]

    def _update_status(self, ss, pods):
        live = [p for p in pods.values() if is_pod_active(p)]
        ready = sum(1 for p in live if is_pod_ready(p))
        st = ss.status
        if (st.replicas, st.ready_replicas) == (len(live), ready):
            return
        st.replicas = len(live)
        st.ready_replicas = ready
        st.current_replicas = len(live)
        try:
            self.store.update("statefulsets", ss)
        except (Conflict, KeyError):
            pass
