"""StatefulSet: ordered pods with stable identities.

Reference: pkg/controller/statefulset/stateful_set_control.go
(UpdateStatefulSet: ordinal-ordered create/scale; OrderedReady waits for
predecessor readiness before creating the next replica; Parallel does
not). Pod names are <set>-<ordinal> — the stable network identity.
"""

from __future__ import annotations

from typing import Dict, Optional

from ..api import types as api
from ..runtime.store import Conflict
from .base import (Controller, is_pod_active, is_pod_ready,
                   make_pod_from_template)
from .history import REV_LABEL


class StatefulSetController(Controller):
    name = "statefulset"

    def __init__(self, store):
        super().__init__(store)
        self.informer("statefulsets")
        self.informer("pods",
                      on_add=self._pod_event,
                      on_update=lambda o, n: self._pod_event(n),
                      on_delete=self._pod_event)

    def _pod_event(self, pod):
        for ref in pod.metadata.owner_references:
            if ref.controller and ref.kind == "StatefulSet":
                self.queue.add(f"{pod.metadata.namespace}/{ref.name}")

    def _pods_by_ordinal(self, ss) -> Dict[int, api.Pod]:
        out: Dict[int, api.Pod] = {}
        prefix = ss.metadata.name + "-"
        for pod in self.store.list("pods", ss.metadata.namespace):
            if not pod.metadata.name.startswith(prefix):
                continue
            if not any(r.controller and r.kind == "StatefulSet"
                       and r.name == ss.metadata.name
                       for r in pod.metadata.owner_references):
                continue
            suffix = pod.metadata.name[len(prefix):]
            if suffix.isdigit():
                out[int(suffix)] = pod
        return out

    def sync(self, key: str):
        from . import history

        ns, name = key.split("/", 1)
        ss = self.store.get("statefulsets", ns, name)
        if ss is None:
            return
        # getStatefulSetRevisions (stateful_set_control.go:315): the
        # update revision snapshots the current template; currentRevision
        # trails it until the rollout completes
        revisions = history.list_revisions(self.store, ss, "StatefulSet")
        rev = history.sync_revision(self.store, ss, "StatefulSet",
                                    ss.spec.template, revisions=revisions)
        rev_hash = (rev.metadata.labels or {}).get(
            REV_LABEL, "")
        pods = self._pods_by_ordinal(ss)
        want = ss.spec.replicas
        ordered = ss.spec.pod_management_policy != "Parallel"
        # create missing ordinals in order; under OrderedReady stop at the
        # first not-ready predecessor (stateful_set_control.go:433)
        for i in range(want):
            pod = pods.get(i)
            if pod is None:
                # newVersionedStatefulSetPod: ordinals below the
                # RollingUpdate partition are rebuilt from the CURRENT
                # revision's snapshot, not the update template — a
                # restart must not advance a pinned ordinal
                template, use_hash = self._template_for_ordinal(
                    ss, i, rev_hash)
                new = make_pod_from_template(template, "StatefulSet",
                                             ss, f"{name}-{i}")
                new.metadata.labels["statefulset.kubernetes.io/pod-name"] = \
                    new.metadata.name
                new.metadata.labels[REV_LABEL] = use_hash
                self._ensure_claims(ss, new, i)
                try:
                    self.store.create("pods", new)
                except Conflict:
                    pass
                if ordered:
                    raise RuntimeError(f"waiting for ordinal {i}")
            elif ordered and not (is_pod_active(pod) and is_pod_ready(pod)):
                # predecessor not ready: halt rollout here
                break
        # scale down from the top ordinal (reverse order)
        for i in sorted((o for o in pods if o >= want), reverse=True):
            pod = pods[i]
            try:
                self.store.delete("pods", pod.metadata.namespace,
                                  pod.metadata.name)
            except KeyError:
                pass
            if ordered:
                raise RuntimeError(f"scaling down ordinal {i}")
        self._rolling_update(ss, pods, want, rev_hash)
        self._update_status(ss, pods, rev, rev_hash)
        history.truncate_history(
            self.store, ss, "StatefulSet",
            live_hashes={(p.metadata.labels or {}).get(
                REV_LABEL) for p in pods.values()
                if is_pod_active(p)},
            keep_names={rev.metadata.name, ss.status.current_revision},
            revisions=revisions)

    def _template_for_ordinal(self, ss, ordinal, rev_hash):
        """Template + revision hash a missing ordinal should be rebuilt
        from: the current revision's snapshot below the RollingUpdate
        partition, the update template otherwise
        (stateful_set_control.go newVersionedStatefulSetPod)."""
        from ..api import scheme
        from . import history

        strat = ss.spec.update_strategy
        cur_name = ss.status.current_revision
        if (strat.type != "RollingUpdate" or ordinal >= strat.partition
                or not cur_name):
            return ss.spec.template, rev_hash
        cur = self.store.get("controllerrevisions", ss.metadata.namespace,
                             cur_name)
        if cur is None:
            return ss.spec.template, rev_hash
        template = scheme.decode(api.PodTemplateSpec,
                                 cur.data["spec"]["template"])
        return template, (cur.metadata.labels or {}).get(
            history.REV_LABEL, rev_hash)

    def _rolling_update(self, ss, pods, want, rev_hash):
        """updateStatefulSet (stateful_set_control.go:520): under
        RollingUpdate, delete the highest-ordinal pod whose revision is
        stale, never touching ordinals below spec.updateStrategy.
        partition, and only one at a time while every replica is
        healthy (monotonic rollout). The create pass above recreates the
        ordinal at the update revision. OnDelete leaves stale pods for
        the operator."""
        if ss.spec.update_strategy.type != "RollingUpdate":
            return
        live = [p for o, p in pods.items() if o < want and is_pod_active(p)]
        if len(live) < want or not all(is_pod_ready(p) for p in live):
            return  # unhealthy replica: halt the rollout, don't compound
        partition = ss.spec.update_strategy.partition
        for i in sorted((o for o in pods if o < want), reverse=True):
            if i < partition:
                break
            p = pods[i]
            if (p.metadata.labels or {}).get(
                    REV_LABEL) != rev_hash:
                try:
                    self.store.delete("pods", p.metadata.namespace,
                                      p.metadata.name)
                except KeyError:
                    pass
                del pods[i]
                raise RuntimeError(f"rolling ordinal {i} to new revision")

    def _ensure_claims(self, ss, pod: api.Pod, ordinal: int):
        """volumeClaimTemplates (stateful_set_utils.go updateStorage +
        stateful_pod_control.go createPersistentVolumeClaims): mint the
        per-ordinal PVC `<template>-<set>-<ordinal>` if absent and mount
        it into the pod under the template's name. Claims survive
        scale-down/delete (the reference never reaps them)."""
        import copy

        for tmpl in ss.spec.volume_claim_templates:
            claim_name = f"{tmpl.metadata.name}-{ss.metadata.name}-{ordinal}"
            if self.store.get("persistentvolumeclaims",
                              ss.metadata.namespace, claim_name) is None:
                pvc = api.PersistentVolumeClaim(
                    metadata=api.ObjectMeta(
                        name=claim_name,
                        namespace=ss.metadata.namespace,
                        labels=dict(pod.metadata.labels or {})),
                    spec=copy.deepcopy(tmpl.spec))
                try:
                    self.store.create("persistentvolumeclaims", pvc)
                except Conflict:
                    pass
            # updateStorage semantics: a template volume of the SAME
            # name is REPLACED by the claim mount (not duplicated —
            # duplicate names fail pod validation)
            pod.spec.volumes = [v for v in pod.spec.volumes
                                if v.name != tmpl.metadata.name] + [
                api.Volume(name=tmpl.metadata.name, pvc_name=claim_name)]

    def _update_status(self, ss, pods, rev=None, rev_hash=""):
        live = [p for p in pods.values() if is_pod_active(p)]
        ready = sum(1 for p in live if is_pod_ready(p))
        updated = sum(1 for p in live if (p.metadata.labels or {}).get(
            REV_LABEL) == rev_hash)
        st = ss.status
        update_rev = rev.metadata.name if rev else st.update_revision
        # completeRollingUpdate: currentRevision catches up once every
        # replica serves the update revision AND is Ready — a rolled-
        # but-broken replica keeps the rollout in progress
        # (stateful_set_control.go completeRollingUpdate)
        current_rev = st.current_revision or update_rev
        if updated == len(live) == ready == ss.spec.replicas:
            current_rev = update_rev
        # currentReplicas counts pods at the CURRENT revision (apps/v1
        # semantics) — it shrinks as the rolling update advances
        cur_hash = rev_hash
        if current_rev != update_rev:
            cur_obj = self.store.get("controllerrevisions",
                                     ss.metadata.namespace, current_rev)
            cur_hash = (cur_obj.metadata.labels or {}).get(
                REV_LABEL, "") if cur_obj else ""
        current = sum(1 for p in live if (p.metadata.labels or {}).get(
            REV_LABEL) == cur_hash)
        gen = ss.metadata.generation
        if (st.replicas, st.ready_replicas, st.updated_replicas,
                st.current_replicas, st.current_revision,
                st.update_revision, st.observed_generation) == \
                (len(live), ready, updated, current, current_rev,
                 update_rev, gen):
            return
        st.replicas = len(live)
        st.ready_replicas = ready
        st.current_replicas = current
        st.updated_replicas = updated
        st.observed_generation = gen
        st.current_revision = current_rev
        st.update_revision = update_rev
        try:
            self.store.update("statefulsets", ss)
        except (Conflict, KeyError):
            pass
