"""Storage-object-in-use protection: PVC and PV protection controllers.

Reference: pkg/controller/volume/pvcprotection/pvc_protection_
controller.go and .../pvprotection/ (the StorageObjectInUseProtection
feature): every claim carries the kubernetes.io/pvc-protection
finalizer, so deleting a claim a running pod still mounts only MARKS it
(Terminating) — the data cannot be yanked out from under the pod. The
controller removes the finalizer once no pod uses the claim, which
completes the deletion. PVs get the same treatment while bound to a
claim.

Deletion gating itself is API machinery (metadata.finalizers +
deletion_timestamp, server/apiserver.py delete/update paths); these
controllers only add/remove the finalizers. In-process components that
call store.delete directly bypass finalizers by design (raw storage
access, like etcdctl would).
"""

from __future__ import annotations

from ..api import types as api
from .base import Controller

PVC_PROTECTION_FINALIZER = "kubernetes.io/pvc-protection"
PV_PROTECTION_FINALIZER = "kubernetes.io/pv-protection"


def release_finalizer(store, plural: str, obj, finalizer: str) -> None:
    """Remove one finalizer; when it was the LAST one on an object
    marked for deletion, complete the removal. The completion cannot
    live in ObjectStore.update generically: namespaces legitimately
    update with deletion_timestamp set and empty metadata.finalizers
    during their own spec.finalizers-driven termination flow, so a
    store-level rule would delete them mid-flight. The apiserver's
    update path applies the same rule for API writers."""
    obj.metadata.finalizers = [f for f in (obj.metadata.finalizers or [])
                               if f != finalizer]
    store.update(plural, obj)
    if obj.metadata.deletion_timestamp is not None \
            and not obj.metadata.finalizers:
        try:
            store.delete(plural, obj.metadata.namespace,
                         obj.metadata.name)
        except KeyError:
            pass  # an API-path writer already completed it


def _pods_using_pvc(store, namespace: str, claim_name: str):
    for pod in store.list("pods", namespace):
        if not api.is_pod_active(pod):
            continue
        for v in pod.spec.volumes:
            if v.pvc_name == claim_name:
                yield pod
                break


class PVCProtectionController(Controller):
    name = "pvcprotection"

    def __init__(self, store):
        super().__init__(store)
        self.informer("persistentvolumeclaims")
        # pod deletions can unblock a Terminating claim
        self.informer("pods", enqueue_fn=self._enqueue_pod_claims)

    def _enqueue_pod_claims(self, pod, new=None):
        pod = new if new is not None else pod
        for v in pod.spec.volumes:
            if v.pvc_name:
                self.enqueue(f"{pod.metadata.namespace}/{v.pvc_name}")

    def sync(self, key: str):
        ns, name = key.split("/", 1)
        pvc = self.store.get("persistentvolumeclaims", ns, name)
        if pvc is None:
            return
        fins = list(pvc.metadata.finalizers or [])
        if pvc.metadata.deletion_timestamp is None:
            if PVC_PROTECTION_FINALIZER not in fins:
                pvc.metadata.finalizers = fins + [PVC_PROTECTION_FINALIZER]
                self.store.update("persistentvolumeclaims", pvc)
            return
        # Terminating: release once no active pod mounts it
        if PVC_PROTECTION_FINALIZER not in fins:
            return
        if any(True for _ in _pods_using_pvc(self.store, ns, name)):
            return  # still in use: stay Terminating
        release_finalizer(self.store, "persistentvolumeclaims", pvc,
                          PVC_PROTECTION_FINALIZER)

    def resync(self):
        for pvc in self.store.list("persistentvolumeclaims"):
            self.enqueue(pvc)


class PVProtectionController(Controller):
    name = "pvprotection"

    def __init__(self, store):
        super().__init__(store)
        self.informer("persistentvolumes")
        self.informer("persistentvolumeclaims",
                      enqueue_fn=self._enqueue_bound_pv)

    def _enqueue_bound_pv(self, pvc, new=None):
        pvc = new if new is not None else pvc
        if pvc.spec.volume_name:
            self.enqueue(f"/{pvc.spec.volume_name}")

    def _bound(self, pv_name: str) -> bool:
        return any(pvc.spec.volume_name == pv_name
                   for pvc in self.store.list("persistentvolumeclaims"))

    def sync(self, key: str):
        _, name = key.split("/", 1)
        pv = (self.store.get("persistentvolumes", "", name)
              or self.store.get("persistentvolumes", "default", name))
        if pv is None:
            return
        fins = list(pv.metadata.finalizers or [])
        if pv.metadata.deletion_timestamp is None:
            if PV_PROTECTION_FINALIZER not in fins:
                pv.metadata.finalizers = fins + [PV_PROTECTION_FINALIZER]
                self.store.update("persistentvolumes", pv)
            return
        if PV_PROTECTION_FINALIZER not in fins:
            return
        if self._bound(name):
            return  # a claim still references it
        release_finalizer(self.store, "persistentvolumes", pv,
                          PV_PROTECTION_FINALIZER)

    def resync(self):
        for pv in self.store.list("persistentvolumes"):
            self.enqueue(pv)
