"""TTL controller: scale node object-cache TTL hints with cluster size.

Reference: pkg/controller/ttl/ttl_controller.go — kubelets cache
secrets/configmaps with a TTL the control plane announces via the
`node.alpha.kubernetes.io/ttl` annotation; bigger clusters get longer
TTLs to shed apiserver load (ttl_controller.go:50 ttlBoundaries).
"""

from __future__ import annotations

from .base import Controller

TTL_ANNOTATION = "node.alpha.kubernetes.io/ttl"

# (cluster size threshold, ttl seconds) — ttl_controller.go:58
TTL_BOUNDARIES = [
    (100, 0),
    (500, 15),
    (1000, 30),
    (5000, 60),
    (float("inf"), 300),
]


def ttl_for_size(n_nodes: int) -> int:
    for bound, ttl in TTL_BOUNDARIES:
        if n_nodes <= bound:
            return ttl
    return 300


class TTLController(Controller):
    name = "ttl"

    def __init__(self, store):
        super().__init__(store)
        self.informer("nodes")

    def resync(self):
        for node in self.store.list("nodes"):
            self.enqueue(node)

    def sync(self, key: str):
        _, name = key.split("/", 1)
        node = (self.store.get("nodes", "default", name)
                or self.store.get("nodes", "", name))
        if node is None:
            return
        want = str(ttl_for_size(self.store.count("nodes")))
        ann = node.metadata.annotations
        if ann.get(TTL_ANNOTATION) == want:
            return
        ann[TTL_ANNOTATION] = want
        self.store.update("nodes", node)
