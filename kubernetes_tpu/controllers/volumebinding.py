"""PersistentVolume binder: match PVCs to PVs.

Reference: pkg/controller/volume/persistentvolume/pv_controller.go
(syncUnboundClaim:320 — find the smallest PV satisfying class +
capacity, bind by setting claim.spec.volumeName and marking the PV
bound). The scheduler's volume predicates consume the binding
(NoVolumeZoneConflict / CheckVolumeBinding, plugins/volumes.py).
"""

from __future__ import annotations

from ..api import resources as res
from ..api import types as api
from ..runtime.store import Conflict
from .base import Controller


class PersistentVolumeController(Controller):
    name = "persistentvolume"

    def __init__(self, store):
        super().__init__(store)
        self.informer("persistentvolumeclaims")
        self.informer("persistentvolumes",
                      enqueue_fn=lambda o: self._all_claims())

    def _all_claims(self):
        for pvc in self.store.list("persistentvolumeclaims"):
            self.enqueue(pvc)

    def sync(self, key: str):
        ns, name = key.split("/", 1)
        pvc = self.store.get("persistentvolumeclaims", ns, name)
        if pvc is None or pvc.spec.volume_name:
            return
        if pvc.spec.volume_binding_mode == "WaitForFirstConsumer":
            # owned by the scheduler's VolumeBinder: bound at pod commit,
            # when the node (and thus PV topology) is known — binding here
            # would both race that writer and ignore node affinity
            return
        want = pvc.spec.requests.get(res.MEMORY, 0) or \
            pvc.spec.requests.get("storage", 0)
        bound_pvs = {c.spec.volume_name
                     for c in self.store.list("persistentvolumeclaims")
                     if c.spec.volume_name}
        best = None
        for pv in self.store.list("persistentvolumes"):
            if pv.metadata.name in bound_pvs:
                continue
            if pv.spec.storage_class_name != pvc.spec.storage_class_name:
                continue
            cap = pv.spec.capacity.get("storage",
                                       pv.spec.capacity.get(res.MEMORY, 0))
            if cap < want:
                continue
            if best is None or cap < best[0]:
                best = (cap, pv)
        if best is None:
            raise RuntimeError(f"no PV available for claim {key}")
        pvc.spec.volume_name = best[1].metadata.name
        try:
            self.store.update("persistentvolumeclaims", pvc)
        except (Conflict, KeyError):
            pvc.spec.volume_name = ""
            raise
