"""Node agent — the reference's pkg/kubelet at capability level.

Kubelet runs one node: it admits pods the scheduler binds to it
(re-running the scheduler's GeneralPredicates, pkg/kubelet/lifecycle/
predicate.go), drives them to Running against a container runtime
(fake/hollow by default — pkg/kubemark/hollow_kubelet.go), relays
runtime lifecycle events (PLEG), probes containers, evicts under
resource pressure, and heartbeats node status for the nodelifecycle
controller's failure detection.
"""

from .runtime import ContainerState, FakeRuntime
from .kubelet import Kubelet
