"""Kubelet checkpointing: device and CPU assignments survive restarts.

Reference: pkg/kubelet/checkpointmanager (checksummed JSON files under
the kubelet root), used by the device manager
(cm/devicemanager/manager.go kubelet_internal_checkpoint) and the CPU
manager (cm/cpumanager/state/state_checkpoint.go). A kubelet that
restarts must come back with the SAME device IDs and CPU pins for
running pods — re-allocating would hand a live workload's accelerator
to someone else.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
from typing import Optional


class CorruptCheckpoint(Exception):
    pass


class CheckpointManager:
    """Checksummed JSON state files, written atomically (tmp + rename,
    like checkpointmanager's safe-file write)."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def _path(self, name: str) -> str:
        return os.path.join(self.directory, name)

    def save(self, name: str, state: dict):
        payload = json.dumps(state, sort_keys=True)
        doc = {"data": payload,
               "checksum": hashlib.sha256(payload.encode()).hexdigest()}
        fd, tmp = tempfile.mkstemp(dir=self.directory, prefix=f".{name}-")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, self._path(name))
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def load(self, name: str) -> Optional[dict]:
        """None when absent; CorruptCheckpoint when the checksum fails
        (the reference surfaces this so the caller can decide to start
        fresh rather than trust bad state)."""
        try:
            with open(self._path(name)) as f:
                doc = json.load(f)
        except FileNotFoundError:
            return None
        except (OSError, json.JSONDecodeError):
            raise CorruptCheckpoint(name)
        payload = doc.get("data", "")
        if hashlib.sha256(payload.encode()).hexdigest() != \
                doc.get("checksum"):
            raise CorruptCheckpoint(name)
        return json.loads(payload)

    def remove(self, name: str):
        try:
            os.unlink(self._path(name))
        except FileNotFoundError:
            pass
